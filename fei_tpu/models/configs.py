"""Model configurations for the fei_tpu engine.

Covers the model families named in BASELINE.json configs: Llama-3 (8B/70B),
CodeLlama-34B, Mixtral-8x7B MoE — plus tiny presets for hermetic CPU tests.
All are decoder-only transformers with RMSNorm, RoPE, SwiGLU MLPs, and
grouped-query attention; Mixtral swaps the dense MLP for a top-2 router over
8 experts.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class ModelConfig:
    name: str = "tiny"
    vocab_size: int = 512
    hidden_size: int = 64
    intermediate_size: int = 128
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: int | None = None  # defaults to hidden_size // num_heads
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    # MoE (Mixtral): num_experts == 0 means dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Qwen2-family attention: q/k/v projections carry biases (o does not)
    attn_bias: bool = False
    # HF Llama-family `attention_bias: true` additionally biases o_proj
    o_bias: bool = False
    # Gemma family: RMSNorm multiplies by (1 + w) (weights stored
    # zero-centered), embeddings scale by sqrt(hidden_size), GeGLU MLP
    norm_offset: bool = False
    embed_scale: bool = False
    hidden_act: str = "silu"  # "silu" (SwiGLU) | "gelu" (GeGLU, tanh approx)
    # Mistral-v0.1-style sliding-window attention: each query attends to at
    # most the last `sliding_window` positions (None = full causal)
    sliding_window: int | None = None
    # Phi family: LayerNorm (with bias) instead of RMSNorm, ONE shared norm
    # feeding attention AND MLP in parallel (x + attn(ln x) + mlp(ln x)),
    # partial rotary (first `rotary_dim` dims of each head), non-gated
    # fc1/act/fc2 MLP with biases, and a biased LM head
    norm_kind: str = "rms"  # "rms" | "layernorm"
    parallel_block: bool = False
    rotary_dim: int = 0  # 0 = rotate the full head_dim
    mlp_gated: bool = True
    mlp_bias: bool = False
    lm_head_bias: bool = False
    # tokenizer/bos/eos defaults (overridden by a real tokenizer when loaded)
    bos_token_id: int = 1
    eos_token_id: int = 2

    @property
    def head_dim_(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @property
    def rope_dim_(self) -> int:
        """Head dims that rotate: `rotary_dim` when partial (Phi), else all."""
        return self.rotary_dim or self.head_dim_

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def num_params(self) -> int:
        """Approximate parameter count (for memory planning)."""
        h, i, v, L = self.hidden_size, self.intermediate_size, self.vocab_size, self.num_layers
        d = self.head_dim_
        attn = h * (self.num_heads * d) + 2 * h * (self.num_kv_heads * d) + (self.num_heads * d) * h
        if self.attn_bias:
            attn += self.num_heads * d + 2 * self.num_kv_heads * d
        if self.o_bias:
            attn += h
        if self.is_moe:
            mlp = self.num_experts * 3 * h * i + h * self.num_experts
        else:
            mlp = (3 if self.mlp_gated else 2) * h * i
        norms = (1 if self.parallel_block else 2) * h
        embed = v * h * (1 if self.tie_embeddings else 2)
        return L * (attn + mlp + norms) + embed + h

    def num_active_params(self) -> int:
        """Parameters that participate in MATMULS for one decoded token —
        the right basis for FLOPs/token (≈ 2·active): only the top-k
        experts run, the embedding lookup is a gather (not a matmul), and
        the LM head is one h×v matmul whether tied or not."""
        h, i, v, L = (
            self.hidden_size, self.intermediate_size, self.vocab_size,
            self.num_layers,
        )
        d = self.head_dim_
        attn = (
            h * (self.num_heads * d)
            + 2 * h * (self.num_kv_heads * d)
            + (self.num_heads * d) * h
        )
        if self.is_moe:
            mlp = self.num_experts_per_tok * 3 * h * i + h * self.num_experts
        else:
            mlp = (3 if self.mlp_gated else 2) * h * i
        return L * (attn + mlp) + v * h


# Shapes follow the published architecture cards for each family. These are
# architectural constants (layer/head/dim counts), not code from the reference
# repo — the reference has no model code at all (SURVEY.md §2: LLM calls go out
# over HTTP via LiteLLM, fei/core/assistant.py:524-530).
MODEL_CONFIGS: dict[str, ModelConfig] = {
    # hermetic-test presets
    "tiny": ModelConfig(),
    "debug": ModelConfig(
        name="debug", vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=4, num_heads=8, num_kv_heads=4, max_seq_len=2048,
    ),
    "tiny-moe": ModelConfig(
        name="tiny-moe", vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, num_experts=4,
        num_experts_per_tok=2, max_seq_len=2048,
    ),
    # benchmark-scale presets (weights random-init unless a checkpoint is given)
    "llama3-1b": ModelConfig(
        name="llama3-1b", vocab_size=128256, hidden_size=2048,
        intermediate_size=8192, num_layers=16, num_heads=32, num_kv_heads=8,
        rope_theta=500000.0, max_seq_len=8192, tie_embeddings=True,
        bos_token_id=128000, eos_token_id=128009,
    ),
    "llama3-3b": ModelConfig(
        name="llama3-3b", vocab_size=128256, hidden_size=3072,
        intermediate_size=8192, num_layers=28, num_heads=24, num_kv_heads=8,
        rope_theta=500000.0, max_seq_len=8192, tie_embeddings=True,
        bos_token_id=128000, eos_token_id=128009,
    ),
    "llama3-8b": ModelConfig(
        name="llama3-8b", vocab_size=128256, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        rope_theta=500000.0, max_seq_len=8192,
        bos_token_id=128000, eos_token_id=128009,
    ),
    "llama3-70b": ModelConfig(
        name="llama3-70b", vocab_size=128256, hidden_size=8192,
        intermediate_size=28672, num_layers=80, num_heads=64, num_kv_heads=8,
        rope_theta=500000.0, max_seq_len=8192,
        bos_token_id=128000, eos_token_id=128009,
    ),
    "codellama-34b": ModelConfig(
        name="codellama-34b", vocab_size=32000, hidden_size=8192,
        intermediate_size=22016, num_layers=48, num_heads=64, num_kv_heads=8,
        rope_theta=1000000.0, max_seq_len=16384,
    ),
    # bench-scale MoE: Mixtral routing shape (8 experts, top-2) at a size a
    # single 16 GB v5e chip holds in bf16 (~1.9B params), for measuring the
    # routed-vs-dense expert paths on real hardware
    "moe-2b": ModelConfig(
        name="moe-2b", vocab_size=32000, hidden_size=2048,
        intermediate_size=2048, num_layers=16, num_heads=16, num_kv_heads=8,
        rope_theta=1000000.0, max_seq_len=8192,
        num_experts=8, num_experts_per_tok=2,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        rope_theta=1000000.0, max_seq_len=32768,
        num_experts=8, num_experts_per_tok=2,
    ),
    # Qwen2 family (qkv biases; otherwise the same pre-norm GQA block)
    "tiny-bias": ModelConfig(
        name="tiny-bias", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        max_seq_len=2048, attn_bias=True,
    ),
    # Mistral family (Llama block + sliding-window attention)
    "tiny-swa": ModelConfig(
        name="tiny-swa", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        max_seq_len=2048, sliding_window=8, rope_theta=10000.0,
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        rope_theta=10000.0, max_seq_len=32768, sliding_window=4096,
    ),
    # Phi family (parallel attn+MLP block, LayerNorm, partial rotary).
    # phi-2 is the architecture the reference's node-onboarding doc mocks at
    # "67 tokens/s" on a hypothetical RTX 3080
    # (/root/reference/docs/HOW_FEI_NETWORK_WORKS.md:60-75) — here it runs
    # for real, in-tree, on TPU (2.7B bf16 = 5.6 GB: fits one v5e chip).
    "tiny-phi": ModelConfig(
        name="tiny-phi", vocab_size=512, hidden_size=64,
        intermediate_size=256, num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_len=2048, rope_theta=10000.0, norm_kind="layernorm",
        parallel_block=True, rotary_dim=8, mlp_gated=False, mlp_bias=True,
        attn_bias=True, o_bias=True, lm_head_bias=True, hidden_act="gelu",
    ),
    "phi-2": ModelConfig(
        name="phi-2", vocab_size=51200, hidden_size=2560,
        intermediate_size=10240, num_layers=32, num_heads=32, num_kv_heads=32,
        max_seq_len=2048, rope_theta=10000.0, norm_kind="layernorm",
        parallel_block=True, rotary_dim=32, mlp_gated=False, mlp_bias=True,
        attn_bias=True, o_bias=True, lm_head_bias=True, hidden_act="gelu",
        bos_token_id=50256, eos_token_id=50256,
    ),
    # Gemma family (norm offset, GeGLU, scaled embeddings, head_dim 256,
    # always-tied embeddings, rope 10000)
    "tiny-gemma": ModelConfig(
        name="tiny-gemma", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=32, max_seq_len=2048, tie_embeddings=True,
        norm_offset=True, embed_scale=True, hidden_act="gelu",
        rope_theta=10000.0,
    ),
    "gemma-2b": ModelConfig(
        name="gemma-2b", vocab_size=256000, hidden_size=2048,
        intermediate_size=16384, num_layers=18, num_heads=8, num_kv_heads=1,
        head_dim=256, rope_theta=10000.0, max_seq_len=8192,
        tie_embeddings=True, norm_offset=True, embed_scale=True,
        hidden_act="gelu", bos_token_id=2, eos_token_id=1,
    ),
    "gemma-7b": ModelConfig(
        name="gemma-7b", vocab_size=256000, hidden_size=3072,
        intermediate_size=24576, num_layers=28, num_heads=16, num_kv_heads=16,
        head_dim=256, rope_theta=10000.0, max_seq_len=8192,
        tie_embeddings=True, norm_offset=True, embed_scale=True,
        hidden_act="gelu", bos_token_id=2, eos_token_id=1,
    ),
    "qwen2-0.5b": ModelConfig(
        name="qwen2-0.5b", vocab_size=151936, hidden_size=896,
        intermediate_size=4864, num_layers=24, num_heads=14, num_kv_heads=2,
        rope_theta=1000000.0, max_seq_len=32768, tie_embeddings=True,
        attn_bias=True, bos_token_id=151643, eos_token_id=151645,
    ),
    "qwen2-7b": ModelConfig(
        name="qwen2-7b", vocab_size=152064, hidden_size=3584,
        intermediate_size=18944, num_layers=28, num_heads=28, num_kv_heads=4,
        rope_theta=1000000.0, max_seq_len=32768,
        attn_bias=True, bos_token_id=151643, eos_token_id=151645,
    ),
}


def get_model_config(name: str, **overrides) -> ModelConfig:
    if name not in MODEL_CONFIGS:
        raise KeyError(f"unknown model config {name!r}; known: {sorted(MODEL_CONFIGS)}")
    cfg = MODEL_CONFIGS[name]
    return replace(cfg, **overrides) if overrides else cfg
