from fei_tpu.models.configs import ModelConfig, get_model_config, MODEL_CONFIGS

__all__ = ["ModelConfig", "get_model_config", "MODEL_CONFIGS"]
