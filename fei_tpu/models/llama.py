"""Llama-family decoder (covers Llama-3, CodeLlama, Mixtral via config).

Design is TPU-first, not a port (the reference has no model code — its LLM
calls leave the process over HTTP, fei/core/assistant.py:524-530):

- Parameters are a plain pytree with layers **stacked on a leading axis** so
  the forward pass is one ``lax.scan`` over layers: compile time is O(1) in
  depth (matters at 80 layers for 70B) and XLA pipelines the per-layer HBM
  weight streams.
- Pure functions of (params, config, inputs) — jit/pjit/shard_map compose
  from the outside; sharding is applied to the pytree by
  fei_tpu.parallel.sharding, not baked in here.
- Static shapes everywhere: the KV cache is a fixed [L, B, S, K, D] buffer
  with a per-sequence valid length; prefill and decode are the same code path
  with different T.
"""

from __future__ import annotations

import os
from types import SimpleNamespace
from typing import NamedTuple

import jax
import jax.numpy as jnp

from fei_tpu.models.configs import ModelConfig
from fei_tpu.ops.attention import attention
from fei_tpu.ops.moe import moe_mlp, moe_mlp_routed
from fei_tpu.ops.quant import (
    _int4_ok,
    mm,
    quantize as _quantize_w,
    quantize4 as _quantize4_w,
)
from fei_tpu.ops.rmsnorm import rms_norm
from fei_tpu.ops.rope import apply_rope, compute_rope_freqs

class KVCache(NamedTuple):
    """Static-shape KV cache. k/v: [L, B, S, K, D]; length: [B] valid prefix."""

    k: jnp.ndarray
    v: jnp.ndarray
    length: jnp.ndarray

    @classmethod
    def create(cls, cfg: ModelConfig, batch: int, max_seq: int, dtype=jnp.bfloat16):
        shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_dim_)
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            length=jnp.zeros((batch,), dtype=jnp.int32),
        )


_INIT_BUILDERS: dict = {}  # (repr(cfg), str(dtype), quantize) -> jitted builder


def init_params(
    cfg: ModelConfig,
    key: jax.Array,
    dtype=jnp.bfloat16,
    quantize: str | None = None,
    int4_exclude: frozenset = frozenset(),
) -> dict:
    """Random-init parameter pytree (layers stacked on axis 0).

    The whole tree is built inside ONE jitted program: each eager dispatch
    pays a compile + RPC round-trip (over the tunneled axon TPU backend
    these run ~30-60 s apiece, so per-tensor init of an 8B took >20 min),
    while one compiled program materializes every tensor on device in
    seconds. ``quantize="int8"`` quantizes each big linear inline, and an
    ``optimization_barrier`` chain threads each tensor's key through the
    previous tensor so XLA cannot materialize several bf16 sources at once
    — peak memory stays near one source tensor plus the finished outputs
    (an 8B random-init would otherwise risk ~16 GB of simultaneous bf16
    before the quantize consumers run). Builders are cached per
    (config, dtype, quantize) so repeated inits hit the compile cache."""
    # FEI_TPU_INT4_LM_HEAD changes _int4_ok's trace-time answer, so it must
    # key the builder cache or a flip mid-process would reuse a stale layout
    cache_key = (
        repr(cfg), str(dtype), quantize, tuple(sorted(int4_exclude)),
        os.environ.get("FEI_TPU_INT4_LM_HEAD"),
        os.environ.get("FEI_TPU_QUANT_EMBED"),
    )
    built = _INIT_BUILDERS.get(cache_key)
    if built is not None:
        return built(key)

    h, d = cfg.hidden_size, cfg.head_dim_
    H, K, I, L = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size, cfg.num_layers

    def _build(key):
        keys = iter(jax.random.split(key, 16))
        prev = None  # barrier chain: orders tensor materialization

        def init(k, shape, fan_in, quant=False, name=None):
            nonlocal prev
            if prev is not None:
                k, _ = jax.lax.optimization_barrier((k, prev))
            shape_only = SimpleNamespace(shape=shape)  # _int4_ok reads .shape
            use_int4 = (
                quant
                and quantize == "int4"
                and name not in int4_exclude
                and _int4_ok(name, shape_only, cfg.is_moe)
            )
            if use_int4 and len(shape) >= 3:
                # int4's reduce(amax)-then-pack chain defeats the fusion
                # that keeps int8 init memory-flat: XLA materializes the
                # full stacked fp32 source (w_down at 8B is 7.5 GB) before
                # the packed bytes exist. Building per layer under lax.map
                # bounds the fp32 transient to ONE layer's weights.
                def one_layer(kl):
                    wl = (
                        jax.random.normal(kl, shape[1:], dtype=jnp.float32)
                        * (fan_in ** -0.5)
                    ).astype(dtype)
                    return _quantize4_w(wl)

                w = jax.lax.map(one_layer, jax.random.split(k, shape[0]))
            else:
                w = (
                    jax.random.normal(k, shape, dtype=jnp.float32)
                    * (fan_in ** -0.5)
                ).astype(dtype)
                if quant and quantize:
                    w = _quantize4_w(w) if use_int4 else _quantize_w(w)
            prev = w.q if hasattr(w, "q") else (w.p if hasattr(w, "p") else w)
            return w

        # Gemma-family norms multiply by (1 + w): identity init is zeros
        ninit = jnp.zeros if cfg.norm_offset else jnp.ones
        layers: dict = {
            "attn_norm": ninit((L, h), dtype=dtype),
            "wq": init(next(keys), (L, h, H * d), h, quant=True, name="wq"),
            "wk": init(next(keys), (L, h, K * d), h, quant=True, name="wk"),
            "wv": init(next(keys), (L, h, K * d), h, quant=True, name="wv"),
            "wo": init(next(keys), (L, H * d, h), H * d, quant=True, name="wo"),
        }
        if not cfg.parallel_block:  # Phi's ONE shared norm feeds attn + mlp
            layers["mlp_norm"] = ninit((L, h), dtype=dtype)
        if cfg.norm_kind == "layernorm":  # Phi: LayerNorm carries biases
            layers["attn_norm_b"] = jnp.zeros((L, h), dtype=dtype)
            if not cfg.parallel_block:
                layers["mlp_norm_b"] = jnp.zeros((L, h), dtype=dtype)
        if cfg.attn_bias:  # Qwen2-style qkv biases
            layers.update(
                bq=jnp.zeros((L, H * d), dtype=dtype),
                bk=jnp.zeros((L, K * d), dtype=dtype),
                bv=jnp.zeros((L, K * d), dtype=dtype),
            )
        if cfg.o_bias:  # HF Llama attention_bias=true also biases o_proj
            layers["bo"] = jnp.zeros((L, h), dtype=dtype)
        if cfg.is_moe:
            E = cfg.num_experts
            layers.update(
                router=init(next(keys), (L, h, E), h),
                w_gate=init(next(keys), (L, E, h, I), h, quant=True, name="w_gate"),
                w_up=init(next(keys), (L, E, h, I), h, quant=True, name="w_up"),
                w_down=init(next(keys), (L, E, I, h), I, quant=True, name="w_down"),
            )
        elif cfg.mlp_gated:
            layers.update(
                w_gate=init(next(keys), (L, h, I), h, quant=True, name="w_gate"),
                w_up=init(next(keys), (L, h, I), h, quant=True, name="w_up"),
                w_down=init(next(keys), (L, I, h), I, quant=True, name="w_down"),
            )
        else:
            # Phi fc1/fc2 reuse the w_gate/w_down leaves (same column/row
            # sharding + quantization rules); no w_up
            layers.update(
                w_gate=init(next(keys), (L, h, I), h, quant=True, name="w_gate"),
                w_down=init(next(keys), (L, I, h), I, quant=True, name="w_down"),
            )
            if cfg.mlp_bias:
                layers.update(
                    b_gate=jnp.zeros((L, I), dtype=dtype),
                    b_down=jnp.zeros((L, h), dtype=dtype),
                )
        # FEI_TPU_QUANT_EMBED=1 (with any quantize mode): int8 embed table
        # with per-row scales — halves embed HBM, and for tie_embeddings
        # models halves the LM-head stream (ops.quant.quantize_embed)
        quant_embed = bool(quantize) and os.environ.get("FEI_TPU_QUANT_EMBED") == "1"
        embed = init(next(keys), (cfg.vocab_size, h), h)
        if quant_embed:
            from fei_tpu.ops.quant import quantize_embed

            embed = quantize_embed(embed)
        params = {
            "embed": embed,
            "layers": layers,
            "final_norm": ninit((h,), dtype=dtype),
        }
        if cfg.norm_kind == "layernorm":
            params["final_norm_b"] = jnp.zeros((h,), dtype=dtype)
        if not cfg.tie_embeddings:
            params["lm_head"] = init(
                next(keys), (h, cfg.vocab_size), h, quant=True, name="lm_head"
            )
            if cfg.lm_head_bias:
                params["lm_head_b"] = jnp.zeros((cfg.vocab_size,), dtype=dtype)
        return params

    built = jax.jit(_build)
    _INIT_BUILDERS[cache_key] = built
    return built(key)


_FLASH_MIN_T = 64  # below this, kernel launch overhead beats the fusion win
_ROUTED_MIN_TOKENS = 16  # below this, sort/gather overhead beats the k/E win


def _moe(cfg: ModelConfig, y, lp, allow_routed: bool, moe_mesh=None):
    """Pick the MoE formulation at trace time.

    With an ``ep`` mesh (``moe_mesh``), tokens route to the devices owning
    their experts via parallel.expert.moe_mlp_ep_routed (dispatch/combine
    + two all_to_alls over ICI, TP-composed). Single chip:
    FEI_TPU_ROUTED_MOE=1 forces token routing (ragged_dot grouped GEMM),
    =0 forces the dense oracle everywhere; default "auto" routes when the
    caller allows it and the token count amortizes the sort. Expert FLOPs
    drop to k/E of dense when routed."""
    mode = os.environ.get("FEI_TPU_ROUTED_MOE", "auto")
    # int8 expert weights pass through as QTensor: every MoE formulation
    # streams the int8 and applies scales to einsum/ragged_dot results
    # (ops.quant.scale_expert_out/scale_rows) — no dense bf16 copy
    args = (
        y, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
        cfg.num_experts_per_tok,
    )
    if (
        mode != "0"
        and moe_mesh is not None
        and moe_mesh.shape.get("ep", 1) > 1
    ):
        from fei_tpu.parallel.expert import moe_mlp_ep_routed

        tp = "tp" if moe_mesh.shape.get("tp", 1) > 1 else None
        # FEI_TPU_EP_CAPACITY: "dropless" (exact, worst-case buffers — no
        # FLOPs saving, use for parity tests) or a capacity factor (default
        # 2.0: expert compute = 2k/E of dense, skewed tokens beyond 2x the
        # balanced load are dropped — standard GShard serving trade)
        cap = os.environ.get("FEI_TPU_EP_CAPACITY", "2.0")
        if cap == "dropless":
            return moe_mlp_ep_routed(*args, moe_mesh, dropless=True, tp_axis=tp)
        return moe_mlp_ep_routed(
            *args, moe_mesh, capacity_factor=float(cap), tp_axis=tp
        )
    N = y.shape[0] * y.shape[1]
    use_routed = mode == "1" or (
        mode == "auto" and allow_routed and N >= _ROUTED_MIN_TOKENS
    )
    fn = moe_mlp_routed if use_routed else moe_mlp
    return fn(*args)


def _norm(x, w, cfg: ModelConfig, b=None):
    """RMSNorm (Llama families) or LayerNorm with bias (Phi family,
    cfg.norm_kind == "layernorm"; ``b`` is the bias leaf or None)."""
    if cfg.norm_kind == "layernorm":
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + cfg.rms_norm_eps)
        y = y * w.astype(jnp.float32)
        if b is not None:
            y = y + b.astype(jnp.float32)
        return y.astype(x.dtype)
    return rms_norm(x, w, cfg.rms_norm_eps, offset=cfg.norm_offset)


def _rope(x, cos, sin, positions, rope_dim: int):
    """apply_rope over the first ``rope_dim`` head dims (Phi partial
    rotary; the HF convention rotates the leading slice split-half and
    passes the rest through), or the whole head when rope_dim covers it.
    ``cos``/``sin`` tables are sized for ``rope_dim``."""
    if rope_dim and rope_dim != x.shape[-1]:
        return jnp.concatenate(
            [apply_rope(x[..., :rope_dim], cos, sin, positions),
             x[..., rope_dim:]],
            axis=-1,
        )
    return apply_rope(x, cos, sin, positions)


def _mlp_dense(cfg: ModelConfig, y, lp, kernel_mesh=None):
    """The dense (non-MoE) MLP: gated SwiGLU/GeGLU (w_gate*w_up -> w_down)
    for the Llama families, fc1 -> act -> fc2 with biases for Phi
    (cfg.mlp_gated=False; fc1/fc2 reuse the w_gate/w_down leaves so the
    column/row sharding and quantization rules apply unchanged)."""
    if not cfg.mlp_gated:
        a = _mm_k(y, lp["w_gate"], kernel_mesh)
        if "b_gate" in lp:
            a = a + lp["b_gate"]
        act = _mlp_act(cfg, a.astype(jnp.float32)).astype(y.dtype)
        out = mm(act, lp["w_down"])
        if "b_down" in lp:
            out = out + lp["b_down"]
        return out
    act = _mlp_act(
        cfg, _mm_k(y, lp["w_gate"], kernel_mesh).astype(jnp.float32)
    ).astype(y.dtype)
    return mm(act * _mm_k(y, lp["w_up"], kernel_mesh), lp["w_down"])


def _mlp_act(cfg: ModelConfig, gate):
    """Gated-MLP activation on the fp32-cast gate: SwiGLU (silu) for the
    Llama/Qwen/Mixtral families, GeGLU (tanh-approx gelu — HF Gemma's
    gelu_pytorch_tanh) for Gemma."""
    if cfg.hidden_act == "gelu":
        return jax.nn.gelu(gate, approximate=True)
    return jax.nn.silu(gate)


def model_dtype(params: dict):
    """The model compute dtype, read from a leaf that is never quantized
    (the embed table may be a row-scaled QTensor whose .dtype is fp32)."""
    return params["layers"]["attn_norm"].dtype


def embed_tokens(params: dict, cfg: ModelConfig, tokens, dtype):
    """Embedding lookup (plain or row-quantized table — ops.quant
    embed_lookup); Gemma scales by sqrt(hidden_size) (in the compute
    dtype, matching HF's normalizer cast)."""
    from fei_tpu.ops.quant import embed_lookup

    x = embed_lookup(params["embed"], tokens, dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, dtype)
    return x


def _mm_k(x, w, kernel_mesh):
    """mm that routes int4 leaves through the shard_map'd kernel under a
    tp mesh. XLA auto-partitions plain dots and int8 QTensor dots, but not
    a pallas_call — a global-view QTensor4 matmul would all-gather the full
    packed weight to every device. Only out-channel-sharded weights can be
    QTensor4 on a mesh (eligibility keeps row-parallel wo/w_down int8), so
    the column-parallel shard_map contract always applies."""
    from fei_tpu.ops.quant import QTensor4

    if (
        kernel_mesh is not None
        and isinstance(w, QTensor4)
        and kernel_mesh.shape.get("tp", 1) > 1
    ):
        from fei_tpu.ops.pallas.int4_matmul import int4_mm_sharded

        return int4_mm_sharded(x, w, kernel_mesh)
    return mm(x, w)


def qkv_proj(lp, y, Hq: int, K: int, d: int, kernel_mesh=None):
    """Project y -> (q [B,T,Hq,d], k [B,T,K,d], v [B,T,K,d]), applying the
    Qwen2-style qkv biases when the layer carries them (cfg.attn_bias)."""
    B, T, _ = y.shape
    q = _mm_k(y, lp["wq"], kernel_mesh)
    k = _mm_k(y, lp["wk"], kernel_mesh)
    v = _mm_k(y, lp["wv"], kernel_mesh)
    if "bq" in lp:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    return (
        q.reshape(B, T, Hq, d), k.reshape(B, T, K, d), v.reshape(B, T, K, d)
    )


def _attend(q, k, v, kv_length, positions, window: int = 0):
    """Pick the attention path at trace time.

    FEI_TPU_FLASH=1 forces the Pallas flash kernel (interpret mode off-TPU,
    for tests), =0 forces the XLA oracle; default "auto" uses flash for
    TPU prefill-sized T. ``kv_length`` is the pre-write cache length [B];
    keys are valid below kv_length + T. The kernel has a Pallas flash
    backward (custom_vjp, recompute) so the training path uses it too.
    ``window``: sliding-window attention (cfg.sliding_window) — both paths
    mask keys at positions <= p - window.
    """
    T = q.shape[1]
    mode = os.environ.get("FEI_TPU_FLASH", "auto")
    use_flash = (
        mode == "1"
        or (mode == "auto" and T >= _FLASH_MIN_T and jax.default_backend() == "tpu")
    )
    if use_flash:
        from fei_tpu.ops.pallas import flash_attention

        return flash_attention(
            q, k, v, kv_length, kv_length + T, window=window
        )
    return attention(q, k, v, positions, kv_length + T, window=window)


def _layer(
    cfg: ModelConfig, x, lp, cache_k, cache_v, kv_length, positions, cos, sin,
    allow_routed: bool = False, moe_mesh=None, kernel_mesh=None,
):
    """One decoder block. x: [B,T,H]; cache_k/v: [B,S,K,D] (this layer's
    slice) or None for the cache-free training path.
    Returns (x_out, new_cache_k, new_cache_v)."""
    B, T, h = x.shape
    K, d = cfg.num_kv_heads, cfg.head_dim_
    Hq = cfg.num_heads

    y = _norm(x, lp["attn_norm"], cfg, b=lp.get("attn_norm_b"))
    q, k, v = qkv_proj(lp, y, Hq, K, d, kernel_mesh=kernel_mesh)
    rd = cfg.rope_dim_
    q = _rope(q, cos, sin, positions, rd)
    k = _rope(k, cos, sin, positions, rd)

    if cache_k is None:
        new_k, new_v = k, v
    else:
        # write new k/v at each sequence's current length offset (batch-ragged)
        def write(buf, new, start):
            return jax.lax.dynamic_update_slice(buf, new, (start, 0, 0))

        new_k = jax.vmap(write)(cache_k, k, kv_length)
        new_v = jax.vmap(write)(cache_v, v, kv_length)

    attn_out = _attend(
        q, new_k, new_v, kv_length, positions,
        window=cfg.sliding_window or 0,
    )
    o = mm(attn_out.reshape(B, T, Hq * d), lp["wo"])
    if "bo" in lp:  # HF Llama attention_bias=true also biases o_proj
        o = o + lp["bo"]

    if cfg.parallel_block:
        # Phi: attention and MLP both read the ONE shared norm output and
        # sum into the residual — x + attn(ln x) + mlp(ln x)
        mlp_out = (
            _moe(cfg, y, lp, allow_routed, moe_mesh) if cfg.is_moe
            else _mlp_dense(cfg, y, lp, kernel_mesh)
        )
        return x + o + mlp_out, new_k, new_v
    x = x + o

    y = _norm(x, lp["mlp_norm"], cfg, b=lp.get("mlp_norm_b"))
    if cfg.is_moe:
        mlp_out = _moe(cfg, y, lp, allow_routed, moe_mesh)
    else:
        mlp_out = _mlp_dense(cfg, y, lp, kernel_mesh)
    return x + mlp_out, new_k, new_v


def _logits(x, params, cfg: ModelConfig, kernel_mesh=None) -> jnp.ndarray:
    """LM head (quantization-aware). Tied embeddings project through the
    (possibly row-quantized) embed table — ops.quant.tied_logits applies
    the row scales to the result columns, exact since each scale is
    constant along the contraction."""
    if cfg.tie_embeddings:
        from fei_tpu.ops.quant import tied_logits

        return tied_logits(x, params["embed"])
    out = _mm_k(x, params["lm_head"], kernel_mesh).astype(jnp.float32)
    if "lm_head_b" in params:  # Phi: biased LM head
        out = out + params["lm_head_b"].astype(jnp.float32)
    return out


def forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] int32
    cache: KVCache,
    routed_moe: bool = False,
    moe_mesh=None,
    lm_head: bool = True,
    kernel_mesh=None,
) -> tuple[jnp.ndarray, KVCache]:
    """Run T tokens through the model against the cache.

    Serves prefill (T = prompt chunk) and decode (T = 1) identically.
    Returns (logits [B, T, V], updated cache with length += T).
    ``lm_head=False`` returns final-norm hidden states [B, T, H] instead of
    logits — chunked prefill only needs one position's logits, so callers
    skip the [T, V] head matmul and project the position they want.
    ``kernel_mesh``: a mesh with a tp axis routes int4 (QTensor4) linears
    through the shard_map'd kernel (see _mm_k).
    """
    B, T = tokens.shape
    positions = cache.length[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    cos, sin = compute_rope_freqs(cfg.rope_dim_, cache.k.shape[2], cfg.rope_theta)

    x = embed_tokens(params, cfg, tokens, cache.k.dtype)

    def body(carry, layer_inputs):
        x = carry
        lp, ck, cv = layer_inputs
        x, nk, nv = _layer(
            cfg, x, lp, ck, cv, cache.length, positions, cos, sin,
            allow_routed=routed_moe, moe_mesh=moe_mesh,
            kernel_mesh=kernel_mesh,
        )
        return x, (nk, nv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )

    x = _norm(x, params["final_norm"], cfg, b=params.get("final_norm_b"))
    new_cache = KVCache(k=new_k, v=new_v, length=cache.length + T)
    if not lm_head:
        return x, new_cache
    return _logits(x, params, cfg, kernel_mesh=kernel_mesh), new_cache


def forward_paged(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, 1] int32 — one decode token per sequence
    cache,  # PagedKVCache (engine/paged_cache.py)
    routed_moe: bool = False,
    moe_mesh=None,
    kernel_mesh=None,
) -> tuple[jnp.ndarray, object]:
    """Single-token decode against a paged KV cache.

    Same math as ``forward`` with T=1, but K/V land in per-sequence pages
    (write_token_kv) and attention reads through the block table with the
    Pallas ragged paged kernel. Returns (logits [B, 1, V], updated cache
    with lengths += 1).

    ``kernel_mesh``: a mesh with a tp axis — the paged kernel then runs
    under shard_map with kv heads sharded (XLA cannot auto-partition a
    pallas_call), making multi-chip paged serving real; everything else in
    the layer partitions from the param/pool shardings as usual.

    Implemented as the T=1 case of ``forward_paged_block`` so single-step
    decode and speculative verification can never diverge.
    """
    return forward_paged_block(
        params, cfg, tokens, cache,
        routed_moe=routed_moe, moe_mesh=moe_mesh, kernel_mesh=kernel_mesh,
    )


def forward_paged_block(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] int32 — T draft tokens per sequence
    cache,  # PagedKVCache
    routed_moe: bool = False,
    moe_mesh=None,
    kernel_mesh=None,
    lm_head: bool = True,
) -> tuple[jnp.ndarray, object]:
    """Multi-token paged forward for speculative VERIFICATION — and, with
    ``lm_head=False`` (returns final-norm hidden [B, T, H] instead of
    logits), the chunk body of paged-native prefill, which only projects
    one position.

    All T tokens' projections/MLP batch into single matmuls (one weight
    read for T tokens — the point of speculation on a weight-streaming-
    bound decode) and their K/V scatter into the sequence's pool pages.
    Attention uses the multi-query block kernel
    (ops.pallas.paged_attention_block): pool history is read ONCE for the
    whole block with per-row causal limits. FEI_TPU_BLOCK_ATTN=0 falls
    back to T unrolled single-query kernel calls; T=1 (plain decode)
    always takes the single-query kernel already validated under Mosaic.
    Returns (logits [B, T, V] fp32, cache with lengths += T). The CALLER
    owns rollback: only the accepted prefix's K/V is real — shrink
    ``lengths`` to mask the rest, exactly like the dense lookahead path.
    """
    from fei_tpu.engine.paged_cache import write_token_kv
    from fei_tpu.ops.pallas import paged_attention
    from fei_tpu.ops.pallas.paged_attention import (
        paged_attention_block,
        paged_attention_block_sharded,
        paged_attention_sharded,
    )

    B, T = tokens.shape
    K, d, Hq = cfg.num_kv_heads, cfg.head_dim_, cfg.num_heads
    positions = cache.lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    max_pos = cache.block_table.shape[1] * cache.page_size
    cos, sin = compute_rope_freqs(cfg.rope_dim_, max_pos, cfg.rope_theta)
    # kernel-selection policy: see the docstring
    block_kernel = T > 1 and os.environ.get("FEI_TPU_BLOCK_ATTN", "1") != "0"
    # any sharding axis (tp heads OR dp batch groups) must lift the pallas
    # kernel through shard_map — XLA cannot auto-partition a pallas_call
    sharded = kernel_mesh is not None and (
        kernel_mesh.shape.get("tp", 1) > 1
        or kernel_mesh.shape.get("dp", 1) > 1
    )
    win = cfg.sliding_window or 0

    kv_int8 = cache.k_scales is not None
    dtype = model_dtype(params) if kv_int8 else cache.k_pages.dtype
    x = embed_tokens(params, cfg, tokens, dtype)  # [B, T, h]

    def body(x, layer_inputs):
        if kv_int8:
            lp, kp, vp, ksc, vsc = layer_inputs
        else:
            lp, kp, vp = layer_inputs
            ksc = vsc = None
        y = _norm(x, lp["attn_norm"], cfg, b=lp.get("attn_norm_b"))
        q, k, v = qkv_proj(lp, y, Hq, K, d, kernel_mesh=kernel_mesh)
        q = _rope(q, cos, sin, positions, cfg.rope_dim_)
        k = _rope(k, cos, sin, positions, cfg.rope_dim_)

        # write all T positions' K/V (causality is the kernel's per-row
        # mask, so writing ahead of attending is safe)
        for i in range(T):
            written = write_token_kv(
                kp, vp, k[:, i], v[:, i], cache.block_table,
                cache.lengths + i, k_scales=ksc, v_scales=vsc,
            )
            if kv_int8:
                kp, vp, ksc, vsc = written
            else:
                kp, vp = written
        if block_kernel:
            if sharded:
                attn = paged_attention_block_sharded(
                    q, kp, vp, cache.block_table, cache.lengths,
                    kernel_mesh, axis_name="tp", k_scales=ksc, v_scales=vsc,
                    window=win,
                )
            else:
                attn = paged_attention_block(
                    q, kp, vp, cache.block_table, cache.lengths,
                    k_scales=ksc, v_scales=vsc, window=win,
                )  # [B, T, Hq, D]
        else:
            attns = []
            for i in range(T):  # per-position fallback
                if sharded:
                    a = paged_attention_sharded(
                        q[:, i], kp, vp, cache.block_table,
                        cache.lengths + i + 1, kernel_mesh, axis_name="tp",
                        k_scales=ksc, v_scales=vsc, window=win,
                    )
                else:
                    a = paged_attention(
                        q[:, i], kp, vp, cache.block_table,
                        cache.lengths + i + 1, k_scales=ksc, v_scales=vsc,
                        window=win,
                    )  # [B, Hq, D]
                attns.append(a)
            attn = jnp.stack(attns, axis=1)  # [B, T, Hq, D]
        o = mm(attn.reshape(B, T, Hq * d), lp["wo"])
        if "bo" in lp:
            o = o + lp["bo"]
        out = (kp, vp, ksc, vsc) if kv_int8 else (kp, vp)
        if cfg.parallel_block:  # Phi: x + attn(ln x) + mlp(ln x)
            mlp_out = (
                _moe(cfg, y, lp, routed_moe, moe_mesh) if cfg.is_moe
                else _mlp_dense(cfg, y, lp, kernel_mesh)
            )
            return x + o + mlp_out, out
        x = x + o

        y = _norm(x, lp["mlp_norm"], cfg, b=lp.get("mlp_norm_b"))
        if cfg.is_moe:
            mlp_out = _moe(cfg, y, lp, routed_moe, moe_mesh)
        else:
            mlp_out = _mlp_dense(cfg, y, lp, kernel_mesh)
        return x + mlp_out, out

    if kv_int8:
        xs = (
            params["layers"], cache.k_pages, cache.v_pages,
            cache.k_scales, cache.v_scales,
        )
        x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(body, x, xs)
    else:
        xs = (params["layers"], cache.k_pages, cache.v_pages)
        x, (new_k, new_v) = jax.lax.scan(body, x, xs)
        new_ks = new_vs = None

    x = _norm(x, params["final_norm"], cfg, b=params.get("final_norm_b"))
    out = _logits(x, params, cfg, kernel_mesh=kernel_mesh) if lm_head else x
    new_cache = cache._replace(
        k_pages=new_k, v_pages=new_v, lengths=cache.lengths + T,
        k_scales=new_ks, v_scales=new_vs,
    )
    return out, new_cache


def forward_paged_merged(
    params: dict,
    cfg: ModelConfig,
    chunk_toks: jnp.ndarray,  # [1, C] int32 — one prefill chunk
    chunk_row: jnp.ndarray,  # [1, max_pages] admitting slot's table row
    chunk_pos: jnp.ndarray,  # [1] int32 — chunk's absolute start position
    dec_tokens: jnp.ndarray,  # [B, 1] int32 — one decode token per slot
    cache,  # PagedKVCache under the LIVE table/lengths
    routed_moe: bool = False,
    moe_mesh=None,
    kernel_mesh=None,
    rows: int = 8,
) -> tuple[jnp.ndarray, jnp.ndarray, object]:
    """One ragged dispatch serves a prefill chunk AND a decode step.

    The legacy scheduler iteration issues two programs — the chunk body
    (``forward_paged_block`` through a one-slot view) and the decode step
    (``forward_paged``) — streaming the weights twice. Here the two run
    through ONE layer scan: per layer the chunk's [1, C] tokens and the
    decode batch's [B, 1] tokens each keep their own legacy-shaped
    projections/norms/MLP matmuls (bitwise the ops the solo programs run),
    and only the two attention invocations merge into a single ragged
    kernel call over ``B + ceil(C/rows)`` virtual rows — decode rows at
    q_len=1 against the live table, the chunk split into ``rows``-position
    groups against the admitting slot's row. Splitting is bitwise-neutral:
    each query row's online softmax walks the same pages in the same
    order, and pages beyond a row's causal limit are exact no-ops for it
    (masked scores underflow to p=0 with correction=1 once any live page
    has been seen — the property the legacy block kernel's per-row limits
    already rely on).

    Writes commute: chunk K/V lands in the admitting slot's pages (its
    LIVE row is still zeroed, so no decode row reads them), decode K/V in
    each armed slot's own pages. Returns ``(chunk_hidden [1, C, H]
    final-normed, dec_logits [B, 1, V], cache with lengths += 1)`` —
    chunk-side lengths are host-tracked (``st["pos"]``), as on the solo
    path.
    """
    from fei_tpu.engine.paged_cache import write_token_kv
    from fei_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
        ragged_paged_attention_sharded,
    )

    B, _ = dec_tokens.shape
    _, C = chunk_toks.shape
    K, d, Hq = cfg.num_kv_heads, cfg.head_dim_, cfg.num_heads
    R = rows
    nG = -(-C // R)  # chunk groups of R query positions
    Cp = nG * R
    Bv = B + nG
    chunk_positions = chunk_pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    dec_positions = cache.lengths[:, None]
    max_pos = cache.block_table.shape[1] * cache.page_size
    cos, sin = compute_rope_freqs(cfg.rope_dim_, max_pos, cfg.rope_theta)
    sharded = kernel_mesh is not None and (
        kernel_mesh.shape.get("tp", 1) > 1
        or kernel_mesh.shape.get("dp", 1) > 1
    )
    win = cfg.sliding_window or 0

    # per-virtual-row metadata: decode rows then chunk groups
    btv = jnp.concatenate(
        [cache.block_table, jnp.tile(chunk_row, (nG, 1))], axis=0
    )
    group_starts = chunk_pos + jnp.arange(nG, dtype=jnp.int32) * R
    limits = jnp.concatenate([cache.lengths + 1, group_starts + 1])
    q_lens = jnp.concatenate([
        jnp.ones((B,), dtype=jnp.int32),
        jnp.clip(C - jnp.arange(nG, dtype=jnp.int32) * R, 0, R),
    ])
    # mode=1 rows re-run the online update at single-query shapes so the
    # decode side rounds exactly like the standalone qt=1 program
    modes = jnp.concatenate([
        jnp.ones((B,), dtype=jnp.int32),
        jnp.zeros((nG,), dtype=jnp.int32),
    ])

    kv_int8 = cache.k_scales is not None
    dtype = model_dtype(params) if kv_int8 else cache.k_pages.dtype
    xc = embed_tokens(params, cfg, chunk_toks, dtype)  # [1, C, h]
    xd = embed_tokens(params, cfg, dec_tokens, dtype)  # [B, 1, h]

    def body(carry, layer_inputs):
        xc, xd = carry
        if kv_int8:
            lp, kp, vp, ksc, vsc = layer_inputs
        else:
            lp, kp, vp = layer_inputs
            ksc = vsc = None
        yc = _norm(xc, lp["attn_norm"], cfg, b=lp.get("attn_norm_b"))
        qc, kc, vc = qkv_proj(lp, yc, Hq, K, d, kernel_mesh=kernel_mesh)
        qc = _rope(qc, cos, sin, chunk_positions, cfg.rope_dim_)
        kc = _rope(kc, cos, sin, chunk_positions, cfg.rope_dim_)
        yd = _norm(xd, lp["attn_norm"], cfg, b=lp.get("attn_norm_b"))
        qd, kd, vd = qkv_proj(lp, yd, Hq, K, d, kernel_mesh=kernel_mesh)
        qd = _rope(qd, cos, sin, dec_positions, cfg.rope_dim_)
        kd = _rope(kd, cos, sin, dec_positions, cfg.rope_dim_)

        # chunk writes first, then the decode row writes — page-disjoint,
        # so the order is free (mirrors the solo programs' chunk-first)
        for i in range(C):
            written = write_token_kv(
                kp, vp, kc[:, i], vc[:, i], chunk_row, chunk_pos + i,
                k_scales=ksc, v_scales=vsc,
            )
            if kv_int8:
                kp, vp, ksc, vsc = written
            else:
                kp, vp = written
        written = write_token_kv(
            kp, vp, kd[:, 0], vd[:, 0], cache.block_table, cache.lengths,
            k_scales=ksc, v_scales=vsc,
        )
        if kv_int8:
            kp, vp, ksc, vsc = written
        else:
            kp, vp = written

        # ONE ragged invocation for both sides: decode rows padded to the
        # R-row tile (pad rows compute garbage never read), chunk padded
        # to a whole number of groups
        qv = jnp.concatenate([
            jnp.pad(qd, ((0, 0), (0, R - 1), (0, 0), (0, 0))),
            jnp.pad(qc, ((0, 0), (0, Cp - C), (0, 0), (0, 0)))
            .reshape(nG, R, Hq, d),
        ], axis=0)  # [Bv, R, Hq, d]
        if sharded:
            av = ragged_paged_attention_sharded(
                qv, kp, vp, btv, limits, q_lens, modes, kernel_mesh,
                axis_name="tp", k_scales=ksc, v_scales=vsc, window=win,
            )
        else:
            av = ragged_paged_attention(
                qv, kp, vp, btv, limits, q_lens, modes,
                k_scales=ksc, v_scales=vsc, window=win,
            )
        dec_attn = av[:B, :1]  # [B, 1, Hq, d]
        chunk_attn = av[B:].reshape(1, Cp, Hq, d)[:, :C]

        out = (kp, vp, ksc, vsc) if kv_int8 else (kp, vp)

        def tail(x, y, attn, T, nB):
            o = mm(attn.reshape(nB, T, Hq * d), lp["wo"])
            if "bo" in lp:
                o = o + lp["bo"]
            if cfg.parallel_block:  # Phi: x + attn(ln x) + mlp(ln x)
                mlp_out = (
                    _moe(cfg, y, lp, routed_moe, moe_mesh) if cfg.is_moe
                    else _mlp_dense(cfg, y, lp, kernel_mesh)
                )
                return x + o + mlp_out
            x = x + o
            y2 = _norm(x, lp["mlp_norm"], cfg, b=lp.get("mlp_norm_b"))
            if cfg.is_moe:
                mlp_out = _moe(cfg, y2, lp, routed_moe, moe_mesh)
            else:
                mlp_out = _mlp_dense(cfg, y2, lp, kernel_mesh)
            return x + mlp_out

        xc = tail(xc, yc, chunk_attn, C, 1)
        xd = tail(xd, yd, dec_attn, 1, B)
        return (xc, xd), out

    if kv_int8:
        xs = (
            params["layers"], cache.k_pages, cache.v_pages,
            cache.k_scales, cache.v_scales,
        )
        (xc, xd), (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
            body, (xc, xd), xs
        )
    else:
        xs = (params["layers"], cache.k_pages, cache.v_pages)
        (xc, xd), (new_k, new_v) = jax.lax.scan(body, (xc, xd), xs)
        new_ks = new_vs = None

    xc = _norm(xc, params["final_norm"], cfg, b=params.get("final_norm_b"))
    xd = _norm(xd, params["final_norm"], cfg, b=params.get("final_norm_b"))
    dec_logits = _logits(xd, params, cfg, kernel_mesh=kernel_mesh)
    new_cache = cache._replace(
        k_pages=new_k, v_pages=new_v, lengths=cache.lengths + 1,
        k_scales=new_ks, v_scales=new_vs,
    )
    return xc, dec_logits, new_cache


def forward_train(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T]
    remat: bool = True,
) -> jnp.ndarray:
    """Cache-free forward for training/fine-tuning: full causal attention
    over the sequence, layers rematerialized (``jax.checkpoint``) so the
    backward pass trades FLOPs for HBM. Returns logits [B, T, V] fp32."""
    B, T = tokens.shape
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None, :], (B, 1))
    cos, sin = compute_rope_freqs(cfg.rope_dim_, T, cfg.rope_theta)
    kv_length = jnp.zeros((B,), dtype=jnp.int32)

    dtype = model_dtype(params)
    x = embed_tokens(params, cfg, tokens, dtype)

    def body(x, lp):
        x, _, _ = _layer(cfg, x, lp, None, None, kv_length, positions, cos, sin)
        return x, None

    if remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])

    x = _norm(x, params["final_norm"], cfg, b=params.get("final_norm_b"))
    return _logits(x, params, cfg)
