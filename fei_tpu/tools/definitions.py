"""The 15 core tool declarations (JSON schema), parity with the reference's
fei/tools/definitions.py:11-441. Descriptions carry the behavior contracts the
model must follow (e.g. the Edit uniqueness rule, definitions.py:81-92).
"""

from __future__ import annotations

GLOB_TOOL = {
    "name": "GlobTool",
    "description": (
        "Fast file-pattern matching for any codebase size. Supports glob patterns like "
        "'**/*.js' or 'src/**/*.ts'. Returns matching file paths sorted by modification "
        "time (newest first). Use when you need to find files by name pattern."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "pattern": {"type": "string", "description": "Glob pattern to match files against"},
            "path": {"type": "string", "description": "Directory to search in (defaults to cwd)"},
        },
        "required": ["pattern"],
    },
}

GREP_TOOL = {
    "name": "GrepTool",
    "description": (
        "Fast content search using regular expressions. Searches file contents, returning "
        "matching lines with file and line number. Filter files with the include glob. "
        "Use when you need to find code by content rather than name."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "pattern": {"type": "string", "description": "Regex to search for in file contents"},
            "path": {"type": "string", "description": "Directory to search in (defaults to cwd)"},
            "include": {"type": "string", "description": "Glob filter, e.g. '*.py' or '*.{ts,tsx}'"},
        },
        "required": ["pattern"],
    },
}

VIEW_TOOL = {
    "name": "View",
    "description": (
        "Read a file from the filesystem. Returns numbered lines. By default reads from "
        "the beginning; pass offset/limit for long files. Files over 10 MB are rejected."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "file_path": {"type": "string", "description": "Absolute path to the file to read"},
            "offset": {"type": "integer", "description": "Line number to start reading from"},
            "limit": {"type": "integer", "description": "Number of lines to read"},
        },
        "required": ["file_path"],
    },
}

EDIT_TOOL = {
    "name": "Edit",
    "description": (
        "Edit a file by replacing one unique occurrence of old_string with new_string. "
        "CONTRACT: old_string must match EXACTLY one location in the file, including all "
        "whitespace and surrounding context — include at least 3 lines of context before "
        "and after the change point to make the match unique. If old_string matches zero "
        "or multiple locations the edit is rejected. To create a new file, pass the new "
        "path with an empty old_string and the full contents as new_string."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "file_path": {"type": "string", "description": "Absolute path to the file to modify"},
            "old_string": {"type": "string", "description": "Text to replace (must be unique)"},
            "new_string": {"type": "string", "description": "Replacement text"},
        },
        "required": ["file_path", "old_string", "new_string"],
    },
}

REPLACE_TOOL = {
    "name": "Replace",
    "description": (
        "Write a file to the filesystem, fully overwriting any existing content. "
        "Prefer Edit for partial changes; use Replace to create or rewrite whole files."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "file_path": {"type": "string", "description": "Absolute path to the file to write"},
            "content": {"type": "string", "description": "Complete new file content"},
        },
        "required": ["file_path", "content"],
    },
}

LS_TOOL = {
    "name": "LS",
    "description": (
        "List files and directories at a path. Optionally ignore glob patterns. "
        "Returns entries with type and size."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "path": {"type": "string", "description": "Absolute path to the directory to list"},
            "ignore": {
                "type": "array",
                "items": {"type": "string"},
                "description": "Glob patterns to exclude",
            },
        },
        "required": ["path"],
    },
}

BRAVE_SEARCH_TOOL = {
    "name": "brave_web_search",
    "description": (
        "Search the web with the Brave Search API. Use for current events, external "
        "documentation, or anything not in the local filesystem."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "query": {"type": "string", "description": "Search query"},
            "count": {"type": "integer", "description": "Number of results (1-20)", "minimum": 1, "maximum": 20},
        },
        "required": ["query"],
    },
}

REGEX_EDIT_TOOL = {
    "name": "RegexEdit",
    "description": (
        "Edit a file by applying a regex substitution to every match. Supports capture "
        "group references (\\1, \\g<name>) in the replacement. Validates the edited "
        "result parses (Python files are ast-checked) before committing."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "file_path": {"type": "string", "description": "Absolute path to the file to modify"},
            "pattern": {"type": "string", "description": "Regular expression to match"},
            "replacement": {"type": "string", "description": "Replacement (supports backrefs)"},
            "validate": {"type": "boolean", "description": "Syntax-check result before saving (default true)"},
        },
        "required": ["file_path", "pattern", "replacement"],
    },
}

BATCH_GLOB_TOOL = {
    "name": "BatchGlob",
    "description": (
        "Run multiple glob patterns in one call, in parallel. Returns a mapping from "
        "pattern to matched paths. Use to explore several file families at once."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "patterns": {
                "type": "array",
                "items": {"type": "string"},
                "description": "Glob patterns to match",
            },
            "path": {"type": "string", "description": "Directory to search in (defaults to cwd)"},
        },
        "required": ["patterns"],
    },
}

FIND_IN_FILES_TOOL = {
    "name": "FindInFiles",
    "description": (
        "Search for a regex across a specific list of files (rather than a directory "
        "tree). Returns matches grouped by file with line numbers."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "files": {
                "type": "array",
                "items": {"type": "string"},
                "description": "Files to search",
            },
            "pattern": {"type": "string", "description": "Regex to search for"},
        },
        "required": ["files", "pattern"],
    },
}

SMART_SEARCH_TOOL = {
    "name": "SmartSearch",
    "description": (
        "Code-aware search: give a natural query like 'function parse_args in python' "
        "and it combines language-specific file globs with definition-pattern regexes "
        "(def/class/function/etc.) to find the symbol."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "query": {"type": "string", "description": "Search query, may include a language hint"},
            "context": {"type": "string", "description": "Optional extra context about what you're looking for"},
        },
        "required": ["query"],
    },
}

REPO_MAP_TOOL = {
    "name": "RepoMap",
    "description": (
        "Generate a ranked map of the repository: files with their key symbols "
        "(classes/functions), ordered by cross-file reference importance, within a "
        "token budget. Use to orient in an unfamiliar codebase."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "path": {"type": "string", "description": "Repository root (defaults to cwd)"},
            "token_budget": {"type": "integer", "description": "Approximate token budget for the map"},
            "exclude": {"type": "array", "items": {"type": "string"}, "description": "Glob patterns to exclude"},
        },
    },
}

REPO_SUMMARY_TOOL = {
    "name": "RepoSummary",
    "description": (
        "Summarize repository structure by module/directory: file counts, languages, "
        "top symbols per module. Coarser and cheaper than RepoMap."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "path": {"type": "string", "description": "Repository root (defaults to cwd)"},
        },
    },
}

REPO_DEPS_TOOL = {
    "name": "RepoDependencies",
    "description": (
        "Extract the cross-file symbol dependency graph: which files reference symbols "
        "defined in which other files. Returns edges with the symbols involved."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "path": {"type": "string", "description": "Repository root (defaults to cwd)"},
            "file": {"type": "string", "description": "Restrict to dependencies of this file"},
        },
    },
}

SHELL_TOOL = {
    "name": "Shell",
    "description": (
        "Run a shell command. Only allowlisted commands are permitted (file inspection, "
        "build tools, test runners, version control); destructive or interactive "
        "commands are denied. Long-running commands can be sent to the background."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "command": {"type": "string", "description": "The command to execute"},
            "timeout": {"type": "integer", "description": "Seconds before the command is killed"},
            "background": {"type": "boolean", "description": "Run detached, returning a process id"},
            "cwd": {"type": "string", "description": "Working directory for the command"},
        },
        "required": ["command"],
    },
}

TOOL_DEFINITIONS = [
    GLOB_TOOL,
    GREP_TOOL,
    VIEW_TOOL,
    EDIT_TOOL,
    REPLACE_TOOL,
    LS_TOOL,
    REGEX_EDIT_TOOL,
    BATCH_GLOB_TOOL,
    FIND_IN_FILES_TOOL,
    SMART_SEARCH_TOOL,
    REPO_MAP_TOOL,
    REPO_SUMMARY_TOOL,
    REPO_DEPS_TOOL,
    SHELL_TOOL,
]

# The Anthropic-format list additionally exposes web search (parity:
# fei/tools/definitions.py:425-441).
ANTHROPIC_TOOL_DEFINITIONS = TOOL_DEFINITIONS + [BRAVE_SEARCH_TOOL]
