"""Memdir REST client with server auto-start.

Capability parity with the reference connector (fei/tools/memdir_connector.py:
25-644): URL/API-key resolution from config + env, ``X-API-Key``-authed JSON
requests, port-in-use probing, spawning ``python -m fei_tpu.memory.memdir.server``
as a detached child with a log file and atexit cleanup, health checking with
startup wait, and thin wrappers over the server's CRUD / search / folders /
filters routes (fei_tpu/memory/memdir/server.py).

Differences from the reference: stdlib ``urllib`` instead of ``requests``
(no extra dependency), and the child is killed via its process group with a
SIGTERM→SIGKILL escalation instead of the reference's bare killpg.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

from fei_tpu.utils.config import get_config
from fei_tpu.utils.errors import ConnectionError_, MemoryError_
from fei_tpu.utils.logging import get_logger

log = get_logger("tools.memdir_connector")

DEFAULT_URL = "http://127.0.0.1:5000"


class MemdirConnector:
    """HTTP client for the Memdir server; can spawn the server itself."""

    def __init__(
        self,
        server_url: str | None = None,
        api_key: str | None = None,
        auto_start: bool = False,
        base_dir: str | None = None,
        timeout: float = 10.0,
    ):
        cfg = get_config()
        self.server_url = (
            server_url
            or os.environ.get("MEMDIR_SERVER_URL")
            or cfg.get("memdir", "server_url", DEFAULT_URL)
        ).rstrip("/")
        self.api_key = (
            api_key
            or os.environ.get("MEMDIR_API_KEY")
            or cfg.get("memdir", "api_key", "")
            or "fei-tpu-memdir"
        )
        self.auto_start = auto_start
        self.base_dir = base_dir
        self.timeout = timeout
        self._server_proc: subprocess.Popen | None = None

    # ------------------------------------------------------------- requests
    def _make_request(self, method: str, path: str, params: dict | None = None,
                      body: dict | None = None, _retry: bool = True) -> dict:
        url = f"{self.server_url}{path}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode("utf-8") if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={
                "X-API-Key": self.api_key,
                "Content-Type": "application/json",
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            try:
                payload = json.loads(exc.read())
            except Exception:  # noqa: BLE001
                payload = {"error": str(exc)}
            raise MemoryError_(
                f"memdir server error {exc.code}: {payload.get('error', payload)}"
            ) from exc
        except (urllib.error.URLError, socket.timeout, ConnectionError) as exc:
            # one retry after an auto-start; never re-send non-idempotent
            # requests that may have reached a slow server
            if (self.auto_start and _retry and method == "GET"
                    and self._maybe_start_server()):
                return self._make_request(method, path, params, body, _retry=False)
            if self.auto_start and _retry and method != "GET":
                started = (self._is_local and not self._port_in_use()
                           and self._maybe_start_server())
                if started:
                    return self._make_request(method, path, params, body,
                                              _retry=False)
            raise ConnectionError_(
                f"cannot reach memdir server at {self.server_url}: {exc}"
            ) from exc

    # ------------------------------------------------------- server control
    @property
    def _port(self) -> int:
        parsed = urllib.parse.urlparse(self.server_url)
        return parsed.port or 5000

    @property
    def _is_local(self) -> bool:
        host = urllib.parse.urlparse(self.server_url).hostname
        return host in ("127.0.0.1", "localhost", "::1")

    def _port_in_use(self) -> bool:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.settimeout(0.5)
            return s.connect_ex(("127.0.0.1", self._port)) == 0

    def start_server_command(self) -> list[str]:
        cmd = [
            sys.executable, "-m", "fei_tpu.memory.memdir.server",
            "--port", str(self._port), "--api-key", self.api_key,
        ]
        if self.base_dir:
            cmd += ["--base", self.base_dir]
        return cmd

    def _maybe_start_server(self) -> bool:
        """Spawn the server if the URL is local and the port is free; never
        auto-start for a remote server_url — a local replacement would be a
        different (empty) store."""
        if not self._is_local:
            return False
        if self._server_proc is not None and self._server_proc.poll() is None:
            return self._wait_healthy(5.0)
        if self._port_in_use():
            return self._wait_healthy(2.0)
        return self.start_server()

    def start_server(self, wait: float = 10.0) -> bool:
        log_path = os.path.join(
            self.base_dir or os.path.expanduser("~/.fei_tpu"), "memdir_server.log"
        )
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        log.info("starting memdir server: %s", " ".join(self.start_server_command()))
        with open(log_path, "ab") as logf:
            self._server_proc = subprocess.Popen(
                self.start_server_command(),
                stdout=logf,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        atexit.register(self.stop_server)
        return self._wait_healthy(wait)

    def _wait_healthy(self, wait: float) -> bool:
        deadline = time.time() + wait
        while time.time() < deadline:
            if self.check_connection():
                return True
            time.sleep(0.15)
        return False

    def stop_server(self) -> bool:
        proc, self._server_proc = self._server_proc, None
        if proc is None or proc.poll() is not None:
            return False
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            try:
                proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                proc.wait(timeout=3)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def check_connection(self) -> bool:
        try:
            req = urllib.request.Request(f"{self.server_url}/health")
            with urllib.request.urlopen(req, timeout=2) as resp:
                return json.loads(resp.read()).get("status") == "ok"
        except Exception:  # noqa: BLE001
            return False

    def server_status(self) -> dict:
        running = self.check_connection()
        return {
            "running": running,
            "url": self.server_url,
            "managed_pid": self._server_proc.pid
            if self._server_proc and self._server_proc.poll() is None
            else None,
        }

    # ---------------------------------------------------------------- CRUD
    def create_memory(self, content: str, headers: dict | None = None,
                      folder: str = "", flags: str = "",
                      tags: list[str] | str | None = None) -> dict:
        if isinstance(tags, str):
            tags = [t.strip() for t in tags.split(",") if t.strip()]
        out = self._make_request("POST", "/memories", body={
            "content": content, "headers": headers or {},
            "folder": folder, "flags": flags, "tags": tags,
        })
        return out.get("memory", out)

    def list_memories(self, folder: str = "", status: str = "new",
                      with_content: bool = False) -> list[dict]:
        out = self._make_request("GET", "/memories", params={
            "folder": folder, "status": status,
            "with_content": "true" if with_content else "false",
        })
        return out.get("memories", [])

    def get_memory(self, memory_id: str, folder: str | None = None) -> dict:
        params = {"folder": folder} if folder else None
        return self._make_request("GET", f"/memories/{memory_id}",
                                  params=params).get("memory", {})

    def update_memory(self, memory_id: str, folder: str | None = None,
                      status: str | None = None, flags: str | None = None,
                      headers: dict | None = None) -> dict:
        body: dict = {}
        if folder is not None:
            body["folder"] = folder
        if status is not None:
            body["status"] = status
        if flags is not None:
            body["flags"] = flags
        if headers is not None:
            body["headers"] = headers
        return self._make_request("PUT", f"/memories/{memory_id}",
                                  body=body).get("memory", {})

    def move_memory(self, memory_id: str, target_folder: str,
                    status: str = "cur") -> dict:
        return self.update_memory(memory_id, folder=target_folder, status=status)

    def delete_memory(self, memory_id: str, hard: bool = False) -> bool:
        out = self._make_request("DELETE", f"/memories/{memory_id}",
                                 params={"hard": "true" if hard else "false"})
        return bool(out.get("deleted"))

    # -------------------------------------------------------------- search
    def search(self, query: str, folder: str | None = None,
               with_content: bool = False, limit: int | None = None) -> dict:
        if limit is not None and "limit:" not in query:
            query = f"{query} limit:{limit}".strip()
        params = {"q": query, "with_content": "true" if with_content else "false"}
        if folder:
            params["folder"] = folder
        out = self._make_request("GET", "/search", params=params)
        return {"results": out.get("results", []), "count": out.get("count", 0)}

    # ------------------------------------------------------------- folders
    def list_folders(self) -> list[str]:
        return self._make_request("GET", "/folders").get("folders", [])

    def create_folder(self, name: str) -> str:
        return self._make_request("POST", "/folders",
                                  body={"name": name}).get("folder", name)

    def delete_folder(self, name: str, force: bool = False) -> bool:
        quoted = urllib.parse.quote(name, safe="")
        out = self._make_request("DELETE", f"/folders/{quoted}",
                                 params={"force": "true" if force else "false"})
        return bool(out.get("deleted"))

    def rename_folder(self, name: str, new_name: str) -> str:
        quoted = urllib.parse.quote(name, safe="")
        return self._make_request("PUT", f"/folders/{quoted}",
                                  body={"rename": new_name}).get("folder", new_name)

    def folder_stats(self, name: str) -> dict:
        quoted = urllib.parse.quote(name, safe="")
        return self._make_request("GET", f"/folders/{quoted}/stats").get("stats", {})

    # ------------------------------------------------------------- filters
    def run_filters(self, folder: str = "") -> dict:
        return self._make_request("POST", "/filters/run",
                                  body={"folder": folder}).get("stats", {})
