"""PTY wrapper with prompt auto-confirmation.

Capability parity with the reference's ``claude_wrapper.js:1-117`` (a Node
node-pty script that runs a CLI under a pseudo-terminal and auto-answers its
interactive confirmation prompts) — rebuilt on the stdlib ``pty`` module so
it needs no Node runtime and wraps any command.

Use as a library::

    from fei_tpu.tools.pty_wrapper import PtyWrapper
    w = PtyWrapper(["some-cli", "--flag"],
                   responses={r"\\[y/N\\]": "y\\n", r"❯ Yes": "\\r"})
    exit_code = w.run()

or from the command line::

    python -m fei_tpu.tools.pty_wrapper --respond '\\[y/N\\]=y' -- some-cli --flag
"""

from __future__ import annotations

import argparse
import os
import pty
import re
import select
import sys
import time

from fei_tpu.utils.logging import get_logger

log = get_logger("tools.pty_wrapper")

# Defaults mirror the reference's auto-confirm behavior (claude_wrapper.js
# answers highlighted "❯ Yes" menus and y/N prompts affirmatively).
DEFAULT_RESPONSES = {
    r"❯\s*Yes": "\r",
    r"\[y/N\]|\[Y/n\]|\(y/n\)": "y\n",
    r"Press Enter to continue": "\n",
}


class PtyWrapper:
    def __init__(
        self,
        command: list[str],
        responses: dict[str, str] | None = None,
        echo: bool = True,
        timeout: float | None = None,
        response_cooldown: float = 0.5,
    ):
        if not command:
            raise ValueError("command must be non-empty")
        self.command = command
        self.responses = {
            re.compile(pat): reply
            for pat, reply in (responses or DEFAULT_RESPONSES).items()
        }
        self.echo = echo
        self.timeout = timeout
        self.response_cooldown = response_cooldown
        self.transcript: list[str] = []
        self.timed_out = False  # set when the timeout killed the child

    def run(self) -> int:
        """Run the command under a pty until it exits. Returns the exit code."""
        pid, master = pty.fork()
        if pid == 0:  # child
            try:
                os.execvp(self.command[0], self.command)
            except OSError as exc:
                os.write(2, f"exec failed: {exc}\n".encode())
                os._exit(127)

        start = time.monotonic()
        window = ""  # rolling tail of output the patterns match against
        last_response: tuple[str, float] | None = None
        reaped_status: int | None = None  # exit status if WNOHANG reaps first
        try:
            while True:
                if self.timeout and time.monotonic() - start > self.timeout:
                    log.warning("pty wrapper timeout; killing %s", self.command[0])
                    self.timed_out = True
                    os.kill(pid, 9)
                    break
                ready, _, _ = select.select([master], [], [], 0.25)
                if not ready:
                    done_pid, status = os.waitpid(pid, os.WNOHANG)
                    if done_pid != 0:
                        reaped_status = status  # don't lose the exit code
                        break
                    continue
                try:
                    chunk = os.read(master, 4096)
                except OSError:  # child closed the pty
                    break
                if not chunk:
                    break
                text = chunk.decode("utf-8", errors="replace")
                self.transcript.append(text)
                if self.echo:
                    sys.stdout.write(text)
                    sys.stdout.flush()
                window = (window + text)[-2048:]
                for rx, reply in self.responses.items():
                    if rx.search(window):
                        now = time.monotonic()
                        # don't machine-gun the same prompt: one reply per
                        # pattern per cooldown window
                        if (
                            last_response
                            and last_response[0] == rx.pattern
                            and now - last_response[1] < self.response_cooldown
                        ):
                            continue
                        log.info("auto-responding to %r", rx.pattern)
                        os.write(master, reply.encode())
                        last_response = (rx.pattern, now)
                        window = ""
                        break
        finally:
            os.close(master)
        if reaped_status is None:
            try:
                _, reaped_status = os.waitpid(pid, 0)
            except ChildProcessError:
                return 0
        if os.WIFEXITED(reaped_status):
            return os.WEXITSTATUS(reaped_status)
        return 128 + os.WTERMSIG(reaped_status)

    @property
    def output(self) -> str:
        return "".join(self.transcript)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fei_tpu.tools.pty_wrapper",
        description="run a command under a pty, auto-answering prompts",
    )
    p.add_argument(
        "--respond", action="append", default=[],
        metavar="REGEX=REPLY",
        help="add a pattern->reply rule (repeatable); replaces the defaults",
    )
    p.add_argument("--timeout", type=float, default=None)
    p.add_argument("--quiet", action="store_true", help="don't echo output")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- command and args to wrap")
    args = p.parse_args(argv)
    cmd = [c for c in args.command if c != "--"]
    if not cmd:
        p.error("no command given (use: ... -- cmd args)")
    responses = None
    if args.respond:
        responses = {}
        for rule in args.respond:
            pat, _, reply = rule.partition("=")
            responses[pat] = reply.encode().decode("unicode_escape")
    w = PtyWrapper(cmd, responses=responses, echo=not args.quiet,
                   timeout=args.timeout)
    return w.run()


if __name__ == "__main__":
    raise SystemExit(main())
