"""File/search/edit/shell machinery behind the code tools.

Capability parity with the reference's fei/tools/code.py:49-1724 (GlobFinder,
GrepTool, CodeEditor, FileViewer, DirectoryExplorer, SystemInfo, ShellRunner),
with the reference's known defects fixed:

- path-safety uses ``os.path.commonpath`` instead of the bypassable string
  prefix check (reference code.py:77-81);
- no ``shell=True`` for foreground commands unless the command needs shell
  features (pipes/redirection), and the allow/deny check runs on every
  pipeline segment, not just the first token;
- backups are atomic and pruned under a lock.

A native C++ scan engine (fei_tpu.native) accelerates the grep hot loop when
built; the pure-Python path is the always-available fallback.
"""

from __future__ import annotations

import fnmatch
import glob as _glob
import hashlib
import os
import re
import shlex
import shutil
import signal
import stat
import subprocess
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass

from fei_tpu.utils.errors import ToolError
from fei_tpu.utils.logging import get_logger

log = get_logger("tools.code")

MAX_FILE_SIZE = 10 * 1024 * 1024  # 10 MB read cap (parity: reference code.py:33)
MAX_OUTPUT_CHARS = 50_000  # shell output truncation (parity: reference code.py:35)
_BINARY_SNIFF = 4096


def _is_within(base: str, path: str) -> bool:
    """True if ``path`` is inside ``base`` (commonpath, not prefix-string)."""
    try:
        base = os.path.realpath(base)
        path = os.path.realpath(path)
        return os.path.commonpath([base, path]) == base
    except ValueError:  # different drives / mixed abs-rel
        return False


def _looks_binary(path: str) -> bool:
    try:
        with open(path, "rb") as fh:
            chunk = fh.read(_BINARY_SNIFF)
        return b"\0" in chunk
    except OSError:
        return True


def _expand_brace(pattern: str) -> list[str]:
    """Expand one level of {a,b} alternation (fnmatch has none)."""
    m = re.search(r"\{([^{}]*)\}", pattern)
    if not m:
        return [pattern]
    out = []
    for alt in m.group(1).split(","):
        out.extend(_expand_brace(pattern[: m.start()] + alt + pattern[m.end():]))
    return out


class GlobFinder:
    """Glob matching with a result cache and an optional base-path jail."""

    def __init__(self, base_path: str | None = None, cache_ttl: float = 60.0):
        self.base_path = os.path.realpath(base_path) if base_path else None
        self.cache_ttl = cache_ttl
        self._cache: dict[tuple[str, str], tuple[float, list[str]]] = {}
        self._lock = threading.Lock()

    def _check_path(self, path: str) -> None:
        if self.base_path and not _is_within(self.base_path, path):
            raise ToolError(f"path {path!r} escapes the allowed base {self.base_path!r}")

    def find(self, pattern: str, path: str | None = None) -> list[str]:
        root = os.path.realpath(path or os.getcwd())
        self._check_path(root)
        key = (pattern, root)
        now = time.time()
        with self._lock:
            hit = self._cache.get(key)
            if hit and now - hit[0] < self.cache_ttl:
                return list(hit[1])
        matches: list[str] = []
        for pat in _expand_brace(pattern):
            full = pat if os.path.isabs(pat) else os.path.join(root, pat)
            matches.extend(p for p in _glob.glob(full, recursive=True) if os.path.isfile(p))
        matches = sorted(set(matches), key=lambda p: -_safe_mtime(p))
        with self._lock:
            self._cache[key] = (now, matches)
        return matches


def _safe_mtime(path: str) -> float:
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


@dataclass
class GrepMatch:
    file: str
    line_number: int
    line: str


class GrepTool:
    """Parallel regex content search with compiled-pattern caching."""

    def __init__(self, max_workers: int = 8):
        self.max_workers = max_workers
        self._regex_cache: dict[str, re.Pattern] = {}
        self._lock = threading.Lock()

    def _compile(self, pattern: str) -> re.Pattern:
        with self._lock:
            rx = self._regex_cache.get(pattern)
            if rx is None:
                rx = re.compile(pattern)
                self._regex_cache[pattern] = rx
        return rx

    def _candidate_files(self, path: str, include: str | None) -> list[str]:
        files: list[str] = []
        skip_dirs = {".git", "__pycache__", "node_modules", ".venv", "venv", ".fei_backups"}
        inc_pats = _expand_brace(include) if include else None
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d not in skip_dirs]
            for fn in filenames:
                if inc_pats and not any(fnmatch.fnmatch(fn, p) for p in inc_pats):
                    continue
                files.append(os.path.join(dirpath, fn))
        return files

    def search(
        self,
        pattern: str,
        path: str | None = None,
        include: str | None = None,
        max_results: int = 1000,
    ) -> list[GrepMatch]:
        rx = self._compile(pattern)
        root = os.path.realpath(path or os.getcwd())
        if os.path.isfile(root):
            return self._search_file(root, rx, max_results)
        files = self._candidate_files(root, include)
        # Try the native C++ scanner first (fei_tpu.native, task: hot loop).
        try:
            from fei_tpu.native import scan as native_scan

            raw = native_scan.grep_files(files, pattern, max_results)
            if raw is not None:
                matches = [GrepMatch(f, ln, text) for f, ln, text in raw]
                # same ordering contract as the Python path
                matches.sort(
                    key=lambda m: (-_safe_mtime(m.file), m.file, m.line_number)
                )
                return matches[:max_results]
        except Exception:  # noqa: BLE001 — native path is best-effort
            pass
        results: list[GrepMatch] = []
        with ThreadPoolExecutor(max_workers=min(self.max_workers, max(1, len(files)))) as pool:
            futures = {pool.submit(self._search_file, f, rx, max_results): f for f in files}
            for fut in as_completed(futures):
                results.extend(fut.result())
                if len(results) >= max_results:
                    for other in futures:
                        other.cancel()
                    break
        results.sort(key=lambda m: (-_safe_mtime(m.file), m.file, m.line_number))
        return results[:max_results]

    def _search_file(self, path: str, rx: re.Pattern, limit: int) -> list[GrepMatch]:
        out: list[GrepMatch] = []
        try:
            if os.path.getsize(path) > MAX_FILE_SIZE or _looks_binary(path):
                return out
            with open(path, "r", encoding="utf-8", errors="replace") as fh:
                for i, line in enumerate(fh, 1):
                    if rx.search(line):
                        out.append(GrepMatch(path, i, line.rstrip("\n")))
                        if len(out) >= limit:
                            break
        except OSError:
            pass
        return out


def _check_brackets(content: str, lang: str = "js") -> str | None:
    """Comment/string-aware bracket balance for brace-family languages.

    Not a parser: it exists to reject the failure modes edits actually
    produce (truncated blocks, a deleted closing brace) while never
    rejecting valid code. ``lang``:

    - "js"    — ``'…'`` is a string; regex literals (after operators or
                regex-context keywords) are skipped; ``#field`` is code.
    - "c"     — preprocessor lines (incl. backslash continuations) are
                skipped; single quotes are short char literals only.
    - "brace" — go/java/rust: char-literal single quotes (so Rust
                lifetimes pass), no preprocessor, no regex literals.
    """
    pairs = {")": "(", "]": "[", "}": "{"}
    # '/' after these starts a regex, after a value it's division — the
    # standard JS lexer heuristic, extended with regex-context keywords
    _REGEX_PUNCT = "(=,:[!&|?{};\n<>+-*%~^"
    _REGEX_KEYWORDS = {
        "return", "typeof", "case", "in", "of", "delete", "void", "do",
        "else", "instanceof", "new", "throw", "yield", "await",
    }
    stack: list[tuple[str, int]] = []
    line = 1
    i, n = 0, len(content)
    prev_sig = "\n"  # last non-whitespace char outside comments/strings
    word = ""  # identifier/keyword accumulator ending at prev_sig
    word_dotted = False  # word is a property access (obj.in) — not a keyword
    while i < n:
        c = content[i]
        if c == "\n":
            line += 1
        elif c == "/" and i + 1 < n and content[i + 1] == "/":
            i = content.find("\n", i)
            if i < 0:
                break
            continue
        elif (
            c == "#"
            and lang == "c"  # never JS ('#field' is a class member) or rust
            and (i == 0 or content[i - 1] in "\n\t ")
        ):
            # preprocessor line: skip to EOL, following backslash
            # continuations (#define WRAP(x) do { \ ... } while (0))
            while True:
                eol = content.find("\n", i)
                if eol < 0:
                    i = n
                    break
                line += 1
                i = eol + 1
                skipped = content[content.rfind("\n", 0, eol) + 1:eol]
                if not skipped.rstrip().endswith("\\"):
                    break
            continue
        elif c == "/" and i + 1 < n and content[i + 1] == "*":
            end = content.find("*/", i + 2)
            if end < 0:
                return f"unterminated block comment starting line {line}"
            line += content.count("\n", i, end)
            i = end + 2
            continue
        elif (
            c == "/" and lang == "js"
            and (
                prev_sig in _REGEX_PUNCT
                or (word in _REGEX_KEYWORDS and not word_dotted)
            )
        ):
            # regex literal — quotes/brackets inside are not code
            j, in_class = i + 1, False
            while j < n and content[j] != "\n":
                cj = content[j]
                if cj == "\\":
                    j += 2
                    continue
                if cj == "[":
                    in_class = True
                elif cj == "]":
                    in_class = False
                elif cj == "/" and not in_class:
                    break
                j += 1
            if j < n and content[j] == "/":
                i = j + 1
                prev_sig, word = "/", ""
                continue
            # no closing '/': treat as division, fall through
        elif c == "'" and lang in ("c", "brace"):
            # consume only a genuine char literal: exactly one char ('a',
            # '{') or an escape ('\n', '\u{1F600}'). A lone quote (Rust
            # lifetime, apostrophe) is plain text — a wide window with
            # any closing quote would swallow code like <'a>(x: &'a [u8]).
            j, limit = i + 1, min(i + 12, n)
            is_escape = j < n and content[j] == "\\"
            while j < limit and content[j] != "'" and content[j] != "\n":
                j += 2 if content[j] == "\\" else 1
            if (
                j < limit and content[j] == "'"
                and (j == i + 2 or is_escape)
            ):
                i = j + 1
                continue
        elif c in ("'", '"', "`") and not (c == "'" and lang in ("c", "brace")):
            quote, start_line = c, line
            i += 1
            while i < n:
                if content[i] == "\\":
                    i += 2
                    continue
                if content[i] == "\n":
                    line += 1
                    if quote != "`":  # ordinary strings don't span lines
                        break
                if content[i] == quote:
                    break
                i += 1
            if i >= n:
                return f"unterminated string starting line {start_line}"
            i += 1
            prev_sig, word = quote, ""  # a string is a value: '/' divides
            continue
        elif c in "([{":
            stack.append((c, line))
        elif c in ")]}":
            if not stack or stack[-1][0] != pairs[c]:
                return f"unbalanced {c!r} at line {line}"
            stack.pop()
        if not c.isspace():
            if c.isalnum() or c in "_$":
                if not word:
                    word_dotted = prev_sig == "."
                word += c
            else:
                word = ""
            prev_sig = c
        i += 1
    if stack:
        ch, ln = stack[-1]
        return f"unclosed {ch!r} opened at line {ln}"
    return None


class CodeEditor:
    """Edit/create/replace files with rolling backups and syntax validation."""

    def __init__(self, backup_dir: str = ".fei_backups", max_backups: int = 10):
        self.backup_dir = backup_dir
        self.max_backups = max_backups
        self._lock = threading.Lock()

    def _backup(self, file_path: str) -> str | None:
        if not os.path.exists(file_path):
            return None
        bdir = os.path.join(os.path.dirname(os.path.abspath(file_path)), self.backup_dir)
        os.makedirs(bdir, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S") + f"-{int(time.time_ns() % 1_000_000):06d}"
        dest = os.path.join(bdir, f"{os.path.basename(file_path)}.{stamp}")
        with self._lock:
            shutil.copy2(file_path, dest)
            # prune oldest beyond max_backups for this file
            base = os.path.basename(file_path) + "."
            backups = sorted(p for p in os.listdir(bdir) if p.startswith(base))
            for old in backups[: max(0, len(backups) - self.max_backups)]:
                try:
                    os.remove(os.path.join(bdir, old))
                except OSError:
                    pass
        return dest

    @staticmethod
    def _validate_code(path: str, content: str) -> str | None:
        """Tiered post-edit validation (capability parity with the
        reference's ast→esprima→pylint/flake8 ladder at
        fei/tools/code.py:827-932, without its external-tool deps):

        - .py          — exact: stdlib ast
        - .json        — exact: json.loads
        - .yaml/.yml   — exact when PyYAML importable, else skipped
        - brace langs  — js/ts/c/c++/java/go/rust: comment/string-aware
                         bracket balance (catches the truncated-edit and
                         mismatched-block failures edits actually produce)
        - anything else— no validation (plain text is always legal)
        """
        ext = os.path.splitext(path)[1].lower()
        if ext == ".py":
            import ast

            try:
                ast.parse(content)
                return None
            except SyntaxError as exc:
                return f"python syntax error at line {exc.lineno}: {exc.msg}"
        if ext == ".json":
            import json

            try:
                json.loads(content)
                return None
            except ValueError as exc:
                return f"invalid json: {exc}"
        if ext in (".yaml", ".yml"):
            try:
                import yaml
            except ImportError:
                return None
            try:
                yaml.safe_load(content)
                return None
            except yaml.YAMLError as exc:
                return f"invalid yaml: {exc}"
        if ext in (".js", ".jsx", ".ts", ".tsx", ".mjs", ".cjs"):
            return _check_brackets(content, lang="js")
        if ext in (".c", ".h", ".cc", ".cpp", ".hpp"):
            return _check_brackets(content, lang="c")
        if ext in (".java", ".go", ".rs"):
            return _check_brackets(content, lang="brace")
        return None

    def edit_file(self, file_path: str, old_string: str, new_string: str) -> dict:
        """Unique-match replace; empty old_string creates a new file.

        Contract parity: reference fei/tools/code.py:618-668 + the uniqueness
        rule in definitions.py:81-92.
        """
        if old_string == "":
            return self.create_file(file_path, new_string)
        if not os.path.isfile(file_path):
            raise ToolError(f"file not found: {file_path}")
        with open(file_path, "r", encoding="utf-8", errors="replace") as fh:
            content = fh.read()
        count = content.count(old_string)
        if count == 0:
            raise ToolError("old_string not found in file — include exact text with context")
        if count > 1:
            raise ToolError(
                f"old_string matches {count} locations — add surrounding context to make it unique"
            )
        new_content = content.replace(old_string, new_string, 1)
        err = self._validate_code(file_path, new_content)
        if err:
            raise ToolError(f"edit rejected, result does not parse: {err}")
        backup = self._backup(file_path)
        _atomic_write(file_path, new_content)
        return {"file_path": file_path, "backup": backup, "replaced": 1}

    def create_file(self, file_path: str, content: str) -> dict:
        if os.path.exists(file_path):
            raise ToolError(f"file already exists: {file_path} (use Replace to overwrite)")
        err = self._validate_code(file_path, content)
        if err:
            raise ToolError(f"create rejected, content does not parse: {err}")
        os.makedirs(os.path.dirname(os.path.abspath(file_path)), exist_ok=True)
        _atomic_write(file_path, content)
        return {"file_path": file_path, "created": True, "bytes": len(content.encode())}

    def replace_file(self, file_path: str, content: str) -> dict:
        err = self._validate_code(file_path, content)
        if err:
            raise ToolError(f"replace rejected, content does not parse: {err}")
        backup = self._backup(file_path)
        os.makedirs(os.path.dirname(os.path.abspath(file_path)), exist_ok=True)
        _atomic_write(file_path, content)
        return {"file_path": file_path, "backup": backup, "bytes": len(content.encode())}

    def regex_replace(
        self, file_path: str, pattern: str, replacement: str, validate: bool = True
    ) -> dict:
        if not os.path.isfile(file_path):
            raise ToolError(f"file not found: {file_path}")
        rx = re.compile(pattern, re.MULTILINE)
        with open(file_path, "r", encoding="utf-8", errors="replace") as fh:
            content = fh.read()
        new_content, n = rx.subn(replacement, content)
        if n == 0:
            return {"file_path": file_path, "replaced": 0}
        if validate:
            err = self._validate_code(file_path, new_content)
            if err:
                raise ToolError(f"regex edit rejected, result does not parse: {err}")
        backup = self._backup(file_path)
        _atomic_write(file_path, new_content)
        return {"file_path": file_path, "backup": backup, "replaced": n}


def _atomic_write(path: str, content: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(content)
    os.replace(tmp, path)


class FileViewer:
    """Read files with numbered lines, size caps, offset/limit, hashing."""

    def view(self, file_path: str, offset: int = 0, limit: int | None = None) -> dict:
        if not os.path.isfile(file_path):
            raise ToolError(f"file not found: {file_path}")
        size = os.path.getsize(file_path)
        if size > MAX_FILE_SIZE:
            raise ToolError(f"file too large ({size} bytes > {MAX_FILE_SIZE})")
        if _looks_binary(file_path):
            return {"file_path": file_path, "binary": True, "size": size}
        lines: list[str] = []
        total = 0
        with open(file_path, "r", encoding="utf-8", errors="replace") as fh:
            for i, line in enumerate(fh):
                total = i + 1
                if i < offset:
                    continue
                if limit is not None and len(lines) >= limit:
                    # keep counting total lines cheaply
                    continue
                lines.append(f"{i + 1:6d}\t{line.rstrip(chr(10))}")
        return {
            "file_path": file_path,
            "content": "\n".join(lines),
            "total_lines": total,
            "offset": offset,
            "shown": len(lines),
        }

    @staticmethod
    def count_lines(file_path: str) -> int:
        n = 0
        with open(file_path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                n += chunk.count(b"\n")
        return n

    @staticmethod
    def file_hash(file_path: str) -> str:
        h = hashlib.sha256()
        with open(file_path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()


class DirectoryExplorer:
    """Directory listing with ignore patterns and recursive mode."""

    def list_directory(
        self,
        path: str,
        ignore: list[str] | None = None,
        recursive: bool = False,
        max_entries: int = 2000,
    ) -> dict:
        if not os.path.isdir(path):
            raise ToolError(f"not a directory: {path}")
        ignore = ignore or []

        def ignored(name: str) -> bool:
            return any(fnmatch.fnmatch(name, pat) for pat in ignore)

        entries: list[dict] = []
        if recursive:
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if not ignored(d)]
                for name in sorted(dirnames):
                    entries.append({"path": os.path.join(dirpath, name), "type": "dir"})
                for name in sorted(filenames):
                    if ignored(name):
                        continue
                    fp = os.path.join(dirpath, name)
                    entries.append({"path": fp, "type": "file", "size": _safe_size(fp)})
                if len(entries) >= max_entries:
                    break
        else:
            for name in sorted(os.listdir(path)):
                if ignored(name):
                    continue
                fp = os.path.join(path, name)
                if os.path.isdir(fp):
                    entries.append({"path": fp, "type": "dir"})
                else:
                    entries.append({"path": fp, "type": "file", "size": _safe_size(fp)})
        truncated = len(entries) > max_entries
        return {"path": path, "entries": entries[:max_entries], "truncated": truncated}


def _safe_size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


class SystemInfo:
    """OS / memory / disk information (psutil optional)."""

    def get_info(self) -> dict:
        import platform

        info: dict = {
            "platform": platform.platform(),
            "python": platform.python_version(),
            "cpu_count": os.cpu_count(),
            "cwd": os.getcwd(),
        }
        try:
            usage = shutil.disk_usage("/")
            info["disk"] = {"total": usage.total, "free": usage.free}
        except OSError:
            pass
        try:
            with open("/proc/meminfo") as fh:
                mem = dict(
                    (k.strip(), v.strip())
                    for k, _, v in (ln.partition(":") for ln in fh)
                )
            info["memory"] = {
                "total": mem.get("MemTotal"),
                "available": mem.get("MemAvailable"),
            }
        except OSError:
            pass
        try:
            import jax

            info["accelerator"] = {
                "backend": jax.default_backend(),
                "devices": [str(d) for d in jax.devices()],
            }
        except Exception:  # noqa: BLE001
            pass
        return info


# Commands the agent may run. A command is allowed iff EVERY pipeline segment's
# argv[0] basename is in ALLOWED_COMMANDS and no DENIED pattern matches the
# whole line (parity: reference code.py:1352-1404, with per-segment checks).
ALLOWED_COMMANDS = {
    # inspection
    "ls", "cat", "head", "tail", "wc", "file", "stat", "du", "df", "find",
    "grep", "egrep", "fgrep", "rg", "awk", "sed", "sort", "uniq", "cut", "tr",
    "diff", "cmp", "md5sum", "sha256sum", "which", "whereis", "realpath",
    "basename", "dirname", "pwd", "echo", "printf", "env", "date", "uname",
    "xargs", "tee", "jq", "column", "nl", "strings", "od", "hexdump", "tree",
    # vcs
    "git",
    # build / test
    "python", "python3", "pip", "pytest", "make", "cmake", "ninja", "g++",
    "gcc", "cc", "ld", "ar", "nm", "objdump", "bazel", "protoc", "node",
    "npm", "npx", "tar", "gzip", "gunzip", "zip", "unzip", "touch", "mkdir",
}

DENIED_PATTERNS = [
    r"\brm\s+(-[a-zA-Z]*\s+)*/((\s|$)|\*)",  # rm at filesystem root
    r"\bdd\b.*\bof=/dev/",
    r"\bmkfs\b",
    r"\bshutdown\b|\breboot\b|\bhalt\b",
    r":\(\)\s*\{.*\};:",  # fork bomb
    r"\bcurl\b.*\|\s*(ba)?sh",
    r"\bwget\b.*\|\s*(ba)?sh",
    r"\bchmod\s+777\s+/",
    r"\bsudo\b|\bsu\b\s",
    r">\s*/dev/sd",
]

INTERACTIVE_COMMANDS = {"vi", "vim", "nano", "emacs", "less", "more", "top", "htop",
                        "ssh", "ftp", "telnet"}


class ShellRunner:
    """Allowlisted shell execution with timeout, background mode, truncation."""

    def __init__(self, allowed: set[str] | None = None, denied: list[str] | None = None):
        self.allowed = allowed or ALLOWED_COMMANDS
        self.denied = [re.compile(p) for p in (denied or DENIED_PATTERNS)]
        self._lock = threading.RLock()
        self._background: dict[int, subprocess.Popen] = {}

    def _segments(self, command: str) -> list[list[str]] | str:
        """Quote-aware pipeline segmentation; str return = parse error."""
        try:
            lex = shlex.shlex(command, posix=True, punctuation_chars=True)
            lex.whitespace_split = True
            tokens = list(lex)
        except ValueError as exc:
            return f"unparseable command: {exc}"
        segments: list[list[str]] = [[]]
        for tok in tokens:
            if tok in ("|", "||", "&&", ";", "&", "|&") or set(tok) <= {"|", "&", ";"}:
                segments.append([])
            elif tok.startswith((">", "<", ">>", "2>")):
                continue
            else:
                segments[-1].append(tok)
        return segments

    @staticmethod
    def _segment_prog(argv: list[str]) -> str | None:
        # skip env-var assignments prefix (FOO=bar cmd ...)
        i = 0
        while i < len(argv) and re.match(r"^[A-Za-z_][A-Za-z0-9_]*=", argv[i]):
            i += 1
        return os.path.basename(argv[i]) if i < len(argv) else None

    def is_interactive(self, command: str) -> bool:
        """Heuristic from the reference (fei/tools/code.py:1494-1519): any
        pipeline segment whose program expects a terminal. Allowed
        interactive commands run under the PTY wrapper with prompt
        auto-confirmation instead of hanging on a missing tty; note the
        allowlist still gates first, so members of INTERACTIVE_COMMANDS
        only reach the PTY when a caller's custom allowlist includes them
        — the default allowlist admits only the flag/subcommand cases
        (python -i, git rebase -i, npm init, pip uninstall)."""
        segments = self._segments(command)
        if isinstance(segments, str):
            return False
        progs = {self._segment_prog(a) for a in segments}
        if progs & INTERACTIVE_COMMANDS:
            return True
        # flag/subcommand-based interactivity of otherwise-batch programs
        for argv in segments:
            prog = self._segment_prog(argv)
            rest = argv[argv.index(prog) if prog in argv else 0:]
            if prog in ("python", "python3") and "-i" in rest:
                return True
            if prog == "git" and (
                ("rebase" in rest and "-i" in rest)
                or ("add" in rest and ("-p" in rest or "-i" in rest))
            ):
                return True
            if prog == "npm" and any(s in rest for s in ("init", "login")):
                return True
            if prog == "pip" and "uninstall" in rest and "-y" not in rest:
                return True
        return False

    def check_command(self, command: str) -> str | None:
        """Return a denial reason, or None if the command is allowed."""
        for rx in self.denied:
            if rx.search(command):
                return f"command denied by policy: {rx.pattern}"
        segments = self._segments(command)
        if isinstance(segments, str):
            return segments
        for argv in segments:
            prog = self._segment_prog(argv)
            if prog is None:
                continue
            if prog not in self.allowed:
                return f"command not in allowlist: {prog}"
        return None

    def run(
        self,
        command: str,
        timeout: int = 60,
        background: bool = False,
        cwd: str | None = None,
    ) -> dict:
        reason = self.check_command(command)
        if reason:
            return {"error": reason, "exit_code": -1}
        if background:
            return self._run_background(command, timeout, cwd)
        if self.is_interactive(command):
            return self._run_pty(command, timeout, cwd)
        try:
            proc = subprocess.run(
                command,
                shell=True,
                capture_output=True,
                text=True,
                timeout=timeout,
                cwd=cwd,
                start_new_session=True,
            )
            out, err = proc.stdout, proc.stderr
            truncated = False
            if len(out) > MAX_OUTPUT_CHARS:
                out, truncated = out[:MAX_OUTPUT_CHARS] + "\n…[truncated]", True
            if len(err) > MAX_OUTPUT_CHARS:
                err, truncated = err[:MAX_OUTPUT_CHARS] + "\n…[truncated]", True
            return {
                "stdout": out,
                "stderr": err,
                "exit_code": proc.returncode,
                "truncated": truncated,
            }
        except subprocess.TimeoutExpired:
            return {"error": f"command timed out after {timeout}s", "exit_code": -1}

    def _run_pty(self, command: str, timeout: int, cwd: str | None) -> dict:
        """Run an interactive command under the PTY wrapper: it gets a real
        tty and its confirmation prompts are auto-answered
        (tools/pty_wrapper.py; reference behavior claude_wrapper.js:48-60
        generalized). Output is the captured transcript."""
        from fei_tpu.tools.pty_wrapper import PtyWrapper

        if cwd:
            command = f"cd {shlex.quote(cwd)} && {command}"
        try:
            wrapper = PtyWrapper(
                ["bash", "-c", command], echo=False, timeout=float(timeout)
            )
            code = wrapper.run()
            out = wrapper.output
            truncated = len(out) > MAX_OUTPUT_CHARS
            if truncated:
                out = out[:MAX_OUTPUT_CHARS] + "\n…[truncated]"
            result = {
                "stdout": out, "stderr": "", "exit_code": code,
                "interactive": True, "truncated": truncated,
            }
            if wrapper.timed_out:
                # same contract as the subprocess path: timeouts are errors
                result["error"] = f"command timed out after {timeout}s"
            return result
        except Exception as exc:  # noqa: BLE001 — pty can fail in odd envs
            return {"error": f"pty execution failed: {exc}", "exit_code": -1}

    def _run_background(self, command: str, timeout: int, cwd: str | None) -> dict:
        proc = subprocess.Popen(
            command,
            shell=True,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            cwd=cwd,
            start_new_session=True,
        )
        with self._lock:
            self._background[proc.pid] = proc
        if timeout:
            killer = threading.Timer(timeout, self._kill_group, args=(proc,))
            killer.daemon = True
            killer.start()
        return {"pid": proc.pid, "background": True}

    @staticmethod
    def _kill_group(proc: subprocess.Popen) -> None:
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                time.sleep(2)
                if proc.poll() is None:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def stop_background(self, pid: int) -> bool:
        with self._lock:
            proc = self._background.pop(pid, None)
        if proc is None:
            return False
        self._kill_group(proc)
        return True


# Module singletons, mirroring the reference's convenience instances
# (fei/tools/code.py:1717-1724).
glob_finder = GlobFinder()
grep_tool = GrepTool()
code_editor = CodeEditor()
file_viewer = FileViewer()
directory_explorer = DirectoryExplorer()
system_info = SystemInfo()
shell_runner = ShellRunner()
