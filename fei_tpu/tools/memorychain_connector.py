"""Memorychain node HTTP client.

Capability parity with the reference connector (fei/tools/
memorychain_connector.py:33-716): node address from env/config
(``MEMORYCHAIN_NODE``), health + node/network status, ``add_memory`` via
``/memorychain/propose``, chain fetch, client-side content/tag search over
the fetched chain, chain statistics histograms, ``#mem:id`` reference
extraction/resolution, chain validation with a local fallback, plus the task
lifecycle (propose/claim/submit/vote) and FeiCoin wallet wrappers the
reference exposes through its CLI (memdir_tools/memorychain_cli.py:513-801).
"""

from __future__ import annotations

import json
import os
import re
import socket
import urllib.error
import urllib.parse
import urllib.request
import uuid

from fei_tpu.utils.config import get_config
from fei_tpu.utils.errors import ConnectionError_, MemoryError_
from fei_tpu.utils.logging import get_logger

log = get_logger("tools.memorychain_connector")

DEFAULT_NODE = "http://127.0.0.1:6789"

# reference memorychain_connector.py:495-541 — inline memory references
MEM_REF_RE = re.compile(r"#mem:([0-9a-f]{6,})")


class MemorychainConnector:
    """HTTP client for one Memorychain node (fei_tpu/memory/memorychain/node.py)."""

    def __init__(self, node_url: str | None = None, timeout: float = 10.0):
        cfg = get_config()
        self.node_url = (
            node_url
            or os.environ.get("MEMORYCHAIN_NODE")
            or cfg.get("memorychain", "node_url", DEFAULT_NODE)
        ).rstrip("/")
        self.timeout = timeout

    # ------------------------------------------------------------ plumbing
    def _request(self, method: str, path: str, body: dict | None = None,
                 params: dict | None = None) -> dict:
        url = f"{self.node_url}{path}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode("utf-8") if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            try:
                payload = json.loads(exc.read())
            except Exception:  # noqa: BLE001
                payload = {"error": str(exc)}
            raise MemoryError_(
                f"memorychain node error {exc.code}: {payload.get('error', payload)}"
            ) from exc
        except (urllib.error.URLError, socket.timeout, ConnectionError) as exc:
            raise ConnectionError_(
                f"cannot reach memorychain node at {self.node_url}: {exc}"
            ) from exc

    def check_connection(self) -> bool:
        try:
            return self._request("GET", "/health").get("status") == "ok"
        except Exception:  # noqa: BLE001 — predicate must never raise
            return False

    # -------------------------------------------------------------- status
    def node_status(self) -> dict:
        return self._request("GET", "/memorychain/node_status")

    def network_status(self) -> dict:
        return self._request("GET", "/memorychain/network_status")

    def update_status(self, **fields) -> dict:
        return self._request("POST", "/memorychain/update_status", body=fields)

    # ------------------------------------------------------------ memories
    def add_memory(self, content: str, headers: dict | None = None,
                   tags: list[str] | str | None = None,
                   priority: str = "medium") -> dict:
        """Propose a memory to the chain (reference :158-219)."""
        if isinstance(tags, str):
            tags = [t.strip() for t in tags.split(",") if t.strip()]
        hdrs = dict(headers or {})
        hdrs.setdefault("Subject", content.splitlines()[0][:80] if content else "")
        if tags:
            hdrs["Tags"] = ",".join(tags)
        hdrs.setdefault("Priority", priority)
        memory_data = {
            "memory_id": uuid.uuid4().hex[:8],
            "headers": hdrs,
            "content": content,
            "tags": tags or [],  # chain.stats() histograms read this field
        }
        out = self._request("POST", "/memorychain/propose",
                            body={"memory_data": memory_data})
        return out.get("block", out)

    def get_chain(self) -> list[dict]:
        return self._request("GET", "/memorychain/chain").get("chain", [])

    def validate_chain(self) -> bool:
        """Use the node's verdict when present; otherwise validate the fetched
        chain locally (reference :543-576)."""
        out = self._request("GET", "/memorychain/chain")
        if "valid" in out:
            return bool(out["valid"])
        from fei_tpu.memory.memorychain.chain import validate_block_dicts

        return validate_block_dicts(out.get("chain", []))

    # ------------------------------------------------- client-side search
    @staticmethod
    def _block_memory(block: dict) -> dict:
        data = block.get("memory_data") or {}
        return {
            "block_index": block.get("index"),
            "memory_id": data.get("memory_id", ""),
            "headers": data.get("headers", {}),
            "content": data.get("content", ""),
            "responsible_node": block.get("responsible_node"),
            "timestamp": block.get("timestamp"),
        }

    def search_memories(self, query: str, limit: int = 20) -> list[dict]:
        """Substring search over headers+content of the fetched chain
        (reference :273-324 — search is client-side by design)."""
        needle = query.lower()
        hits = []
        for block in self.get_chain():
            mem = self._block_memory(block)
            if not mem["memory_id"]:
                continue
            haystack = (mem["content"] + " " +
                        " ".join(str(v) for v in mem["headers"].values())).lower()
            if needle in haystack:
                hits.append(mem)
            if len(hits) >= limit:
                break
        return hits

    def search_by_tag(self, tag: str, limit: int = 20) -> list[dict]:
        tag = tag.lstrip("#").lower()
        hits = []
        for block in self.get_chain():
            mem = self._block_memory(block)
            tags = [t.strip().lower()
                    for t in str(mem["headers"].get("Tags", "")).split(",")]
            if tag in tags:
                hits.append(mem)
            if len(hits) >= limit:
                break
        return hits

    def get_memory(self, memory_id: str) -> dict | None:
        for block in self.get_chain():
            mem = self._block_memory(block)
            if mem["memory_id"] == memory_id:
                return mem
        return None

    def get_chain_stats(self) -> dict:
        """Node-side stats when available, else the same-shaped histograms
        computed from the fetched chain (reference :396-447). Both paths
        return {length, tags, tasks, responsible, valid}."""
        try:
            return self._request("GET", "/memorychain/stats")
        except MemoryError_:
            pass
        chain = self.get_chain()
        tags: dict[str, int] = {}
        states: dict[str, int] = {}
        nodes: dict[str, int] = {}
        for block in chain[1:]:  # skip genesis, as chain.stats() does
            for t in (block.get("memory_data") or {}).get("tags", []):
                tags[t] = tags.get(t, 0) + 1
            if block.get("is_task"):
                state = block.get("task_state", "")
                states[state] = states.get(state, 0) + 1
            rn = block.get("responsible_node")
            if rn:
                nodes[rn] = nodes.get(rn, 0) + 1
        from fei_tpu.memory.memorychain.chain import validate_block_dicts

        return {
            "length": len(chain),
            "tags": tags,
            "tasks": states,
            "responsible": nodes,
            "valid": validate_block_dicts(chain),
        }

    # ----------------------------------------------------- #mem references
    @staticmethod
    def extract_references(text: str) -> list[str]:
        return MEM_REF_RE.findall(text or "")

    def resolve_references(self, text: str) -> dict[str, dict | None]:
        refs = self.extract_references(text)
        if not refs:
            return {}
        by_id = {}  # one chain fetch for all references
        for block in self.get_chain():
            mem = self._block_memory(block)
            if mem["memory_id"]:
                by_id[mem["memory_id"]] = mem
        return {mid: by_id.get(mid) for mid in refs}

    # ---------------------------------------------------------------- tasks
    def propose_task(self, description: str, difficulty: int = 1,
                     metadata: dict | None = None) -> dict:
        out = self._request("POST", "/memorychain/propose_task", body={
            "description": description, "difficulty": difficulty,
            "metadata": metadata or {},
        })
        return out.get("block", out)

    def list_tasks(self, state: str | None = None) -> list[dict]:
        params = {"state": state} if state else None
        return self._request("GET", "/memorychain/tasks",
                             params=params).get("tasks", [])

    def get_task(self, task_id: str) -> dict:
        return self._request("GET", f"/memorychain/tasks/{task_id}").get("task", {})

    def claim_task(self, task_id: str, node_id: str | None = None) -> bool:
        out = self._request("POST", "/memorychain/claim_task",
                            body={"task_id": task_id, "node_id": node_id})
        return bool(out.get("claimed"))

    def submit_solution(self, task_id: str, solution: str,
                        node_id: str | None = None) -> dict:
        out = self._request("POST", "/memorychain/submit_solution", body={
            "task_id": task_id, "solution": solution, "node_id": node_id,
        })
        return out.get("solution", out)

    def vote_solution(self, task_id: str, solution_id: str, approve: bool,
                      voter: str | None = None) -> str:
        out = self._request("POST", "/memorychain/vote_solution", body={
            "task_id": task_id, "solution_id": solution_id,
            "approve": approve, "voter": voter,
        })
        return out.get("task_state", "")

    def vote_difficulty(self, task_id: str, difficulty: int,
                        voter: str | None = None) -> dict:
        return self._request("POST", "/memorychain/vote_difficulty", body={
            "task_id": task_id, "difficulty": difficulty, "voter": voter,
        })

    # --------------------------------------------------------------- wallet
    def wallet_balance(self, node_id: str) -> float:
        quoted = urllib.parse.quote(node_id, safe="")
        return float(self._request(
            "GET", f"/memorychain/wallet/{quoted}").get("balance", 0.0))

    def wallet_transactions(self, node_id: str) -> list[dict]:
        quoted = urllib.parse.quote(node_id, safe="")
        return self._request(
            "GET", f"/memorychain/wallet/{quoted}/transactions"
        ).get("transactions", [])


def add_memory_from_conversation(
    connector: MemorychainConnector,
    messages: list[dict],
    tags: list[str] | None = None,
    max_chars: int = 4000,
) -> dict:
    """Condense a conversation into one chain memory
    (reference memorychain_connector.py:592-643)."""
    lines = []
    for msg in messages:
        role = msg.get("role", "user")
        content = msg.get("content", "")
        if isinstance(content, list):  # anthropic-style content blocks
            content = " ".join(
                b.get("text", "") for b in content if isinstance(b, dict)
            )
        if content:
            lines.append(f"{role}: {content}")
    body = "\n".join(lines)[:max_chars]
    subject = next((ln for ln in lines if ln.startswith("user:")), lines[0] if lines else "conversation")
    return connector.add_memory(
        body,
        headers={"Subject": subject[:80], "Source": "conversation"},
        tags=(tags or []) + ["conversation"],
    )
