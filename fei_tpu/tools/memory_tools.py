"""Agent-facing memory tools bridging the tool registry to Memdir (+chain).

Capability parity with the reference (fei/tools/memory_tools.py:23-812): the
8 registered tools — memory_search / memory_create / memory_view /
memory_list / memory_delete / memory_search_by_tag plus server start/stop
(+status) — each a JSON-schema definition and a handler over a
MemdirConnector, and the ``MemoryManager`` that fans a query out over both
stores (Memdir + Memorychain) and merges results.

Handlers return ``{"error": ...}`` payloads instead of raising, matching the
registry's contract that tool failures go back into the conversation
(reference fei/tools/registry.py:290-297).
"""

from __future__ import annotations

from fei_tpu.tools.memdir_connector import MemdirConnector
from fei_tpu.tools.memorychain_connector import MemorychainConnector
from fei_tpu.utils.errors import MemoryError_
from fei_tpu.utils.logging import get_logger

log = get_logger("tools.memory")

# --------------------------------------------------------------- definitions

MEMORY_SEARCH = {
    "name": "memory_search",
    "description": (
        "Search stored memories with the Memdir query language. Supports plain "
        "keywords (OR across subject+content), #tag filters, field:value, "
        "field>value with relative dates (now-7d), /regex/, sort:field, "
        "limit:N, and with_content. Example: '#python sort:date limit:5'."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "query": {"type": "string", "description": "Query string"},
            "folder": {"type": "string", "description": "Restrict to one folder"},
            "with_content": {"type": "boolean", "description": "Include memory bodies"},
            "limit": {"type": "integer", "description": "Max results"},
        },
        "required": ["query"],
    },
}

MEMORY_CREATE = {
    "name": "memory_create",
    "description": (
        "Store a new memory. Provide the content, an optional subject, "
        "comma-separated tags, target folder, and flags (S=seen, R=replied, "
        "F=flagged, P=priority)."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "content": {"type": "string", "description": "Memory body text"},
            "subject": {"type": "string", "description": "One-line subject"},
            "tags": {"type": "string", "description": "Comma-separated tags"},
            "folder": {"type": "string", "description": "Target folder ('' = inbox)"},
            "flags": {"type": "string", "description": "Flag string, e.g. 'F' or 'FP'"},
        },
        "required": ["content"],
    },
}

MEMORY_VIEW = {
    "name": "memory_view",
    "description": "View one memory (headers + full content) by its 8-hex id.",
    "input_schema": {
        "type": "object",
        "properties": {
            "memory_id": {"type": "string", "description": "Memory id (8 hex chars)"},
            "folder": {"type": "string", "description": "Folder hint"},
        },
        "required": ["memory_id"],
    },
}

MEMORY_LIST = {
    "name": "memory_list",
    "description": "List memories in a folder (default inbox) by status (new/cur).",
    "input_schema": {
        "type": "object",
        "properties": {
            "folder": {"type": "string", "description": "Folder ('' = inbox)"},
            "status": {"type": "string", "enum": ["new", "cur", "tmp"]},
            "with_content": {"type": "boolean"},
        },
    },
}

MEMORY_DELETE = {
    "name": "memory_delete",
    "description": (
        "Delete a memory by id. By default moves it to .Trash; set hard=true "
        "to remove permanently."
    ),
    "input_schema": {
        "type": "object",
        "properties": {
            "memory_id": {"type": "string"},
            "hard": {"type": "boolean", "description": "Permanently delete"},
        },
        "required": ["memory_id"],
    },
}

MEMORY_SEARCH_BY_TAG = {
    "name": "memory_search_by_tag",
    "description": "Find all memories carrying a tag (with or without leading #).",
    "input_schema": {
        "type": "object",
        "properties": {
            "tag": {"type": "string", "description": "Tag to match"},
            "limit": {"type": "integer"},
        },
        "required": ["tag"],
    },
}

MEMORY_SERVER_START = {
    "name": "memory_server_start",
    "description": "Start the Memdir memory server if it is not already running.",
    "input_schema": {"type": "object", "properties": {}},
}

MEMORY_SERVER_STOP = {
    "name": "memory_server_stop",
    "description": "Stop the Memdir memory server started by this session.",
    "input_schema": {"type": "object", "properties": {}},
}

MEMORY_SERVER_STATUS = {
    "name": "memory_server_status",
    "description": "Report whether the Memdir memory server is reachable.",
    "input_schema": {"type": "object", "properties": {}},
}

MEMORY_TOOL_DEFINITIONS = [
    MEMORY_SEARCH,
    MEMORY_CREATE,
    MEMORY_VIEW,
    MEMORY_LIST,
    MEMORY_DELETE,
    MEMORY_SEARCH_BY_TAG,
    MEMORY_SERVER_START,
    MEMORY_SERVER_STOP,
    MEMORY_SERVER_STATUS,
]


# ------------------------------------------------------------------ handlers


class MemoryToolHandlers:
    """Handlers bound to one MemdirConnector (reference memory_tools.py:146-524)."""

    def __init__(self, connector: MemdirConnector | None = None):
        self.connector = connector or MemdirConnector(auto_start=True)

    def _guard(self, fn, *args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except MemoryError_ as exc:
            return {"error": str(exc)}

    def memory_search(self, query: str, folder: str | None = None,
                      with_content: bool = False, limit: int | None = None) -> dict:
        return self._guard(self.connector.search, query, folder=folder,
                           with_content=with_content, limit=limit)

    def memory_create(self, content: str, subject: str | None = None,
                      tags: str | None = None, folder: str = "",
                      flags: str = "") -> dict:
        headers = {"Subject": subject} if subject else None
        out = self._guard(self.connector.create_memory, content,
                          headers=headers, folder=folder, flags=flags, tags=tags)
        if "error" in out:
            return out
        return {"created": out.get("id"), "folder": out.get("folder", folder)}

    def memory_view(self, memory_id: str, folder: str | None = None) -> dict:
        out = self._guard(self.connector.get_memory, memory_id, folder)
        if isinstance(out, dict) and not out.get("error") and not out:
            return {"error": f"memory {memory_id} not found"}
        return out

    def memory_list(self, folder: str = "", status: str = "new",
                    with_content: bool = False) -> dict:
        out = self._guard(self.connector.list_memories, folder, status, with_content)
        if isinstance(out, dict) and "error" in out:
            return out
        return {"memories": out, "count": len(out)}

    def memory_delete(self, memory_id: str, hard: bool = False) -> dict:
        out = self._guard(self.connector.delete_memory, memory_id, hard)
        if isinstance(out, dict) and "error" in out:
            return out
        return {"deleted": bool(out), "memory_id": memory_id, "hard": hard}

    def memory_search_by_tag(self, tag: str, limit: int | None = None) -> dict:
        # rewrite to a #tag query (reference memory_tools.py:447-458)
        tag = tag.lstrip("#")
        return self.memory_search(f"#{tag}", limit=limit)

    def memory_server_start(self) -> dict:
        if self.connector.check_connection():
            return {"running": True, "already": True}
        ok = self.connector.start_server()
        return {"running": ok}

    def memory_server_stop(self) -> dict:
        return {"stopped": self.connector.stop_server()}

    def memory_server_status(self) -> dict:
        return self.connector.server_status()


def create_memory_tools(registry, connector: MemdirConnector | None = None) -> list[str]:
    """Register the memory tool suite on ``registry``; returns the names
    (reference memory_tools.py:526-610)."""
    handlers = MemoryToolHandlers(connector)
    names = []
    for definition in MEMORY_TOOL_DEFINITIONS:
        registry.register(definition, getattr(handlers, definition["name"]))
        names.append(definition["name"])
    return names


# ------------------------------------------------------------ MemoryManager


class MemoryManager:
    """Unified view over both stores: Memdir (file store) + Memorychain
    (distributed ledger), with per-store error isolation
    (reference memory_tools.py:613-812)."""

    def __init__(self, memdir: MemdirConnector | None = None,
                 chain: MemorychainConnector | None = None):
        self.memdir = memdir or MemdirConnector(auto_start=True)
        self.chain = chain or MemorychainConnector()

    def search_all(self, query: str, limit: int = 20) -> dict:
        """Fan the query out to both stores; failures surface per-store."""
        results: dict = {"memdir": [], "memorychain": [], "errors": {}}
        try:
            results["memdir"] = self.memdir.search(
                query, with_content=True, limit=limit
            )["results"]
        except MemoryError_ as exc:
            results["errors"]["memdir"] = str(exc)
        try:
            results["memorychain"] = self.chain.search_memories(query, limit=limit)
        except MemoryError_ as exc:
            results["errors"]["memorychain"] = str(exc)
        results["count"] = len(results["memdir"]) + len(results["memorychain"])
        return results

    def save(self, content: str, tags: list[str] | str | None = None,
             replicate: bool = False, **headers) -> dict:
        """Save to Memdir; optionally also propose to the chain."""
        out: dict = {}
        mem = self.memdir.create_memory(
            content, headers=headers or None, tags=tags
        )
        out["memdir"] = mem.get("id")
        if replicate:
            try:
                block = self.chain.add_memory(content, headers=headers, tags=tags)
                out["memorychain"] = block.get("memory_id") or block.get(
                    "memory_data", {}
                ).get("memory_id")
            except MemoryError_ as exc:
                out["memorychain_error"] = str(exc)
        return out

    def status(self) -> dict:
        return {
            "memdir": self.memdir.check_connection(),
            "memorychain": self.chain.check_connection(),
        }
