"""Tool layer: registry, JSON-schema definitions, and implementations.

Capability parity with the reference's fei/tools package (SURVEY.md §2.1):
registry.py (schema validation + dispatch), definitions.py (15 tool
declarations), code.py (file/search/edit/shell machinery), handlers.py
(definition→impl wiring), repomap.py (repository mapper).
"""

from fei_tpu.tools.registry import Tool, ToolRegistry
from fei_tpu.tools.definitions import TOOL_DEFINITIONS
from fei_tpu.tools.handlers import create_code_tools

__all__ = ["Tool", "ToolRegistry", "TOOL_DEFINITIONS", "create_code_tools"]
