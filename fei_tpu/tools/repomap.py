"""Repository mapper: symbol extraction, cross-file dependency graph, ranked
token-budgeted map (capability parity: fei/tools/repomap.py:31-711).

Design differences from the reference: Python files use ``ast`` (exact), other
languages use regex definition patterns; tree-sitter is optional and not
required. Ranking is the reference's scheme (incoming + 0.5·outgoing symbol
references) which approximates PageRank at far lower cost.
"""

from __future__ import annotations

import ast
import os
import re
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from fei_tpu.utils.logging import get_logger

log = get_logger("tools.repomap")

LANGUAGE_EXTENSIONS = {
    ".py": "python",
    ".js": "javascript",
    ".jsx": "javascript",
    ".ts": "typescript",
    ".tsx": "typescript",
    ".go": "go",
    ".rs": "rust",
    ".java": "java",
    ".c": "c",
    ".h": "c",
    ".cc": "cpp",
    ".cpp": "cpp",
    ".hpp": "cpp",
    ".rb": "ruby",
    ".sh": "shell",
}

# definition-extraction regexes for non-Python languages
DEF_PATTERNS = {
    "javascript": r"^\s*(?:export\s+)?(?:async\s+)?(?:function\s+(\w+)|class\s+(\w+)|const\s+(\w+)\s*=\s*(?:async\s*)?\()",
    "typescript": r"^\s*(?:export\s+)?(?:async\s+)?(?:function\s+(\w+)|class\s+(\w+)|interface\s+(\w+)|type\s+(\w+)\s*=|const\s+(\w+)\s*=\s*(?:async\s*)?\()",
    "go": r"^\s*func\s+(?:\([^)]*\)\s*)?(\w+)|^\s*type\s+(\w+)",
    "rust": r"^\s*(?:pub\s+)?(?:fn|struct|enum|trait)\s+(\w+)",
    "java": r"^\s*(?:public|private|protected)?\s*(?:static\s+)?(?:class|interface|enum)\s+(\w+)",
    "c": r"^\w[\w\s\*]*\b(\w+)\s*\([^;]*$",
    "cpp": r"^\s*(?:class|struct)\s+(\w+)|^\w[\w\s\*:<>,]*\b(\w+)\s*\([^;]*$",
    "ruby": r"^\s*(?:def|class|module)\s+(\w+)",
    "shell": r"^\s*(?:function\s+)?(\w+)\s*\(\)",
}

DEFAULT_EXCLUDES = [
    ".git", "__pycache__", "node_modules", ".venv", "venv", "build", "dist",
    ".fei_backups", ".pytest_cache", ".mypy_cache", "*.egg-info",
]


@dataclass
class FileSymbols:
    path: str
    language: str
    symbols: list[str] = field(default_factory=list)
    loc: int = 0


def _extract_python(path: str, source: str) -> list[str]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    syms = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            syms.append(node.name)
        elif isinstance(node, ast.ClassDef):
            syms.append(node.name)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    syms.append(f"{node.name}.{sub.name}")
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id.isupper():
                    syms.append(tgt.id)
    return syms


def _extract_regex(language: str, source: str) -> list[str]:
    rx = re.compile(DEF_PATTERNS.get(language, r"$^"), re.MULTILINE)
    syms = []
    for m in rx.finditer(source):
        for g in m.groups():
            if g:
                syms.append(g)
                break
    return syms


def _scan_file(path: str) -> FileSymbols | None:
    ext = os.path.splitext(path)[1]
    language = LANGUAGE_EXTENSIONS.get(ext)
    if language is None:
        return None
    try:
        if os.path.getsize(path) > 2 * 1024 * 1024:
            return None
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            source = fh.read()
    except OSError:
        return None
    if language == "python":
        syms = _extract_python(path, source)
    else:
        syms = _extract_regex(language, source)
    return FileSymbols(path, language, syms, source.count("\n") + 1)


class RepoMapper:
    """Walk → extract symbols (parallel) → reference graph → rank → render."""

    def __init__(self, root: str, exclude: list[str] | None = None):
        self.root = os.path.realpath(root)
        self.exclude = list(DEFAULT_EXCLUDES) + list(exclude or [])

    def _walk(self) -> list[str]:
        import fnmatch

        out = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [
                d for d in dirnames
                if not any(fnmatch.fnmatch(d, pat) for pat in self.exclude)
            ]
            for fn in filenames:
                if any(fnmatch.fnmatch(fn, pat) for pat in self.exclude):
                    continue
                if os.path.splitext(fn)[1] in LANGUAGE_EXTENSIONS:
                    out.append(os.path.join(dirpath, fn))
        return sorted(out)

    def scan(self) -> list[FileSymbols]:
        files = self._walk()
        results: list[FileSymbols] = []
        with ThreadPoolExecutor(max_workers=min(8, max(1, os.cpu_count() or 4))) as pool:
            for fs in pool.map(_scan_file, files):
                if fs is not None and fs.symbols:
                    results.append(fs)
        return results

    def dependency_graph(self, scanned: list[FileSymbols]) -> dict[str, dict[str, list[str]]]:
        """edges[src][dst] = symbols defined in dst that src references."""
        # symbol → defining files (skip very short/common names)
        defs: dict[str, list[str]] = {}
        for fs in scanned:
            for sym in fs.symbols:
                base = sym.split(".")[-1]
                if len(base) < 3:
                    continue
                defs.setdefault(base, []).append(fs.path)
        sources: dict[str, str] = {}
        for fs in scanned:
            try:
                with open(fs.path, "r", encoding="utf-8", errors="replace") as fh:
                    sources[fs.path] = fh.read()
            except OSError:
                sources[fs.path] = ""
        edges: dict[str, dict[str, list[str]]] = {}
        for fs in scanned:
            src_text = sources[fs.path]
            own = set(s.split(".")[-1] for s in fs.symbols)
            for sym, defined_in in defs.items():
                if sym in own:
                    continue
                if re.search(rf"\b{re.escape(sym)}\b", src_text):
                    for dst in defined_in:
                        if dst != fs.path:
                            edges.setdefault(fs.path, {}).setdefault(dst, []).append(sym)
        return edges

    def rank(self, scanned: list[FileSymbols],
             edges: dict[str, dict[str, list[str]]]) -> dict[str, float]:
        incoming: dict[str, int] = {fs.path: 0 for fs in scanned}
        outgoing: dict[str, int] = {fs.path: 0 for fs in scanned}
        for src, dsts in edges.items():
            outgoing[src] = outgoing.get(src, 0) + len(dsts)
            for dst in dsts:
                incoming[dst] = incoming.get(dst, 0) + 1
        return {p: incoming.get(p, 0) + 0.5 * outgoing.get(p, 0) for p in incoming}

    def generate_map(self, token_budget: int = 1024) -> dict:
        scanned = self.scan()
        edges = self.dependency_graph(scanned)
        ranks = self.rank(scanned, edges)
        ordered = sorted(scanned, key=lambda fs: -ranks.get(fs.path, 0.0))
        lines: list[str] = []
        used = 0
        shown = 0
        for fs in ordered:
            rel = os.path.relpath(fs.path, self.root)
            chunk = [f"{rel}  (rank {ranks.get(fs.path, 0):.1f}, {fs.loc} loc)"]
            for sym in fs.symbols[:24]:
                chunk.append(f"  {sym}")
            cost = sum(_token_estimate(ln) for ln in chunk)
            if used + cost > token_budget and shown > 0:
                break
            lines.extend(chunk)
            used += cost
            shown += 1
        return {
            "root": self.root,
            "map": "\n".join(lines),
            "files_total": len(scanned),
            "files_shown": shown,
            "token_estimate": used,
        }

    def generate_json(self) -> dict:
        scanned = self.scan()
        edges = self.dependency_graph(scanned)
        ranks = self.rank(scanned, edges)
        return {
            "root": self.root,
            "files": [
                {
                    "path": os.path.relpath(fs.path, self.root),
                    "language": fs.language,
                    "symbols": fs.symbols,
                    "loc": fs.loc,
                    "rank": ranks.get(fs.path, 0.0),
                }
                for fs in scanned
            ],
            "edges": [
                {
                    "from": os.path.relpath(src, self.root),
                    "to": os.path.relpath(dst, self.root),
                    "symbols": sorted(set(syms)),
                }
                for src, dsts in edges.items()
                for dst, syms in dsts.items()
            ],
        }


def _token_estimate(text: str) -> int:
    return max(1, int(len(text.split()) * 1.3))


def generate_repo_map(path: str, token_budget: int = 1024,
                      exclude: list[str] | None = None) -> dict:
    return RepoMapper(path, exclude=exclude).generate_map(token_budget)


def generate_repo_summary(path: str) -> dict:
    mapper = RepoMapper(path)
    scanned = mapper.scan()
    modules: dict[str, dict] = {}
    for fs in scanned:
        rel = os.path.relpath(fs.path, mapper.root)
        mod = rel.split(os.sep)[0] if os.sep in rel else "."
        entry = modules.setdefault(
            mod, {"files": 0, "loc": 0, "languages": set(), "top_symbols": []}
        )
        entry["files"] += 1
        entry["loc"] += fs.loc
        entry["languages"].add(fs.language)
        entry["top_symbols"].extend(fs.symbols[:3])
    return {
        "root": mapper.root,
        "modules": {
            mod: {
                "files": e["files"],
                "loc": e["loc"],
                "languages": sorted(e["languages"]),
                "top_symbols": e["top_symbols"][:12],
            }
            for mod, e in sorted(modules.items())
        },
    }


def generate_repo_dependencies(path: str, file: str | None = None) -> dict:
    mapper = RepoMapper(path)
    data = mapper.generate_json()
    edges = data["edges"]
    if file:
        rel = os.path.relpath(os.path.realpath(file), mapper.root)
        edges = [e for e in edges if e["from"] == rel or e["to"] == rel]
    return {"root": data["root"], "edges": edges, "count": len(edges)}
