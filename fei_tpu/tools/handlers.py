"""Handlers mapping each tool definition to its implementation, plus
``create_code_tools(registry)`` which registers all 14 local tools
(parity: fei/tools/handlers.py:49-590, code.py:1727-1866).
"""

from __future__ import annotations

import os
import re
from concurrent.futures import ThreadPoolExecutor

from fei_tpu.tools import code as _code
from fei_tpu.tools import definitions as defs
from fei_tpu.utils.logging import get_logger

log = get_logger("tools.handlers")


def glob_tool_handler(pattern: str, path: str | None = None) -> dict:
    files = _code.glob_finder.find(pattern, path)
    return {"pattern": pattern, "files": files, "count": len(files)}


def grep_tool_handler(pattern: str, path: str | None = None, include: str | None = None) -> dict:
    matches = _code.grep_tool.search(pattern, path, include)
    return {
        "pattern": pattern,
        "matches": [
            {"file": m.file, "line_number": m.line_number, "line": m.line} for m in matches
        ],
        "count": len(matches),
    }


def view_handler(file_path: str, offset: int = 0, limit: int | None = None) -> dict:
    return _code.file_viewer.view(file_path, offset=offset, limit=limit)


def edit_handler(file_path: str, old_string: str, new_string: str) -> dict:
    return _code.code_editor.edit_file(file_path, old_string, new_string)


def replace_handler(file_path: str, content: str) -> dict:
    return _code.code_editor.replace_file(file_path, content)


def ls_handler(path: str, ignore: list[str] | None = None) -> dict:
    return _code.directory_explorer.list_directory(path, ignore=ignore)


def regex_edit_handler(
    file_path: str, pattern: str, replacement: str, validate: bool = True
) -> dict:
    return _code.code_editor.regex_replace(file_path, pattern, replacement, validate)


def batch_glob_handler(patterns: list[str], path: str | None = None) -> dict:
    results: dict[str, list[str]] = {}
    with ThreadPoolExecutor(max_workers=min(5, max(1, len(patterns)))) as pool:
        futures = {pool.submit(_code.glob_finder.find, p, path): p for p in patterns}
        for fut, pat in futures.items():
            try:
                results[pat] = fut.result()
            except Exception as exc:  # noqa: BLE001
                results[pat] = []
                log.warning("batch glob %s failed: %s", pat, exc)
    return {"results": results, "total": sum(len(v) for v in results.values())}


def find_in_files_handler(files: list[str], pattern: str) -> dict:
    rx = re.compile(pattern)
    by_file: dict[str, list[dict]] = {}
    for f in files:
        matches = _code.grep_tool._search_file(f, rx, 1000)
        if matches:
            by_file[f] = [{"line_number": m.line_number, "line": m.line} for m in matches]
    return {"pattern": pattern, "files": by_file, "count": sum(len(v) for v in by_file.values())}


# language hint → (globs, definition-pattern template)
_LANGUAGE_MAP = {
    "python": ("*.py", r"(def|class)\s+{sym}\b"),
    "javascript": ("*.{js,jsx}", r"(function\s+{sym}\b|const\s+{sym}\s*=|class\s+{sym}\b)"),
    "typescript": ("*.{ts,tsx}", r"(function\s+{sym}\b|const\s+{sym}\s*=|class\s+{sym}\b|interface\s+{sym}\b)"),
    "go": ("*.go", r"func\s+(\([^)]*\)\s*)?{sym}\b|type\s+{sym}\b"),
    "rust": ("*.rs", r"(fn|struct|enum|trait)\s+{sym}\b"),
    "java": ("*.java", r"(class|interface|enum)\s+{sym}\b|\w+\s+{sym}\s*\("),
    "c": ("*.{c,h}", r"\b{sym}\s*\("),
    "cpp": ("*.{cc,cpp,cxx,h,hpp}", r"\b{sym}\s*\(|class\s+{sym}\b"),
    "ruby": ("*.rb", r"(def|class|module)\s+{sym}\b"),
    "shell": ("*.sh", r"{sym}\s*\(\)"),
}


def smart_search_handler(query: str, context: str | None = None) -> dict:
    """Parse 'function foo in python'-style queries into glob+regex searches."""
    q = query.strip()
    language = None
    for lang in _LANGUAGE_MAP:
        if re.search(rf"\bin\s+{lang}\b|\b{lang}\b", q, re.IGNORECASE):
            language = lang
            q = re.sub(rf"\bin\s+{lang}\b|\b{lang}\b", "", q, flags=re.IGNORECASE).strip()
            break
    kind = None
    m = re.match(r"^(function|class|method|def|symbol|variable)\s+(.*)$", q, re.IGNORECASE)
    if m:
        kind, q = m.group(1).lower(), m.group(2).strip()
    symbol = re.split(r"\s+", q)[0] if q else ""
    if not symbol:
        return {"query": query, "matches": [], "count": 0, "note": "no symbol in query"}
    include, pat_tpl = _LANGUAGE_MAP.get(language, ("*", r"\b{sym}\b"))
    pattern = pat_tpl.format(sym=re.escape(symbol))
    matches = _code.grep_tool.search(pattern, include=include, max_results=200)
    return {
        "query": query,
        "language": language,
        "kind": kind,
        "symbol": symbol,
        "matches": [
            {"file": m.file, "line_number": m.line_number, "line": m.line} for m in matches
        ],
        "count": len(matches),
    }


def repo_map_handler(path: str | None = None, token_budget: int = 1024,
                     exclude: list[str] | None = None) -> dict:
    from fei_tpu.tools.repomap import generate_repo_map

    return generate_repo_map(path or os.getcwd(), token_budget=token_budget, exclude=exclude)


def repo_summary_handler(path: str | None = None) -> dict:
    from fei_tpu.tools.repomap import generate_repo_summary

    return generate_repo_summary(path or os.getcwd())


def repo_deps_handler(path: str | None = None, file: str | None = None) -> dict:
    from fei_tpu.tools.repomap import generate_repo_dependencies

    return generate_repo_dependencies(path or os.getcwd(), file=file)


def shell_handler(command: str, timeout: int = 60, background: bool = False,
                  cwd: str | None = None) -> dict:
    return _code.shell_runner.run(command, timeout=timeout, background=background, cwd=cwd)


_HANDLERS = {
    "GlobTool": glob_tool_handler,
    "GrepTool": grep_tool_handler,
    "View": view_handler,
    "Edit": edit_handler,
    "Replace": replace_handler,
    "LS": ls_handler,
    "RegexEdit": regex_edit_handler,
    "BatchGlob": batch_glob_handler,
    "FindInFiles": find_in_files_handler,
    "SmartSearch": smart_search_handler,
    "RepoMap": repo_map_handler,
    "RepoSummary": repo_summary_handler,
    "RepoDependencies": repo_deps_handler,
    "Shell": shell_handler,
}


def create_code_tools(registry) -> list[str]:
    """Register all local code tools on ``registry``; returns the names."""
    names = []
    for definition in defs.TOOL_DEFINITIONS:
        handler = _HANDLERS[definition["name"]]
        registry.register(definition, handler)
        names.append(definition["name"])
    return names
