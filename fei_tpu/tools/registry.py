"""Tool registry: name→tool map, JSON-schema argument validation, dispatch.

Capability parity with the reference's ToolRegistry (fei/tools/registry.py:49-607):
registration, schema validation, sync/async handler dispatch, MCP passthrough
tools, and reflection-based registration of class methods. Differences by
design: validation errors raise typed ToolValidationError (the reference
returns ad-hoc dicts), async handlers run on the caller's loop via
``asyncio.run`` in a worker thread only when no loop is available (the
reference spawns a nested event loop per call — a known race, FLAWS.md).
"""

from __future__ import annotations

import asyncio
import inspect
import re
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable

from fei_tpu.utils.errors import ToolError, ToolNotFoundError, ToolValidationError
from fei_tpu.utils.logging import get_logger
from fei_tpu.utils.metrics import METRICS

log = get_logger("tools.registry")

_JSON_TYPES = {
    "string": str,
    "integer": int,
    "number": (int, float),
    "boolean": bool,
    "array": list,
    "object": dict,
    "null": type(None),
}


def validate_schema(args: dict, schema: dict, path: str = "") -> list[str]:
    """Validate ``args`` against a (subset of) JSON schema; return error strings.

    Supports: type, required, properties, items, enum, minimum/maximum,
    minLength/maxLength, pattern, additionalProperties. Mirrors the checks the
    reference does in Tool.validate_arguments (fei/tools/registry.py:92-153).
    """
    errors: list[str] = []
    typ = schema.get("type")
    if typ:
        expected = _JSON_TYPES.get(typ)
        if expected is not None and not isinstance(args, expected):
            # JSON has no int/float distinction for "number"; bools are not ints
            if not (typ == "number" and isinstance(args, (int, float))):
                errors.append(f"{path or 'value'}: expected {typ}, got {type(args).__name__}")
                return errors
        if typ == "integer" and isinstance(args, bool):
            errors.append(f"{path or 'value'}: expected integer, got bool")
            return errors
    if "enum" in schema and args not in schema["enum"]:
        errors.append(f"{path or 'value'}: {args!r} not one of {schema['enum']}")
    if isinstance(args, str):
        if "minLength" in schema and len(args) < schema["minLength"]:
            errors.append(f"{path}: shorter than minLength {schema['minLength']}")
        if "maxLength" in schema and len(args) > schema["maxLength"]:
            errors.append(f"{path}: longer than maxLength {schema['maxLength']}")
        if "pattern" in schema and not re.search(schema["pattern"], args):
            errors.append(f"{path}: does not match pattern {schema['pattern']!r}")
    if isinstance(args, (int, float)) and not isinstance(args, bool):
        if "minimum" in schema and args < schema["minimum"]:
            errors.append(f"{path}: {args} < minimum {schema['minimum']}")
        if "maximum" in schema and args > schema["maximum"]:
            errors.append(f"{path}: {args} > maximum {schema['maximum']}")
    if isinstance(args, dict):
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in args:
                errors.append(f"{path or 'object'}: missing required property {req!r}")
        for key, val in args.items():
            if key in props:
                errors.extend(validate_schema(val, props[key], f"{path}.{key}" if path else key))
            elif schema.get("additionalProperties") is False:
                errors.append(f"{path or 'object'}: unexpected property {key!r}")
    if isinstance(args, list) and "items" in schema:
        for i, item in enumerate(args):
            errors.extend(validate_schema(item, schema["items"], f"{path}[{i}]"))
    return errors


@dataclass
class Tool:
    """A registered tool: JSON-schema declaration + Python handler."""

    name: str
    description: str
    input_schema: dict
    handler: Callable[..., Any]
    tags: tuple[str, ...] = ()

    def validate_arguments(self, args: dict) -> list[str]:
        return validate_schema(args, self.input_schema)

    def to_schema(self) -> dict:
        """Anthropic-style tool declaration (name/description/input_schema)."""
        return {
            "name": self.name,
            "description": self.description,
            "input_schema": self.input_schema,
        }

    def to_openai_schema(self) -> dict:
        return {
            "type": "function",
            "function": {
                "name": self.name,
                "description": self.description,
                "parameters": self.input_schema,
            },
        }


class ToolRegistry:
    """Thread-safe name→Tool map with validated dispatch.

    Parity with fei/tools/registry.py:156-607; MCP tools are handled by a
    pluggable ``mcp_dispatcher`` rather than a hardcoded special case.
    """

    def __init__(self, max_workers: int = 10):
        self._tools: dict[str, Tool] = {}
        self._lock = threading.RLock()
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="tool")
        self.mcp_dispatcher: Callable[[str, dict], Any] | None = None

    def register_tool(
        self,
        name: str,
        description: str,
        input_schema: dict,
        handler: Callable[..., Any],
        tags: tuple[str, ...] = (),
    ) -> Tool:
        tool = Tool(name, description, input_schema, handler, tags)
        with self._lock:
            if name in self._tools:
                log.debug("re-registering tool %s", name)
            self._tools[name] = tool
        return tool

    def register(self, definition: dict, handler: Callable[..., Any]) -> Tool:
        """Register from a definitions.py-style dict."""
        return self.register_tool(
            definition["name"], definition["description"], definition["input_schema"], handler
        )

    def unregister(self, name: str) -> bool:
        with self._lock:
            return self._tools.pop(name, None) is not None

    def get_tool(self, name: str) -> Tool | None:
        with self._lock:
            return self._tools.get(name)

    def list_tools(self) -> list[str]:
        with self._lock:
            return sorted(self._tools)

    def get_schemas(self, format: str = "anthropic") -> list[dict]:
        with self._lock:
            tools = list(self._tools.values())
        if format == "openai":
            return [t.to_openai_schema() for t in tools]
        return [t.to_schema() for t in tools]

    # -- dispatch ------------------------------------------------------------

    def execute_tool(self, name: str, args: dict | None = None) -> Any:
        """Validate and run a tool; tool errors come back as {"error": ...}.

        Mirrors the reference contract (fei/tools/registry.py:250-297): errors
        during *execution* are returned as error payloads (so the agent loop
        can feed them back to the model), while unknown tools and invalid
        arguments raise typed errors.
        """
        args = args or {}
        if name.startswith("mcp_") and self.mcp_dispatcher is not None and name not in self._tools:
            return self.mcp_dispatcher(name, args)
        tool = self.get_tool(name)
        if tool is None:
            raise ToolNotFoundError(f"unknown tool: {name}")
        errors = tool.validate_arguments(args)
        if errors:
            raise ToolValidationError(f"invalid arguments for {name}: " + "; ".join(errors))
        METRICS.incr("tool.calls")
        with METRICS.span(f"tool.{name}"):
            try:
                result = tool.handler(**args)
                if inspect.iscoroutine(result):
                    result = self._run_coroutine(result)
                return result
            except ToolError:
                METRICS.incr("tool.errors")
                raise
            except Exception as exc:  # noqa: BLE001 — surfaced to the model
                METRICS.incr("tool.errors")
                log.warning("tool %s failed: %s", name, exc)
                return {"error": f"{type(exc).__name__}: {exc}"}

    async def execute_tool_async(self, name: str, args: dict | None = None) -> Any:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._pool, self.execute_tool, name, args)

    def _run_coroutine(self, coro) -> Any:
        """Run a coroutine from sync context without nesting event loops."""
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return asyncio.run(coro)
        # Called from inside a loop: run in a dedicated thread's fresh loop.
        fut = self._pool.submit(asyncio.run, coro)
        return fut.result()

    # -- reflection ----------------------------------------------------------

    def register_class_methods(
        self, instance: Any, prefix: str = "", include: list[str] | None = None
    ) -> list[str]:
        """Register public methods of ``instance`` as tools, deriving a JSON
        schema from each signature (parity: fei/tools/registry.py:503-584)."""
        registered = []
        for attr in dir(instance):
            if attr.startswith("_"):
                continue
            if include is not None and attr not in include:
                continue
            fn = getattr(instance, attr)
            if not callable(fn):
                continue
            name = f"{prefix}{attr}"
            self.register_tool(name, inspect.getdoc(fn) or name, _signature_schema(fn), fn)
            registered.append(name)
        return registered


def _signature_schema(fn: Callable) -> dict:
    """Derive a JSON schema from a function signature's annotations."""
    py_to_json = {str: "string", int: "integer", float: "number", bool: "boolean",
                  list: "array", dict: "object"}
    props: dict[str, dict] = {}
    required: list[str] = []
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return {"type": "object", "properties": {}}
    for pname, param in sig.parameters.items():
        if pname in ("self", "cls") or param.kind in (
            inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD
        ):
            continue
        ann = param.annotation
        jtype = py_to_json.get(ann, "string")
        props[pname] = {"type": jtype}
        if param.default is inspect.Parameter.empty:
            required.append(pname)
    schema: dict = {"type": "object", "properties": props}
    if required:
        schema["required"] = required
    return schema
