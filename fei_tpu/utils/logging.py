"""Per-module loggers with env/config-driven level/file and rotation.

Parity with reference fei/utils/logging.py:12-118 (setup_logging, get_logger,
env-driven level/file, 10 MB x 5 rotation). Level/file resolution order:
explicit argument > ``FEI_TPU_LOG_LEVEL``/``FEI_TPU_LOG_FILE`` env > the
``[log]`` section of the layered Config > WARNING.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
import threading

_LOCK = threading.Lock()
_CONFIGURED = False

_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"
_MAX_BYTES = 10 * 1024 * 1024
_BACKUP_COUNT = 5


def _resolve(option: str) -> str | None:
    env = os.environ.get(f"FEI_TPU_LOG_{option.upper()}") or os.environ.get(
        f"FEI_LOG_{option.upper()}"
    )
    if env:
        return env
    try:
        from fei_tpu.utils.config import get_config

        return get_config().get("log", option)
    except Exception:
        return None


def setup_logging(
    level: int | str | None = None,
    log_file: str | None = None,
    stream=None,
) -> logging.Logger:
    """Configure the root 'fei_tpu' logger. Safe to call more than once."""
    global _CONFIGURED
    root = logging.getLogger("fei_tpu")
    with _LOCK:
        if level is None:
            level = _resolve("level") or "WARNING"
        if isinstance(level, str):
            level = getattr(logging, level.upper(), logging.WARNING)
        root.setLevel(level)
        log_file = log_file or _resolve("file")
        root.handlers.clear()
        handler: logging.Handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(handler)
        if log_file:
            os.makedirs(os.path.dirname(os.path.abspath(log_file)), exist_ok=True)
            fh = logging.handlers.RotatingFileHandler(
                log_file, maxBytes=_MAX_BYTES, backupCount=_BACKUP_COUNT
            )
            fh.setFormatter(logging.Formatter(_FORMAT))
            root.addHandler(fh)
        root.propagate = False
        _CONFIGURED = True
    return root


def get_logger(name: str) -> logging.Logger:
    """Child logger under the 'fei_tpu' root (stdlib loggers are already
    process-wide singletons; no extra cache needed)."""
    if not name.startswith("fei_tpu"):
        name = f"fei_tpu.{name}"
    with _LOCK:
        configured = _CONFIGURED
    if not configured:
        setup_logging()
    return logging.getLogger(name)
