"""Typed error hierarchy for fei_tpu.

The reference maps transport errors ad hoc (fei/core/assistant.py:543-554 maps
LiteLLM exceptions to strings); here every subsystem raises a typed subclass of
FeiError so callers can catch at the right granularity.
"""

from __future__ import annotations


class FeiError(Exception):
    """Base class for all fei_tpu errors."""

    def __init__(self, message: str, *, cause: Exception | None = None):
        super().__init__(message)
        self.message = message
        self.cause = cause


class ConfigError(FeiError):
    """Invalid or missing configuration."""


class ProviderError(FeiError):
    """LLM provider failure (local engine or remote API)."""


class AuthenticationError(ProviderError):
    """Missing or rejected credentials for a remote provider."""


class RateLimitError(ProviderError):
    """Remote provider rate limit."""


class EngineError(FeiError):
    """TPU inference engine failure (compile, OOM, shape mismatch)."""


class CheckpointError(EngineError):
    """Weight loading / checkpoint save-restore failure."""


class ToolError(FeiError):
    """Tool registration, validation, or execution failure."""


class ToolNotFoundError(ToolError):
    pass


class ToolValidationError(ToolError):
    """Arguments failed JSON-schema validation."""


class MemoryError_(FeiError):
    """Memdir / Memorychain subsystem failure (trailing underscore avoids
    shadowing the builtin)."""


class ConnectionError_(MemoryError_):
    """A memory service endpoint is unreachable (trailing underscore avoids
    shadowing the builtin)."""


class MCPError(FeiError):
    """MCP client/service failure."""


class TaskExecutionError(FeiError):
    """Continuous task executor failure."""
