"""Typed error hierarchy for fei_tpu.

The reference maps transport errors ad hoc (fei/core/assistant.py:543-554 maps
LiteLLM exceptions to strings); here every subsystem raises a typed subclass of
FeiError so callers can catch at the right granularity.
"""

from __future__ import annotations


class FeiError(Exception):
    """Base class for all fei_tpu errors."""

    def __init__(self, message: str, *, cause: Exception | None = None):
        super().__init__(message)
        self.message = message
        self.cause = cause


class ConfigError(FeiError):
    """Invalid or missing configuration."""


class ProviderError(FeiError):
    """LLM provider failure (local engine or remote API)."""


class AuthenticationError(ProviderError):
    """Missing or rejected credentials for a remote provider."""


class RateLimitError(ProviderError):
    """Remote provider rate limit."""


class EngineError(FeiError):
    """TPU inference engine failure (compile, OOM, shape mismatch)."""


class RequestError(EngineError):
    """Host-side failure scoped to ONE request (bad grammar table,
    tokenizer edge case, a user callback that raised). The scheduler
    fails only the offending sequence — its slot evicts through the
    healthy-pool path and every other stream keeps decoding."""


class DeviceError(EngineError):
    """Device-scoped failure: the donated KV pool must be presumed
    consumed (mid-execution dispatch failure). Routes to the
    scheduler's ``_fail_all`` — pool dropped and rebuilt on the next
    admission; every in-flight request fails."""


class QueueFullError(RequestError):
    """Backpressure: the scheduler's waiting queue is at
    ``FEI_TPU_MAX_QUEUE``. The server maps this to HTTP 429 with a
    ``Retry-After`` hint (``retry_after_s``)."""

    def __init__(self, message: str, *, retry_after_s: float = 1.0,
                 cause: Exception | None = None):
        super().__init__(message, cause=cause)
        self.retry_after_s = retry_after_s


class EngineDegradedError(EngineError):
    """The crash-loop breaker tripped: N device failures inside the
    breaker window. New submits are rejected (HTTP 503 with
    ``Retry-After``) until the cooldown elapses or the operator calls
    ``scheduler.reset_degraded()`` — rebuilding the pool on every
    doomed request would just thrash HBM."""

    def __init__(self, message: str, *, retry_after_s: float = 1.0,
                 cause: Exception | None = None):
        super().__init__(message, cause=cause)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(RequestError):
    """The request's deadline expired — shed at admission (queue wait
    alone blew the budget) or cancelled mid-decode at delivery."""


class PoolPressure(EngineError):
    """The paged KV pool could not satisfy a page allocation on the
    scheduler path. NOT a request failure: the scheduler treats pressure
    as a scheduling event (evict prefix-cache entries, preempt a victim
    sequence, retry) — only a request whose worst case can never fit the
    pool fails, and that is rejected typed at submit()."""


class EngineDrainingError(EngineError):
    """The engine is draining (SIGTERM / POST /drain): new submits are
    rejected (HTTP 503 + ``Retry-After``) while in-flight requests finish
    within ``FEI_TPU_DRAIN_DEADLINE_S``; still-queued requests snapshot
    to disk for warm restart."""

    def __init__(self, message: str, *, retry_after_s: float = 5.0,
                 cause: Exception | None = None):
        super().__init__(message, cause=cause)
        self.retry_after_s = retry_after_s


class CheckpointError(EngineError):
    """Weight loading / checkpoint save-restore failure."""


class KVTierError(EngineError):
    """Tiered KV store failure (missing/corrupt/mismatched page entry,
    tier I/O error, incompatible migration blob). Always recoverable at
    the scheduler: a failed fetch falls back to token replay and a failed
    spill just forfeits the fast-resume path — neither may wedge a slot."""


class KVGeometryError(KVTierError):
    """The blob's INVARIANT pool geometry (layers, total kv heads,
    page_size, head_dim, dtype, quantized) can never scatter into this
    pool — a different model, dtype, or page size, not a different mesh.
    Never retryable: the server maps this to HTTP 409 with the
    ``{ours, theirs}`` diff so the router stops re-offering the blob,
    unlike a corrupt/truncated 422 it may refetch elsewhere. A mere tp
    *layout* skew is NOT this error — layout resheds on scatter."""

    def __init__(self, message: str, *, ours: dict | None = None,
                 theirs: dict | None = None,
                 cause: Exception | None = None):
        super().__init__(message, cause=cause)
        self.ours = dict(ours or {})
        self.theirs = dict(theirs or {})


class PageSizeMismatchError(CheckpointError):
    """A durable artifact (drain snapshot, journal session) was produced
    under a different KV page_size than this engine serves. Page size
    changes the paged kernel's summation order, so a cross-page_size
    replay cannot promise byte-identity — the ONE geometry axis warm
    restart still refuses (mesh shape resheds/replays freely)."""

    def __init__(self, message: str, *, ours: int | None = None,
                 theirs: int | None = None,
                 cause: Exception | None = None):
        super().__init__(message, cause=cause)
        self.ours = ours
        self.theirs = theirs


class ToolError(FeiError):
    """Tool registration, validation, or execution failure."""


class ToolNotFoundError(ToolError):
    pass


class ToolValidationError(ToolError):
    """Arguments failed JSON-schema validation."""


class MemoryError_(FeiError):
    """Memdir / Memorychain subsystem failure (trailing underscore avoids
    shadowing the builtin)."""


class ConnectionError_(MemoryError_):
    """A memory service endpoint is unreachable (trailing underscore avoids
    shadowing the builtin)."""


class MCPError(FeiError):
    """MCP client/service failure."""


class TaskExecutionError(FeiError):
    """Continuous task executor failure."""
