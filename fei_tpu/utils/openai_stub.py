"""Loopback OpenAI-compatible ``/chat/completions`` stub server.

One implementation shared by the client-path benchmark (bench.py remote
suite) and the RemoteProvider tests, so the canned protocol cannot drift
between what the bench measures and what the tests pin. Also handy for
driving the agent stack against a fake remote endpoint in demos.
"""

from __future__ import annotations

import http.server
import json
import threading
from typing import Callable


def serve_openai_stub(
    responder: Callable[[dict], tuple[dict, dict]] | None = None,
    content: str = "stub response",
    completion_tokens: int = 8,
):
    """Start a daemon-threaded loopback stub. Returns (server, base_url).

    ``responder(payload) -> (message_dict, usage_dict)`` customizes the
    reply per request; the default returns ``content`` with the given
    usage. The last request body is kept at ``server.last_payload``.
    Callers should ``server.shutdown()`` when done.
    """

    def default_responder(payload: dict) -> tuple[dict, dict]:
        return (
            {"role": "assistant", "content": content},
            {"prompt_tokens": 64, "completion_tokens": completion_tokens,
             "total_tokens": 64 + completion_tokens},
        )

    respond = responder or default_responder

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            raw = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            payload = json.loads(raw) if raw else {}
            self.server.last_payload = payload  # type: ignore[attr-defined]
            message, usage = respond(payload)
            body = json.dumps({
                "choices": [{"message": message, "finish_reason": "stop"}],
                "usage": usage,
            }).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence request spam
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    server.last_payload = {}  # type: ignore[attr-defined]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}/v1"
