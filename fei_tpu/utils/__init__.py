from fei_tpu.utils.logging import get_logger, setup_logging
from fei_tpu.utils.config import Config, get_config
from fei_tpu.utils.errors import (
    FeiError,
    ConfigError,
    ProviderError,
    ToolError,
    EngineError,
    MemoryError_,
)

__all__ = [
    "get_logger",
    "setup_logging",
    "Config",
    "get_config",
    "FeiError",
    "ConfigError",
    "ProviderError",
    "ToolError",
    "EngineError",
    "MemoryError_",
]
