"""JAX platform/version quirks kept in one place.

The deployment container pins an experimental TPU platform through a
sitecustomize hook that ignores the ``JAX_PLATFORMS`` env var; calling
``honor_jax_platforms()`` before the first backend touch makes
``JAX_PLATFORMS=cpu python -m fei_tpu ...`` (smoke runs, outage bypass)
actually run on CPU. One shared implementation — bench.py and the CLI
provider path both use it, so the workaround lives in one place.

``shard_map`` papers over the other environment split: newer jax ships
``jax.shard_map(check_vma=...)`` while the CPU test image has only
``jax.experimental.shard_map.shard_map(check_rep=...)``. Every sharded
program in fei_tpu lifts through this wrapper so both installs run the
same code (and the 8-device host-count CPU mesh exercises the sharded
path in tier-1 instead of skipping it).
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Apply the ``JAX_PLATFORMS`` env var via jax.config (idempotent).

    Must run BEFORE any backend initialization (importing jax is fine —
    backends are lazy). No env var set = default selection, untouched.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def has_shard_map() -> bool:
    """True when some spelling of shard_map is importable (any jax we
    support ships at least the experimental one)."""
    import jax

    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map as _  # noqa: F401

        return True
    except ImportError:
        return False


def pcast(x, axis_name, to: str = "varying"):
    """Version-portable ``jax.lax.pcast``.

    Newer jax requires replicated values to be explicitly cast to
    device-varying before a shard_map loop writes per-device values into
    them; the experimental shard_map has no varying-manual-axes tracking,
    so there the cast is an identity.
    """
    import jax

    fn = getattr(jax.lax, "pcast", None)
    if fn is not None:
        return fn(x, axis_name, to=to)
    return x


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma: bool | None = None, **kwargs):
    """Version-portable ``jax.shard_map``.

    ``check_vma`` (the modern kwarg) maps onto the experimental API's
    ``check_rep`` — both disable the replication/varying-manual-axes
    checker, which cannot see through a ``pallas_call``.
    """
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return native(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as legacy

    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return legacy(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
