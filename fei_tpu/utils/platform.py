"""JAX platform selection honoring ``JAX_PLATFORMS`` despite env pinning.

The deployment container pins an experimental TPU platform through a
sitecustomize hook that ignores the ``JAX_PLATFORMS`` env var; calling
``honor_jax_platforms()`` before the first backend touch makes
``JAX_PLATFORMS=cpu python -m fei_tpu ...`` (smoke runs, outage bypass)
actually run on CPU. One shared implementation — bench.py and the CLI
provider path both use it, so the workaround lives in one place.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Apply the ``JAX_PLATFORMS`` env var via jax.config (idempotent).

    Must run BEFORE any backend initialization (importing jax is fine —
    backends are lazy). No env var set = default selection, untouched.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
