"""Layered, schema-validated configuration.

Parity with reference fei/utils/config.py:45-701: a typed schema, an INI file
(default ``~/.fei_tpu.ini``), ``.env`` files, and environment variables, with
precedence **env > config file > schema default** (reference config.py:406-468)
and env lookups of the form ``FEI_TPU_<SECTION>_<OPTION>`` plus
``{PROVIDER}_API_KEY`` / ``LLM_API_KEY`` fallbacks (reference config.py:470-501).

Differences from the reference (deliberate fixes, see SURVEY.md appendix):
  - no global mutable singleton required for tests — ``Config`` instances are
    independent; ``get_config()`` is a convenience cache that tests can reset.
  - ``.env`` parsing never overrides variables already set in the process
    environment (reference preserved this too, config.py:320-365).
"""

from __future__ import annotations

import configparser
import os
import stat
import threading
from dataclasses import dataclass
from typing import Any, Callable

from fei_tpu.utils.errors import ConfigError

# ---------------------------------------------------------------------------
# Schema
# ---------------------------------------------------------------------------


@dataclass
class ConfigValue:
    """One schema'd option: type, default, optional validator/choices."""

    type: type = str
    default: Any = None
    choices: tuple | None = None
    validator: Callable[[Any], bool] | None = None
    secret: bool = False
    description: str = ""

    def coerce(self, raw: Any) -> Any:
        if raw is None:
            return None
        if self.type is bool:
            if isinstance(raw, bool):
                val = raw
            else:
                s = str(raw).strip().lower()
                if s in ("1", "true", "yes", "on"):
                    val = True
                elif s in ("0", "false", "no", "off"):
                    val = False
                else:
                    raise ConfigError(f"cannot parse boolean from {raw!r}")
        elif self.type is int:
            try:
                val = int(raw)
            except (TypeError, ValueError) as e:
                raise ConfigError(f"cannot parse int from {raw!r}") from e
        elif self.type is float:
            try:
                val = float(raw)
            except (TypeError, ValueError) as e:
                raise ConfigError(f"cannot parse float from {raw!r}") from e
        else:
            val = str(raw)
        if self.choices is not None and val not in self.choices:
            raise ConfigError(f"{val!r} not in allowed choices {self.choices}")
        if self.validator is not None and not self.validator(val):
            raise ConfigError(f"{val!r} failed validation")
        return val


# Mirrors the reference CONFIG_SCHEMA (config.py:45-72) plus engine options the
# TPU build introduces.
CONFIG_SCHEMA: dict[str, dict[str, ConfigValue]] = {
    "llm": {
        "provider": ConfigValue(str, "jax_local", description="LLM provider id"),
        "model": ConfigValue(str, "llama3-8b", description="model id for the provider"),
        "max_tokens": ConfigValue(int, 4000),
        "temperature": ConfigValue(float, 0.0),
        "top_p": ConfigValue(float, 1.0),
        "api_key": ConfigValue(str, None, secret=True),
    },
    "engine": {
        "checkpoint_dir": ConfigValue(str, None, description="dir with safetensors weights"),
        "tokenizer": ConfigValue(str, "byte", description="'byte' or path to tokenizer.json"),
        "max_seq_len": ConfigValue(int, 8192),
        "kv_page_size": ConfigValue(int, 128),
        "dtype": ConfigValue(str, "bfloat16", choices=("bfloat16", "float32", "float16")),
        "mesh_shape": ConfigValue(str, "", description="e.g. 'dp=1,tp=8'; empty = auto"),
        "use_pallas": ConfigValue(bool, True),
    },
    "jax_local": {
        "model": ConfigValue(str, "llama3-1b", description="engine model config name"),
        "checkpoint_dir": ConfigValue(str, None, description="HF safetensors dir"),
        "tokenizer": ConfigValue(str, None, description="'byte' or local tokenizer path"),
        "max_seq_len": ConfigValue(int, 8192),
        "paged": ConfigValue(bool, False, description="paged pool + continuous batching"),
        "batch_size": ConfigValue(int, 1, description="concurrent decode slots (paged)"),
        # modes validated by the engine (loud EngineError); no choices here
        # so a blank INI line means unset rather than a ConfigError
        "quantize": ConfigValue(str, None, description="weight-only int8 ('int8')"),
        "kv_quant": ConfigValue(str, None, description="int8 KV pages ('int8', paged)"),
        "prefix_cache": ConfigValue(bool, False, description="reuse shared prompt-prefix pages (paged)"),
    },
    "memdir": {
        "base_dir": ConfigValue(str, None),
        "server_url": ConfigValue(str, "http://localhost:5000"),
        "api_key": ConfigValue(str, None, secret=True),
        "port": ConfigValue(int, 5000),
    },
    "memorychain": {
        "node_url": ConfigValue(str, "http://localhost:6789"),
        "port": ConfigValue(int, 6789),
        "difficulty": ConfigValue(int, 2),
    },
    "tools": {
        "shell_allow": ConfigValue(str, "", description="extra comma-separated allowed commands"),
        "backup_dir": ConfigValue(str, ".fei_backups"),
    },
    "log": {
        "level": ConfigValue(str, "WARNING"),
        "file": ConfigValue(str, None),
    },
}

_ENV_PREFIX = "FEI_TPU"


def _parse_env_file(path: str) -> dict[str, str]:
    out: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                key, _, value = line.partition("=")
                key = key.strip()
                value = value.strip().strip("'\"")
                if key:
                    out[key] = value
    except OSError:
        pass
    return out


class Config:
    """Layered config. Precedence: env > config file > schema default."""

    def __init__(
        self,
        config_path: str | None = None,
        env_files: list[str] | None = None,
        environ: dict[str, str] | None = None,
    ):
        self._lock = threading.RLock()
        self._environ = environ if environ is not None else os.environ
        self.config_path = config_path or os.path.join(
            os.path.expanduser("~"), ".fei_tpu.ini"
        )
        self._parser = configparser.ConfigParser()
        if os.path.exists(self.config_path):
            self._secure_path(self.config_path)
            self._parser.read(self.config_path)
        # .env files: defaults mirror the reference's 3 locations
        # (reference config.py:320-365): cwd, ~/.fei_tpu/.env, package dir.
        if env_files is None:
            env_files = [
                os.path.join(os.getcwd(), ".env"),
                os.path.join(os.path.expanduser("~"), ".fei_tpu", ".env"),
            ]
        self._dotenv: dict[str, str] = {}
        for path in env_files:
            self._dotenv.update(_parse_env_file(path))

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _secure_path(path: str) -> None:
        """chmod g/o-rw on secret-bearing files (reference config.py:293-318)."""
        try:
            mode = os.stat(path).st_mode
            os.chmod(path, mode & ~(stat.S_IRWXG | stat.S_IRWXO))
        except OSError:
            pass

    def _schema_for(self, section: str, option: str) -> ConfigValue | None:
        return CONFIG_SCHEMA.get(section, {}).get(option)

    def _get_env(self, key: str) -> str | None:
        """Process env wins over .env files. Empty string counts as unset so
        ``FEI_TPU_X=`` in CI falls through to file/default."""
        val = self._environ.get(key)
        if val is None:
            val = self._dotenv.get(key)
        return val if val else None

    def _get_from_env(self, section: str, option: str) -> str | None:
        """FEI_TPU_<SECTION>_<OPTION>; api keys additionally try
        {PROVIDER}_API_KEY then LLM_API_KEY (reference config.py:470-501)."""
        val = self._get_env(f"{_ENV_PREFIX}_{section.upper()}_{option.upper()}")
        if val is not None:
            return val
        if option == "api_key":
            if section == "llm":
                provider = self.get("llm", "provider")
                val = self._get_env(f"{str(provider).upper()}_API_KEY")
                if val is not None:
                    return val
                return self._get_env("LLM_API_KEY")
            val = self._get_env(f"{section.upper()}_API_KEY")
            if val is not None:
                return val
        return None

    # -- public API ---------------------------------------------------------

    def get(self, section: str, option: str, fallback: Any = None) -> Any:
        """Resolve with precedence env > file > schema default > fallback."""
        with self._lock:
            schema = self._schema_for(section, option)
            env_val = self._get_from_env(section, option)
            if env_val is not None:
                return schema.coerce(env_val) if schema else env_val
            if self._parser.has_option(section, option):
                raw = self._parser.get(section, option)
                return schema.coerce(raw) if schema else raw
            if schema is not None and schema.default is not None:
                return schema.default
            return fallback

    def get_int(self, section: str, option: str, fallback: int = 0) -> int:
        val = self.get(section, option, fallback)
        return int(val) if val is not None else fallback

    def get_float(self, section: str, option: str, fallback: float = 0.0) -> float:
        val = self.get(section, option, fallback)
        return float(val) if val is not None else fallback

    def get_bool(self, section: str, option: str, fallback: bool = False) -> bool:
        val = self.get(section, option, fallback)
        if isinstance(val, bool):
            return val
        return ConfigValue(bool).coerce(val) if val is not None else fallback

    def set(self, section: str, option: str, value: Any) -> None:
        """Validate against schema and persist to the INI file
        (reference config.py:503-578)."""
        if value is None:
            # Persisting None would write an empty string that poisons typed
            # reads; treat as removal instead.
            self.delete(section, option)
            return
        with self._lock:
            schema = self._schema_for(section, option)
            if schema is not None:
                value = schema.coerce(value)
            if not self._parser.has_section(section):
                self._parser.add_section(section)
            self._parser.set(section, option, str(value))
            self._persist()

    def delete(self, section: str, option: str) -> bool:
        with self._lock:
            if self._parser.has_option(section, option):
                self._parser.remove_option(section, option)
                self._persist()
                return True
            return False

    def _persist(self) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.config_path)), exist_ok=True)
        with open(self.config_path, "w", encoding="utf-8") as f:
            self._parser.write(f)
        self._secure_path(self.config_path)

    def as_dict(self) -> dict[str, dict[str, Any]]:
        out: dict[str, dict[str, Any]] = {}
        for section, options in CONFIG_SCHEMA.items():
            out[section] = {}
            for option, schema in options.items():
                val = self.get(section, option)
                out[section][option] = "****" if (schema.secret and val) else val
        return out


_SINGLETON: Config | None = None
_SINGLETON_LOCK = threading.Lock()


def get_config(reload: bool = False) -> Config:
    """Convenience process-wide config (reference config.py:240). Tests should
    construct Config directly instead."""
    global _SINGLETON
    with _SINGLETON_LOCK:
        if _SINGLETON is None or reload:
            _SINGLETON = Config()
        return _SINGLETON


def reset_config() -> None:
    global _SINGLETON
    with _SINGLETON_LOCK:
        _SINGLETON = None
