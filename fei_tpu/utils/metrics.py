"""Back-compat shim: the metrics implementation moved to fei_tpu/obs/.

The grown observability subsystem (histograms with p50/p95/p99 summaries,
per-request lifecycle traces, Prometheus exposition) lives in
fei_tpu.obs; this module re-exports the same names so every pre-existing
``from fei_tpu.utils.metrics import METRICS`` call site works unchanged.
See docs/OBSERVABILITY.md.
"""

from fei_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    METRICS,
    Histogram,
    Metrics,
    _Stat,
)

__all__ = ["DEFAULT_BUCKETS", "METRICS", "Histogram", "Metrics"]
