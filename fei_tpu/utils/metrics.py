"""Structured metrics: counters, gauges, and phase timers.

The reference has no tracing/profiling at all (SURVEY.md §5); this module is
greenfield. It gives every subsystem cheap counters plus wall-clock span timing
with per-phase aggregation (prefill/decode/tool/llm), and can wrap
``jax.profiler`` traces when requested.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict
from dataclasses import dataclass


@dataclass
class _Stat:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def record(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.min_s = min(self.min_s, dt)
        self.max_s = max(self.max_s, dt)

    def as_dict(self) -> dict:
        mean = self.total_s / self.count if self.count else 0.0
        return {
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "mean_s": round(mean, 6),
            "min_s": round(self.min_s, 6) if self.count else 0.0,
            "max_s": round(self.max_s, 6),
        }


class Metrics:
    """Thread-safe counters, gauges, and span timers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._spans: dict[str, _Stat] = defaultdict(_Stat)

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    @contextlib.contextmanager
    def span(self, name: str, jax_trace: bool = False):
        """Time a block; optionally also emit a jax.profiler trace annotation."""
        ctx = contextlib.nullcontext()
        if jax_trace:
            try:
                import jax

                ctx = jax.profiler.TraceAnnotation(name)
            except Exception:
                ctx = contextlib.nullcontext()
        start = time.perf_counter()
        try:
            with ctx:
                yield
        finally:
            dt = time.perf_counter() - start
            with self._lock:
                self._spans[name].record(dt)

    def timing(self, name: str, dt: float) -> None:
        with self._lock:
            self._spans[name].record(dt)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "spans": {k: v.as_dict() for k, v in self._spans.items()},
            }

    def dumps(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._spans.clear()


METRICS = Metrics()
