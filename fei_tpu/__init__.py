"""fei_tpu — a TPU-native AI coding-assistant framework.

Capability parity with the reference (david-strejc/fei): tool-calling agent
loop, code tools, task executor, Memdir + Memorychain memory subsystems, CLI
and Textual UIs — but the LLM runs in-tree as the ``jax_local`` provider: a
JAX/XLA autoregressive decoder with Pallas attention/RoPE kernels, a paged
KV cache, and tensor/expert/sequence parallelism over a ``jax.sharding.Mesh``.

Package layout:
  fei_tpu.utils     — config / logging / errors / metrics foundation
  fei_tpu.models    — model definitions (Llama family, Mixtral MoE) as pure
                      functions over parameter pytrees
  fei_tpu.ops       — numerics: RMSNorm, RoPE, attention (incl. Pallas kernels)
  fei_tpu.engine    — tokenizer, KV cache, sampling, decode loop, engine
  fei_tpu.parallel  — mesh construction, sharding rules, collectives
  fei_tpu.agent     — Assistant agent loop, providers (jax_local, mock, litellm)
  fei_tpu.tools     — tool registry/definitions/handlers, code tools, repo map
  fei_tpu.memory    — Memdir (Maildir store) and Memorychain (distributed ledger)
  fei_tpu.ui        — CLI REPL and Textual TUI
"""

__version__ = "0.1.0"
