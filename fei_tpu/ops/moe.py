"""Mixture-of-experts layer (Mixtral-style top-k routing over SwiGLU experts).

Dense-compute formulation: every expert processes every token and the router's
top-k weights zero out non-selected experts. On TPU this keeps the MXU busy
with one big batched einsum and avoids data-dependent shapes inside jit; the
expert-parallel path (fei_tpu.parallel.expert) shards the expert dimension
over the mesh so each chip only computes its resident experts, turning the
dense mask into a real compute saving at scale.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_mlp(
    x: jnp.ndarray,  # [B, T, H]
    router_w: jnp.ndarray,  # [H, E]
    w_gate: jnp.ndarray,  # [E, H, I]
    w_up: jnp.ndarray,  # [E, H, I]
    w_down: jnp.ndarray,  # [E, I, H]
    num_experts_per_tok: int,
) -> jnp.ndarray:
    B, T, H = x.shape
    E = router_w.shape[-1]
    logits = jnp.einsum("bth,he->bte", x.astype(jnp.float32), router_w.astype(jnp.float32))
    topk_vals, topk_idx = jax.lax.top_k(logits, num_experts_per_tok)  # [B,T,k]
    topk_weights = jax.nn.softmax(topk_vals, axis=-1)
    # scatter the normalized top-k weights back to a dense [B,T,E] mask
    one_hot = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [B,T,k,E]
    weights = jnp.einsum("btk,btke->bte", topk_weights, one_hot)

    # every expert runs on every token; weights gate the combination
    gate = jnp.einsum("bth,ehi->beti", x, w_gate)
    up = jnp.einsum("bth,ehi->beti", x, w_up)
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    expert_out = jnp.einsum("beti,eih->beth", act, w_down)  # [B,E,T,H]
    out = jnp.einsum("bte,beth->bth", weights.astype(x.dtype), expert_out)
    return out
