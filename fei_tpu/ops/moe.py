"""Mixture-of-experts layer (Mixtral-style top-k routing over SwiGLU experts).

Dense-compute formulation: every expert processes every token and the router's
top-k weights zero out non-selected experts. On TPU this keeps the MXU busy
with one big batched einsum and avoids data-dependent shapes inside jit; the
expert-parallel path (fei_tpu.parallel.expert) shards the expert dimension
over the mesh so each chip only computes its resident experts, turning the
dense mask into a real compute saving at scale.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fei_tpu.ops.quant import scale_expert_out, scale_rows, wcast


def moe_mlp(
    x: jnp.ndarray,  # [B, T, H]
    router_w: jnp.ndarray,  # [H, E]
    w_gate: jnp.ndarray,  # [E, H, I]
    w_up: jnp.ndarray,  # [E, H, I]
    w_down: jnp.ndarray,  # [E, I, H]
    num_experts_per_tok: int,
) -> jnp.ndarray:
    B, T, H = x.shape
    E = router_w.shape[-1]
    logits = jnp.einsum("bth,he->bte", x.astype(jnp.float32), router_w.astype(jnp.float32))
    topk_vals, topk_idx = jax.lax.top_k(logits, num_experts_per_tok)  # [B,T,k]
    topk_weights = jax.nn.softmax(topk_vals, axis=-1)
    # scatter the normalized top-k weights back to a dense [B,T,E] mask
    one_hot = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [B,T,k,E]
    weights = jnp.einsum("btk,btke->bte", topk_weights, one_hot)

    # every expert runs on every token; weights gate the combination.
    # int8 experts: einsum the raw int8 (cast) and scale the result before
    # the nonlinearity — no dense bf16 weight copy is ever materialized
    gate = scale_expert_out(
        jnp.einsum("bth,ehi->beti", x, wcast(w_gate, x.dtype)), w_gate, 1
    )
    up = scale_expert_out(
        jnp.einsum("bth,ehi->beti", x, wcast(w_up, x.dtype)), w_up, 1
    )
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    expert_out = scale_expert_out(
        jnp.einsum("beti,eih->beth", act, wcast(w_down, act.dtype)), w_down, 1
    )  # [B,E,T,H]
    out = jnp.einsum("bte,beth->bth", weights.astype(x.dtype), expert_out)
    return out


def moe_mlp_routed(
    x: jnp.ndarray,  # [B, T, H]
    router_w: jnp.ndarray,  # [H, E]
    w_gate: jnp.ndarray,  # [E, H, I]
    w_up: jnp.ndarray,  # [E, H, I]
    w_down: jnp.ndarray,  # [E, I, H]
    num_experts_per_tok: int,
) -> jnp.ndarray:
    """Token-routed MoE: each token runs ONLY its top-k experts.

    Sort-based grouped matmul: the N·k (token, expert) assignments are
    sorted by expert so each expert's tokens are a contiguous row block,
    then ``lax.ragged_dot`` (the TPU grouped-GEMM primitive) runs the three
    SwiGLU matmuls over the blocks. Expert FLOPs are k/E of ``moe_mlp``
    (≈4x saving for Mixtral top-2-of-8) with fully static shapes — the
    sort/gather is O(N·k·H) data movement, so this path wins whenever the
    token count is non-trivial; the dense path stays the numerical oracle
    and the better choice for tiny decode batches.
    """
    B, T, H = x.shape
    E = router_w.shape[-1]
    k = num_experts_per_tok
    N = B * T
    xf = x.reshape(N, H)

    logits = jnp.einsum(
        "nh,he->ne", xf.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    topk_vals, topk_idx = jax.lax.top_k(logits, k)  # [N, k]
    topk_weights = jax.nn.softmax(topk_vals, axis=-1)

    flat_expert = topk_idx.reshape(-1)  # [N*k]
    order = jnp.argsort(flat_expert)  # stable: ties keep token order
    token_of = order // k  # source token of each sorted assignment
    xs = jnp.take(xf, token_of, axis=0)  # [N*k, H]
    expert_of = jnp.take(flat_expert, order)  # expert of each sorted row
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

    gate = scale_rows(
        jax.lax.ragged_dot(xs, wcast(w_gate, xs.dtype), group_sizes),
        w_gate, expert_of,
    )
    up = scale_rows(
        jax.lax.ragged_dot(xs, wcast(w_up, xs.dtype), group_sizes),
        w_up, expert_of,
    )
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    outs = scale_rows(
        jax.lax.ragged_dot(act, wcast(w_down, act.dtype), group_sizes),
        w_down, expert_of,
    )  # [N*k, H]

    wf = jnp.take(topk_weights.reshape(-1), order).astype(x.dtype)
    out = jnp.zeros((N, H), dtype=x.dtype).at[token_of].add(outs * wf[:, None])
    return out.reshape(B, T, H)
