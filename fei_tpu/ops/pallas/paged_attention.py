"""Ragged paged-attention decode kernel (Pallas TPU).

Decode-time attention where the KV cache is paged: each sequence owns a list
of fixed-size pages scattered through a shared pool, indirected by a block
table. This is the kernel that keeps the agent's unbounded task-loop
conversations (reference behavior: fei/core/task_executor.py:231-252 grows
context monotonically) from forcing one contiguous max-length buffer per
sequence — HBM is allocated page-by-page as conversations grow.

Grid = (B, K_heads, max_pages); pages are the innermost sequential axis.
The block table and per-sequence lengths arrive as scalar prefetch, and the
page index map reads the table directly — Pallas DMAs exactly the pages each
sequence owns, in table order, with no host gather. Online softmax carries
(m, l, acc) across pages in VMEM scratch; dead pages (beyond the sequence's
length) are predicated off with pl.when.

Page pools are stored head-major ([P, K, page_size, D]) so each DMA'd tile
is (page_size, head_dim) — the Mosaic-native (sublane, lane) orientation.

Interpret mode on CPU; the gather-based oracle for tests lives in
fei_tpu.engine.paged_cache.paged_attention_reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    block_table_ref,  # [B, max_pages] page index per (seq, slot)
    length_ref,  # [B] valid kv length per sequence
    # blocks: q [1,1,G,D], k/v [1,1,page_size,D]; int8 pools add
    # ks/vs [1,1,1,page_size] per-slot scale rows before o [1,1,G,D]
    *refs,
    page_size: int,
    scale: float,
    kv_int8: bool,
):
    if kv_int8:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    pi = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(pi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = length_ref[b]

    @pl.when(pi * page_size < length)
    def _compute():
        q = q_ref[0, 0]  # [G, D]
        k = k_ref[0, 0]  # [page_size, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k.astype(q.dtype) if kv_int8 else k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, page_size]
        if kv_int8:
            # dequant folds into the score row: k_slot scale is constant
            # along the contracted D axis, so (q·k_int8)·ks == q·(k_int8·ks)
            s = s * ks_ref[0, 0]  # [1, page_size] broadcasts over G

        pos = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m_prev - m_new)

        l_ref[:] = correction * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        if kv_int8:
            # fold v's per-slot scale into p (constant along the contracted
            # slot axis per output channel): (p·vs)·v_int8 == p·(v_int8·vs)
            pv = (p * vs_ref[0, 0]).astype(jnp.float32)
            v = v.astype(jnp.float32)
        else:
            pv = p.astype(v.dtype)
        acc_ref[:] = correction * acc_ref[:] + jax.lax.dot_general(
            pv, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(pi == num_pages - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret")
)
def paged_attention(
    q: jnp.ndarray,  # [B, H, D] one decode token per sequence
    k_pages: jnp.ndarray,  # [P, K, page_size, D] shared page pool (head-major)
    v_pages: jnp.ndarray,  # [P, K, page_size, D]
    block_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32 valid kv length
    scale: float | None = None,
    interpret: bool | None = None,
    k_scales: jnp.ndarray | None = None,  # [P, K, 1, page_size] (int8 pools)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Single-token attention over a paged KV cache. Returns [B, H, D].

    int8 pools (``k_scales``/``v_scales`` given) dequantize inside the
    kernel — scale rows ride the same page indirection as their pages, and
    the per-slot scales fold into the score row / p matrix exactly.
    """
    B, H, D = q.shape
    K, page_size = k_pages.shape[1], k_pages.shape[2]
    G = H // K
    max_pages = block_table.shape[1]
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kv_int8 = k_scales is not None

    # group-major so each q tile is this kv head's (G, D) block
    qg = q.reshape(B, K, G, D)

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, scale=scale, kv_int8=kv_int8
    )

    page_spec = pl.BlockSpec(
        (1, 1, page_size, D),
        lambda b, kh, pi, bt, ln: (bt[b, pi], kh, 0, 0),
    )
    scale_spec = pl.BlockSpec(
        (1, 1, 1, page_size),
        lambda b, kh, pi, bt, ln: (bt[b, pi], kh, 0, 0),
    )
    in_specs = [
        pl.BlockSpec(
            (1, 1, G, D),
            lambda b, kh, pi, bt, ln: (b, kh, 0, 0),
        ),
        page_spec,
        page_spec,
    ]
    args = [qg, k_pages, v_pages]
    if kv_int8:
        in_specs += [scale_spec, scale_spec]
        args += [k_scales, v_scales]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, K, max_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, G, D),
                lambda b, kh, pi, bt, ln: (b, kh, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_table.astype(jnp.int32), lengths.astype(jnp.int32), *args)

    return out.reshape(B, H, D)


def paged_attention_sharded(
    q: jnp.ndarray,  # [B, H, D]
    k_pages: jnp.ndarray,  # [P, K, page_size, D] (kv-head sharded over tp)
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    mesh,
    axis_name: str = "tp",
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Tensor-parallel paged attention: XLA cannot auto-partition a
    pallas_call, so the kernel runs under shard_map with kv heads (and the
    query head groups that attend to them) sharded over ``axis_name`` —
    each device attends over its local slice of the page pool. Composable
    inside an outer jit; inputs already laid out this way reshard for free.
    """
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    K = k_pages.shape[1]
    if K % n:
        raise ValueError(f"kv heads {K} must divide {axis_name} axis {n}")
    head_spec = P(None, axis_name, None)  # q/out: heads sharded
    page_spec = P(None, axis_name, None, None)
    in_specs = [head_spec, page_spec, page_spec, P(), P()]
    args = [q, k_pages, v_pages, block_table, lengths]
    if k_scales is not None:
        in_specs += [page_spec, page_spec]
        args += [k_scales, v_scales]

    def body(q, kp, vp, bt, ln, *scales):
        ks, vs = scales if scales else (None, None)
        return paged_attention(q, kp, vp, bt, ln, k_scales=ks, v_scales=vs)

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=head_spec,
        # the vma checker can't see through a pallas_call's output
        check_vma=False,
    )
    return fn(*args)
