"""Ragged paged-attention decode kernel (Pallas TPU).

Decode-time attention where the KV cache is paged: each sequence owns a list
of fixed-size pages scattered through a shared pool, indirected by a block
table. This is the kernel that keeps the agent's unbounded task-loop
conversations (reference behavior: fei/core/task_executor.py:231-252 grows
context monotonically) from forcing one contiguous max-length buffer per
sequence — HBM is allocated page-by-page as conversations grow.

Grid = (B, K_heads, max_pages); pages are the innermost sequential axis.
The block table and per-sequence lengths arrive as scalar prefetch, and the
page index map reads the table directly — Pallas DMAs exactly the pages each
sequence owns, in table order, with no host gather. Online softmax carries
(m, l, acc) across pages in VMEM scratch; dead pages (beyond the sequence's
length) are predicated off with pl.when.

Page pools are stored head-major ([P, K, page_size, D]) so each DMA'd tile
is (page_size, head_dim) — the Mosaic-native (sublane, lane) orientation.

Interpret mode on CPU; the gather-based oracle for tests lives in
fei_tpu.engine.paged_cache.paged_attention_reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fei_tpu.utils.platform import shard_map

# jax renamed pltpu.TPUCompilerParams -> CompilerParams (jax 0.5); alias so
# the kernels run on both API generations
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    block_table_ref,  # [B, max_pages] page index per (seq, slot)
    length_ref,  # [B] valid kv length for the FIRST query row
    # blocks: q [1,1,qt*G,D], k/v [1,1,page_size,D]; int8 pools add
    # ks/vs [1,1,1,page_size] per-slot scale rows before o [1,1,qt*G,D]
    *refs,
    page_size: int,
    scale: float,
    kv_int8: bool,
    qt: int = 1,
    g: int = 1,
    window: int = 0,
):
    """Online-softmax paged attention over one (seq, kv-head) tile.

    ``qt`` is the query-block length: qt consecutive query positions share
    one kernel invocation (speculative verification / block decode), each
    row r attending kv positions < length + r//g — the per-row causal
    limit. qt=1 with length = kv_len+1 is plain single-token decode; the
    pool history is read ONCE for the whole block either way.
    """
    if kv_int8:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    pi = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(pi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = length_ref[b]

    page_live = pi * page_size < length + (qt - 1)
    if window:  # pages entirely below every row's window are dead
        page_live = jnp.logical_and(
            page_live, (pi + 1) * page_size > length - window
        )

    @pl.when(page_live)
    def _compute():
        q = q_ref[0, 0]  # [qt*G, D]
        k = k_ref[0, 0]  # [page_size, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k.astype(q.dtype) if kv_int8 else k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [qt*G, page_size]
        if kv_int8:
            # dequant folds into the score row: k_slot scale is constant
            # along the contracted D axis, so (q·k_int8)·ks == q·(k_int8·ks)
            s = s * ks_ref[0, 0]  # [1, page_size] broadcasts over rows

        pos = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        # per-row causal limit: row r is query position (length-1) + r//g
        row_t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // g
        visible = pos < length + row_t
        if window:  # sliding window: only the last `window` positions
            visible = jnp.logical_and(
                visible, pos > length - 1 + row_t - window
            )
        s = jnp.where(visible, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m_prev - m_new)

        l_ref[:] = correction * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        if kv_int8:
            # fold v's per-slot scale into p (constant along the contracted
            # slot axis per output channel): (p·vs)·v_int8 == p·(v_int8·vs)
            pv = (p * vs_ref[0, 0]).astype(jnp.float32)
            v = v.astype(jnp.float32)
        else:
            pv = p.astype(v.dtype)
        acc_ref[:] = correction * acc_ref[:] + jax.lax.dot_general(
            pv, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(pi == num_pages - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _paged_call(
    qg: jnp.ndarray,  # [B, K, qt*g, D] position-major, group-minor rows
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    limits: jnp.ndarray,  # [B] first-row causal limit (kv positions < it)
    *,
    qt: int,
    g: int,
    scale: float,
    interpret: bool,
    k_scales: jnp.ndarray | None,
    v_scales: jnp.ndarray | None,
    window: int = 0,
) -> jnp.ndarray:
    """Shared pallas_call plumbing for the single-query and block wrappers
    — ONE assembly of specs/grid/scratch so the two paths cannot drift."""
    B, K, rows, D = qg.shape
    page_size = k_pages.shape[2]
    max_pages = block_table.shape[1]
    kv_int8 = k_scales is not None

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, scale=scale, kv_int8=kv_int8,
        qt=qt, g=g, window=window,
    )
    if window:
        # clamp dead leading grid steps to the FIRST in-window page: Pallas
        # elides a block copy when consecutive steps map the same index, so
        # pages entirely below every row's window are never DMA'd (at 32k
        # context with a 4k window that's ~87% of the pool read otherwise)
        def _page_idx(b, kh, pi, bt, ln):
            first = jnp.maximum((ln[b] - window) // page_size, 0)
            return (bt[b, jnp.maximum(pi, first)], kh, 0, 0)
    else:
        def _page_idx(b, kh, pi, bt, ln):
            return (bt[b, pi], kh, 0, 0)

    page_spec = pl.BlockSpec((1, 1, page_size, D), _page_idx)
    scale_spec = pl.BlockSpec((1, 1, 1, page_size), _page_idx)
    row_spec = pl.BlockSpec(
        (1, 1, rows, D),
        lambda b, kh, pi, bt, ln: (b, kh, 0, 0),
    )
    in_specs = [row_spec, page_spec, page_spec]
    args = [qg, k_pages, v_pages]
    if kv_int8:
        in_specs += [scale_spec, scale_spec]
        args += [k_scales, v_scales]

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, K, max_pages),
            in_specs=in_specs,
            out_specs=row_spec,
            scratch_shapes=[
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, rows, D), qg.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_table.astype(jnp.int32), limits.astype(jnp.int32), *args)


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "window")
)
def paged_attention(
    q: jnp.ndarray,  # [B, H, D] one decode token per sequence
    k_pages: jnp.ndarray,  # [P, K, page_size, D] shared page pool (head-major)
    v_pages: jnp.ndarray,  # [P, K, page_size, D]
    block_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32 valid kv length
    scale: float | None = None,
    interpret: bool | None = None,
    k_scales: jnp.ndarray | None = None,  # [P, K, 1, page_size] (int8 pools)
    v_scales: jnp.ndarray | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Single-token attention over a paged KV cache. Returns [B, H, D].
    ``window``: sliding-window attention (only the last ``window``
    positions are visible).

    int8 pools (``k_scales``/``v_scales`` given) dequantize inside the
    kernel — scale rows ride the same page indirection as their pages, and
    the per-slot scales fold into the score row / p matrix exactly.
    """
    B, H, D = q.shape
    K = k_pages.shape[1]
    G = H // K
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # group-major so each q tile is this kv head's (G, D) block
    qg = q.reshape(B, K, G, D)
    out = _paged_call(
        qg, k_pages, v_pages, block_table, lengths,
        qt=1, g=G, scale=scale, interpret=interpret,
        k_scales=k_scales, v_scales=v_scales, window=window,
    )
    return out.reshape(B, H, D)


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "window")
)
def paged_attention_block(
    q: jnp.ndarray,  # [B, T, H, D] — T consecutive query positions per seq
    k_pages: jnp.ndarray,  # [P, K, page_size, D] shared page pool
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32 kv length BEFORE the block
    scale: float | None = None,
    interpret: bool | None = None,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Multi-query paged attention for speculative verification / block
    decode. The T positions' K/V must already be written into the pool
    (positions lengths..lengths+T-1); per-row causal masking keeps query t
    from seeing positions beyond lengths+t. Pool history is read ONCE for
    the whole block — vs T reads for T single-token calls. Returns
    [B, T, H, D]."""
    B, T, H, D = q.shape
    K = k_pages.shape[1]
    G = H // K
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # rows = t*G + g: query position-major, head-group-minor, so the
    # kernel's row//G recovers t for the causal limit; the first row's
    # limit is lengths + 1 (its own position included)
    qg = jnp.swapaxes(q.reshape(B, T, K, G, D), 1, 2).reshape(B, K, T * G, D)
    out = _paged_call(
        qg, k_pages, v_pages, block_table, lengths + 1,
        qt=T, g=G, scale=scale, interpret=interpret,
        k_scales=k_scales, v_scales=v_scales, window=window,
    )
    return jnp.swapaxes(out.reshape(B, K, T, G, D), 1, 2).reshape(B, T, H, D)


def _sharded_paged(
    local_fn,
    head_spec,
    q, k_pages, v_pages, block_table, lengths, mesh, axis_name,
    k_scales, v_scales, window=0, dp_axis="dp",
):
    """Shared shard_map wrapper: XLA cannot auto-partition a pallas_call,
    so kv heads (and the query head groups attending to them) shard over
    ``axis_name`` and each device runs the kernel on its local pool slice.

    A ``dp_axis`` of size > 1 additionally splits the batch rows across dp
    replica groups when the batch divides evenly — each group attends its
    own slot slice against the (replicated) page pool, which is what lets
    dp multiply the scheduler's aggregate decode slots. Attention rows are
    independent, so the split is numerics-neutral.

    The per-device head outputs are all-gathered INSIDE the shard_map and
    the result leaves replicated over ``axis_name``. Emitting a
    head-sharded output instead would let GSPMD partition the following
    ``wo`` contraction (heads fold into the contracted dim) into a psum —
    a different summation order than the single-chip matmul, which flips
    greedy argmax on near-tie logits. The gather is pure data movement, so
    sharded decode stays bit-identical to single-chip."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape.get(axis_name, 1)
    K = k_pages.shape[1]
    if K % n:
        raise ValueError(f"kv heads {K} must divide {axis_name} axis {n}")
    dp = mesh.shape.get(dp_axis, 1)
    batch_axis = dp_axis if (dp > 1 and q.shape[0] % dp == 0) else None
    head_axis = tuple(head_spec).index(axis_name)  # q's head dim position
    head_spec = P(batch_axis, *tuple(head_spec)[1:])
    out_spec = P(batch_axis)  # heads replicated after the in-body gather
    page_spec = P(None, axis_name, None, None)
    in_specs = [head_spec, page_spec, page_spec,
                P(batch_axis), P(batch_axis)]
    args = [q, k_pages, v_pages, block_table, lengths]
    if k_scales is not None:
        in_specs += [page_spec, page_spec]
        args += [k_scales, v_scales]

    def body(q, kp, vp, bt, ln, *scales):
        ks, vs = scales if scales else (None, None)
        out = local_fn(
            q, kp, vp, bt, ln, k_scales=ks, v_scales=vs, window=window
        )
        if n > 1:
            out = jax.lax.all_gather(
                out, axis_name, axis=head_axis, tiled=True
            )
        return out

    fn = shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_spec,
        # the vma checker can't see through a pallas_call's output
        check_vma=False,
    )
    return fn(*args)


def paged_attention_sharded(
    q: jnp.ndarray,  # [B, H, D]
    k_pages: jnp.ndarray,  # [P, K, page_size, D] (kv-head sharded over tp)
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    mesh,
    axis_name: str = "tp",
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Tensor-parallel single-token paged attention (see _sharded_paged)."""
    from jax.sharding import PartitionSpec as P

    return _sharded_paged(
        paged_attention, P(None, axis_name, None),
        q, k_pages, v_pages, block_table, lengths, mesh, axis_name,
        k_scales, v_scales, window=window,
    )


def paged_attention_block_sharded(
    q: jnp.ndarray,  # [B, T, H, D]
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    mesh,
    axis_name: str = "tp",
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Tensor-parallel multi-query paged attention (see _sharded_paged)."""
    from jax.sharding import PartitionSpec as P

    return _sharded_paged(
        paged_attention_block, P(None, None, axis_name, None),
        q, k_pages, v_pages, block_table, lengths, mesh, axis_name,
        k_scales, v_scales, window=window,
    )
