"""Ragged paged-attention decode kernel (Pallas TPU).

Decode-time attention where the KV cache is paged: each sequence owns a list
of fixed-size pages scattered through a shared pool, indirected by a block
table. This is the kernel that keeps the agent's unbounded task-loop
conversations (reference behavior: fei/core/task_executor.py:231-252 grows
context monotonically) from forcing one contiguous max-length buffer per
sequence — HBM is allocated page-by-page as conversations grow.

Grid = (B, K_heads, max_pages); pages are the innermost sequential axis.
The block table and per-sequence lengths arrive as scalar prefetch, and the
page index map reads the table directly — Pallas DMAs exactly the pages each
sequence owns, in table order, with no host gather. Online softmax carries
(m, l, acc) across pages in VMEM scratch; dead pages (beyond the sequence's
length) are predicated off with pl.when.

Page pools are stored head-major ([P, K, page_size, D]) so each DMA'd tile
is (page_size, head_dim) — the Mosaic-native (sublane, lane) orientation.

Interpret mode on CPU; the gather-based oracle for tests lives in
fei_tpu.engine.paged_cache.paged_attention_reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    block_table_ref,  # [B, max_pages] page index per (seq, slot)
    length_ref,  # [B] valid kv length per sequence
    # blocks
    q_ref,  # [1, 1, G, D] this kv head's query group
    k_ref,  # [1, 1, page_size, D] one page of keys
    v_ref,  # [1, 1, page_size, D]
    o_ref,  # [1, 1, G, D]
    # scratch
    m_ref,  # [G, 1]
    l_ref,  # [G, 1]
    acc_ref,  # [G, D]
    *,
    page_size: int,
    scale: float,
):
    b = pl.program_id(0)
    pi = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(pi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = length_ref[b]

    @pl.when(pi * page_size < length)
    def _compute():
        q = q_ref[0, 0]  # [G, D]
        k = k_ref[0, 0]  # [page_size, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, page_size]

        pos = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m_prev - m_new)

        l_ref[:] = correction * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = correction * acc_ref[:] + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(pi == num_pages - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret")
)
def paged_attention(
    q: jnp.ndarray,  # [B, H, D] one decode token per sequence
    k_pages: jnp.ndarray,  # [P, K, page_size, D] shared page pool (head-major)
    v_pages: jnp.ndarray,  # [P, K, page_size, D]
    block_table: jnp.ndarray,  # [B, max_pages] int32
    lengths: jnp.ndarray,  # [B] int32 valid kv length
    scale: float | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Single-token attention over a paged KV cache. Returns [B, H, D]."""
    B, H, D = q.shape
    K, page_size = k_pages.shape[1], k_pages.shape[2]
    G = H // K
    max_pages = block_table.shape[1]
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # group-major so each q tile is this kv head's (G, D) block
    qg = q.reshape(B, K, G, D)

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, scale=scale
    )

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, K, max_pages),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, G, D),
                    lambda b, kh, pi, bt, ln: (b, kh, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, page_size, D),
                    lambda b, kh, pi, bt, ln: (bt[b, pi], kh, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, page_size, D),
                    lambda b, kh, pi, bt, ln: (bt[b, pi], kh, 0, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, G, D),
                lambda b, kh, pi, bt, ln: (b, kh, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, K, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(block_table.astype(jnp.int32), lengths.astype(jnp.int32), qg, k_pages, v_pages)

    return out.reshape(B, H, D)
