"""Blockwise (flash) causal attention as a Pallas TPU kernel.

Replaces the XLA-native oracle (fei_tpu.ops.attention) for prefill, where the
naive path materializes [B, T, S] scores in HBM. Here scores live only as
[block_q, block_k] VMEM tiles; the softmax is computed online (running max /
running sum), so HBM traffic is O(T·D) instead of O(T·S).

Kernel layout (SURVEY.md §7 step 4; the reference has no kernels to port):
  inputs are transposed head-major ([B, H, T, D]) so VMEM tiles are
  (seq, head_dim) — the Mosaic-native (sublane, lane) orientation. grid =
  (B, H, num_q_blocks, num_k_blocks) with the k axis innermost and
  sequential ("arbitrary"); running softmax state (m, l, acc) persists in
  VMEM scratch across k steps and the output tile is written on the last k
  step. GQA is folded into the k/v index maps (kv_head = h // G).

Per-sequence raggedness (cache length, causal offset) comes in as scalar
prefetch so masks are built from SMEM scalars, never materialized in HBM.

On CPU test meshes the kernel runs in Pallas interpret mode (automatic), so
the hermetic 8-device suite exercises the same code path as the TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(
    # scalar prefetch
    q_start_ref,  # [B] absolute position of each batch's first query token
    kv_len_ref,  # [B] valid kv prefix length (after cache write)
    # blocks
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    o_ref,  # [1, 1, block_q, D]
    # scratch
    m_ref,  # [block_q, 1] running max
    l_ref,  # [block_q, 1] running sum
    acc_ref,  # [block_q, D] running output accumulator
    *,
    block_q: int,
    block_k: int,
    scale: float,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = q_start_ref[b]
    kv_len = kv_len_ref[b]

    # absolute positions of this tile's queries / keys
    q_pos = q_start + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    # skip tiles entirely above the causal diagonal or past the valid prefix
    block_live = jnp.logical_and(
        ki * block_k <= q_start + qi * block_q + block_q - 1,
        ki * block_k < kv_len,
    )

    @pl.when(block_live)
    def _compute():
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]

        mask = jnp.logical_and(k_pos <= q_pos, k_pos < kv_len)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]  # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)

        p = jnp.exp(s - m_new)  # [block_q, block_k]
        correction = jnp.exp(m_prev - m_new)  # [block_q, 1]

        l_ref[:] = correction * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = correction * acc_ref[:] + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ki == num_k - 1)
    def _finalize():
        # rows with no live key (padding queries) have l == 0; emit zeros
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    q_start: jnp.ndarray,  # [B] int32: absolute position of first query token
    kv_length: jnp.ndarray,  # [B] int32: valid kv prefix (after cache write)
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal flash attention against a (possibly longer) KV buffer.

    Same contract as fei_tpu.ops.attention.attention: key position s is
    visible to the query at absolute position p iff s <= p and s < kv_length.
    Returns [B, T, H, D] in q.dtype.
    """
    B, T, H, D = q.shape
    S, K = k.shape[1], k.shape[2]
    groups = H // K
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Mosaic tiling: sublane (second-to-last) dim must be a multiple of 8
    block_q = max(8, min(block_q, _round_up(T, 8)))
    block_k = max(8, min(block_k, _round_up(S, 8)))

    # pad T/S up to whole blocks; masks make padded work inert
    T_pad = pl.cdiv(T, block_q) * block_q
    S_pad = pl.cdiv(S, block_k) * block_k

    # head-major so VMEM tiles are (seq, head_dim)
    qt = jnp.transpose(q, (0, 2, 1, 3))  # [B, H, T, D]
    kt = jnp.transpose(k, (0, 2, 1, 3))  # [B, K, S, D]
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if T_pad != T:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, T_pad - T), (0, 0)))
    if S_pad != S:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, S_pad - S), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, S_pad - S), (0, 0)))

    grid = (B, H, T_pad // block_q, S_pad // block_k)

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale
    )

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, D),
                    lambda b, h, qi, ki, *_: (b, h, qi, 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_k, D),
                    lambda b, h, qi, ki, *_: (b, h // groups, ki, 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_k, D),
                    lambda b, h, qi, ki, *_: (b, h // groups, ki, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, D),
                lambda b, h, qi, ki, *_: (b, h, qi, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T_pad, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_start.astype(jnp.int32), kv_length.astype(jnp.int32), qt, kt, vt)

    return jnp.transpose(out[:, :, :T], (0, 2, 1, 3))
