"""Blockwise (flash) causal attention as a Pallas TPU kernel, with a
flash-style Pallas backward (custom_vjp) so training/fine-tuning runs the
kernel too.

Replaces the XLA-native oracle (fei_tpu.ops.attention) for prefill, where the
naive path materializes [B, T, S] scores in HBM. Here scores live only as
[block_q, block_k] VMEM tiles; the softmax is computed online (running max /
running sum), so HBM traffic is O(T·D) instead of O(T·S).

Kernel layout (SURVEY.md §7 step 4; the reference has no kernels to port):
  inputs are transposed head-major ([B, H, T, D]) so VMEM tiles are
  (seq, head_dim) — the Mosaic-native (sublane, lane) orientation. grid =
  (B, H, num_q_blocks, num_k_blocks) with the k axis innermost and
  sequential ("arbitrary"); running softmax state (m, l, acc) persists in
  VMEM scratch across k steps and the output tile is written on the last k
  step. GQA is folded into the k/v index maps (kv_head = h // G).

Per-sequence raggedness (cache length, causal offset) comes in as scalar
prefetch so masks are built from SMEM scalars, never materialized in HBM.

Backward (Dao et al. flash attention 2 recompute scheme): the forward
additionally saves per-row logsumexp L = m + log(l); the backward
recomputes p = exp(q·kᵀ·scale − L) tile-by-tile (never materializing the
score matrix) in two kernels — one accumulating dq over k blocks, one
accumulating dk/dv over q blocks — with D = rowsum(dO ∘ O) precomputed by
XLA. GQA dk/dv are computed per query head and group-summed outside.

On CPU test meshes the kernel runs in Pallas interpret mode (automatic), so
the hermetic 8-device suite exercises the same code path as the TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> CompilerParams (jax 0.5); alias so
# the kernels run on both API generations
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

NEG_INF = -1e30
_LANES = 128  # lane width for row-stat (lse/D) outputs — Mosaic-native


def _fwd_kernel(
    # scalar prefetch
    q_start_ref,  # [B] absolute position of each batch's first query token
    kv_len_ref,  # [B] valid kv prefix length (after cache write)
    # blocks
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    o_ref,  # [1, 1, block_q, D]
    # then, only when save_lse: lse_ref [1, 1, block_q, LANES] (row stats
    # broadcast across lanes — Mosaic-native layout; lane 0 is read back)
    # scratch: m [block_q,1] running max, l [block_q,1] running sum,
    #          acc [block_q,D] running output accumulator
    *rest,
    block_q: int,
    block_k: int,
    scale: float,
    save_lse: bool,
    window: int,
):
    if save_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        lse_ref, (m_ref, l_ref, acc_ref) = None, rest
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = q_start_ref[b]
    kv_len = kv_len_ref[b]

    # absolute positions of this tile's queries / keys
    q_pos = q_start + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    # skip tiles entirely above the causal diagonal or past the valid prefix
    block_live = jnp.logical_and(
        ki * block_k <= q_start + qi * block_q + block_q - 1,
        ki * block_k < kv_len,
    )
    if window:  # k tiles entirely below every query's window are dead
        block_live = jnp.logical_and(
            block_live,
            (ki + 1) * block_k - 1 > q_start + qi * block_q - window,
        )

    @pl.when(block_live)
    def _compute():
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_k, D]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]

        mask = jnp.logical_and(k_pos <= q_pos, k_pos < kv_len)
        if window:  # sliding window: only the last `window` positions
            mask = jnp.logical_and(mask, k_pos > q_pos - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]  # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)

        p = jnp.exp(s - m_new)  # [block_q, block_k]
        correction = jnp.exp(m_prev - m_new)  # [block_q, 1]

        l_ref[:] = correction * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = correction * acc_ref[:] + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ki == num_k - 1)
    def _finalize():
        # rows with no live key (padding queries) have l == 0; emit zeros,
        # and +inf logsumexp so the backward's p = exp(s - L) is 0 there
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        if save_lse:
            lse = jnp.where(
                l == 0.0, jnp.inf, m_ref[:] + jnp.log(safe_l)
            )  # [block_q, 1]
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _kv_index_map(block_q: int, block_k: int, groups: int, window: int):
    """K/V BlockSpec index map with dead-tile elision: k tiles entirely
    below the window (SWA) or entirely above the causal diagonal / past the
    valid prefix are CLAMPED to the nearest live tile index. Pallas elides
    the block copy when consecutive grid steps map the same index, so dead
    tiles are never DMA'd from HBM — without this, a 32k-context/4k-window
    dense SWA prefill streams the full KV despite pl.when skipping the math
    (mirrors _page_idx in paged_attention.py). Compute on dead tiles is
    already predicated off, so the clamped tile's data is never read."""

    def idx(b, h, qi, ki, q_start, kv_len):
        q_first = q_start[b] + qi * block_q
        # last live tile: causal diagonal of the tile's LAST query, capped
        # at the final valid-prefix tile
        last = jnp.minimum(
            (q_first + block_q - 1) // block_k,
            jnp.maximum((kv_len[b] - 1) // block_k, 0),
        )
        if window:
            # first tile holding any position inside the FIRST query's
            # window (its window reaches furthest back)
            first = jnp.maximum((q_first - window + 1) // block_k, 0)
        else:
            first = 0
        return (b, h // groups, jnp.clip(ki, first, jnp.maximum(last, first)), 0)

    return idx


def _resolve_blocks(T: int, S: int, block_q: int, block_k: int):
    # Mosaic tiling: sublane (second-to-last) dim must be a multiple of 8
    block_q = max(8, min(block_q, _round_up(T, 8)))
    block_k = max(8, min(block_k, _round_up(S, 8)))
    return block_q, block_k


def _fwd_impl(
    q, k, v, q_start, kv_length, scale, block_q, block_k, interpret,
    save_lse, window,
):
    """Returns (out [B,T,H,D], lse or None). ``save_lse=False`` (the
    inference primal) emits no logsumexp output at all — zero extra HBM."""
    B, T, H, D = q.shape
    S, K = k.shape[1], k.shape[2]
    groups = H // K

    block_q, block_k = _resolve_blocks(T, S, block_q, block_k)
    T_pad = pl.cdiv(T, block_q) * block_q
    S_pad = pl.cdiv(S, block_k) * block_k

    # head-major so VMEM tiles are (seq, head_dim)
    qt = jnp.transpose(q, (0, 2, 1, 3))  # [B, H, T, D]
    kt = jnp.transpose(k, (0, 2, 1, 3))  # [B, K, S, D]
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if T_pad != T:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, T_pad - T), (0, 0)))
    if S_pad != S:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, S_pad - S), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, S_pad - S), (0, 0)))

    grid = (B, H, T_pad // block_q, S_pad // block_k)

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
        window=window,
        save_lse=save_lse,
    )

    kv_idx = _kv_index_map(block_q, block_k, groups, window)
    outs = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, D),
                    lambda b, h, qi, ki, *_: (b, h, qi, 0),
                ),
                pl.BlockSpec((1, 1, block_k, D), kv_idx),
                pl.BlockSpec((1, 1, block_k, D), kv_idx),
            ],
            out_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, D),
                    lambda b, h, qi, ki, *_: (b, h, qi, 0),
                ),
            ] + ([
                pl.BlockSpec(
                    (1, 1, block_q, _LANES),
                    lambda b, h, qi, ki, *_: (b, h, qi, 0),
                ),
            ] if save_lse else []),
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T_pad, D), q.dtype),
        ] + ([
            jax.ShapeDtypeStruct((B, H, T_pad, _LANES), jnp.float32),
        ] if save_lse else []),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_start.astype(jnp.int32), kv_length.astype(jnp.int32), qt, kt, vt)

    out = jnp.transpose(outs[0][:, :, :T], (0, 2, 1, 3))
    # residual keeps lane 0 only (128x smaller); bwd re-broadcasts
    lse = outs[1][..., :1] if save_lse else None
    return out, lse


def _dq_kernel(
    q_start_ref, kv_len_ref,
    q_ref, k_ref, v_ref, do_ref,  # [1,1,bq,D] / [1,1,bk,D]
    lse_ref, dsum_ref,  # [1,1,bq,_LANES] (lane 0 carries the value)
    dq_ref,  # [1,1,bq,D] out
    dq_acc,  # [bq, D] scratch
    *, block_q, block_k, scale, window,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = q_start_ref[b]
    kv_len = kv_len_ref[b]
    q_pos = q_start + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    block_live = jnp.logical_and(
        ki * block_k <= q_start + qi * block_q + block_q - 1,
        ki * block_k < kv_len,
    )
    if window:  # k tiles entirely below every query's window are dead
        block_live = jnp.logical_and(
            block_live,
            (ki + 1) * block_k - 1 > q_start + qi * block_q - window,
        )

    @pl.when(block_live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]  # [bq, 1] (lane 0)
        dsum = dsum_ref[0, 0][:, :1]

        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = jnp.logical_and(k_pos <= q_pos, k_pos < kv_len)
        if window:  # sliding window: only the last `window` positions
            mask = jnp.logical_and(mask, k_pos > q_pos - window)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk] (0 where masked or empty row)

        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - dsum)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_start_ref, kv_len_ref,
    q_ref, k_ref, v_ref, do_ref,
    lse_ref, dsum_ref,  # [1,1,bq,_LANES]
    dk_ref, dv_ref,  # [1,1,bk,D] out (per query head)
    dk_acc, dv_acc,  # [bk, D] scratch
    *, block_q, block_k, scale, window,
):
    b = pl.program_id(0)
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    num_q = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = q_start_ref[b]
    kv_len = kv_len_ref[b]
    q_pos = q_start + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    block_live = jnp.logical_and(
        ki * block_k <= q_start + qi * block_q + block_q - 1,
        ki * block_k < kv_len,
    )
    if window:  # k tiles entirely below every query's window are dead
        block_live = jnp.logical_and(
            block_live,
            (ki + 1) * block_k - 1 > q_start + qi * block_q - window,
        )

    @pl.when(block_live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]  # lane 0
        dsum = dsum_ref[0, 0][:, :1]

        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = jnp.logical_and(k_pos <= q_pos, k_pos < kv_len)
        if window:  # sliding window: only the last `window` positions
            mask = jnp.logical_and(mask, k_pos > q_pos - window)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)  # [bq, bk]

        # dv_j = sum_i p_ij dO_i
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dsum)  # [bq, bk]
        # dk_j = scale * sum_i ds_ij q_i
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_impl(
    scale, block_q, block_k, interpret, window, res, dout
):
    q, k, v, q_start, kv_length, out, lse = res
    B, T, H, D = q.shape
    S, K = k.shape[1], k.shape[2]
    groups = H // K

    block_q, block_k = _resolve_blocks(T, S, block_q, block_k)
    T_pad = pl.cdiv(T, block_q) * block_q
    S_pad = pl.cdiv(S, block_k) * block_k

    # D_i = rowsum(dO ∘ O): cheap elementwise reduce, XLA fuses it
    dsum = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, T, H]
    dsum = jnp.transpose(dsum, (0, 2, 1))  # [B, H, T]

    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    dot = jnp.transpose(dout, (0, 2, 1, 3))
    if T_pad != T:
        pad4 = ((0, 0), (0, 0), (0, T_pad - T), (0, 0))
        qt = jnp.pad(qt, pad4)
        dot = jnp.pad(dot, pad4)
        dsum = jnp.pad(dsum, ((0, 0), (0, 0), (0, T_pad - T)))
    if S_pad != S:
        pad4 = ((0, 0), (0, 0), (0, S_pad - S), (0, 0))
        kt = jnp.pad(kt, pad4)
        vt = jnp.pad(vt, pad4)

    # row stats ride lane-broadcast into the kernels (transient; the saved
    # residual itself is lane-0 only)
    lse = jnp.broadcast_to(lse, (*lse.shape[:-1], _LANES))
    dsum = jnp.broadcast_to(dsum[..., None], (*dsum.shape, _LANES))
    args = (q_start.astype(jnp.int32), kv_length.astype(jnp.int32),
            qt, kt, vt, dot, lse, dsum)

    q_spec = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, i, j, *_: (b, h, i, 0)
    )
    # dq shares the fwd grid geometry (k blocks innermost) — reuse the
    # dead-tile-eliding index map so SWA backward doesn't stream dead KV
    kv_spec_q = pl.BlockSpec(
        (1, 1, block_k, D), _kv_index_map(block_q, block_k, groups, window)
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, _LANES), lambda b, h, i, j, *_: (b, h, i, 0)
    )

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_q=block_q, block_k=block_k, scale=scale,
            window=window,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, T_pad // block_q, S_pad // block_k),
            in_specs=[q_spec, kv_spec_q, kv_spec_q, q_spec, row_spec, row_spec],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, i, j, *_: (b, h, i, 0)
            ),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T_pad, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    # dk/dv per query head (grid swaps: k blocks outer, q blocks inner).
    # Mirror of _kv_index_map for the swapped grid: q tiles entirely above
    # the diagonal (no query of the tile sees k tile j) or entirely past
    # the window's reach clamp to the nearest live tile, so dead q/dO/row
    # blocks reuse the previous copy instead of streaming from HBM.
    def _q_idx(head_axis):
        def idx(b, h, j, i, q_start, kv_len):
            k_first = j * block_k
            # first live q tile: its LAST query reaches k_first causally
            lo = jnp.maximum(
                -(-(k_first - q_start[b] - block_q + 1) // block_q), 0
            )
            if window:
                # last live q tile: its FIRST query's window still reaches
                # the k tile's last position (q - window < (j+1)*bk - 1)
                hi = jnp.maximum(
                    ((j + 1) * block_k - 2 + window - q_start[b]) // block_q,
                    lo,
                )
                ii = jnp.clip(i, lo, hi)
            else:
                ii = jnp.maximum(i, lo)
            return (b, head_axis(h), ii, 0)

        return idx

    q_spec_i = pl.BlockSpec((1, 1, block_q, D), _q_idx(lambda h: h))
    kv_spec_i = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, j, i, *_: (b, h // groups, j, 0)
    )
    row_spec_i = pl.BlockSpec((1, 1, block_q, _LANES), _q_idx(lambda h: h))
    dkv_out_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, j, i, *_: (b, h, j, 0)
    )

    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, block_k=block_k, scale=scale,
            window=window,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, S_pad // block_k, T_pad // block_q),
            in_specs=[
                q_spec_i, kv_spec_i, kv_spec_i, q_spec_i, row_spec_i, row_spec_i
            ],
            out_specs=[dkv_out_spec, dkv_out_spec],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S_pad, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, S_pad, D), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    dq = jnp.transpose(dq[:, :, :T], (0, 2, 1, 3))  # [B, T, H, D]
    # GQA: sum each group's query-head contributions into its kv head
    dk_h = dk_h[:, :, :S].reshape(B, K, groups, S, D).sum(axis=2)
    dv_h = dv_h[:, :, :S].reshape(B, K, groups, S, D).sum(axis=2)
    dk = jnp.transpose(dk_h, (0, 2, 1, 3))  # [B, S, K, D]
    dv = jnp.transpose(dv_h, (0, 2, 1, 3))

    # integer inputs (q_start, kv_length) take float0 cotangents
    zero = lambda x: np.zeros(x.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, zero(q_start), zero(kv_length)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash(
    scale, block_q, block_k, interpret, window, q, k, v, q_start, kv_length
):
    out, _ = _fwd_impl(
        q, k, v, q_start, kv_length, scale, block_q, block_k, interpret,
        save_lse=False, window=window,
    )
    return out


def _flash_fwd(
    scale, block_q, block_k, interpret, window, q, k, v, q_start, kv_length
):
    out, lse = _fwd_impl(
        q, k, v, q_start, kv_length, scale, block_q, block_k, interpret,
        save_lse=True, window=window,
    )
    return out, (q, k, v, q_start, kv_length, out, lse)


_flash.defvjp(_flash_fwd, _bwd_impl)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_q", "block_k", "interpret", "window"),
)
def flash_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    q_start: jnp.ndarray,  # [B] int32: absolute position of first query token
    kv_length: jnp.ndarray,  # [B] int32: valid kv prefix (after cache write)
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Causal flash attention against a (possibly longer) KV buffer.

    Same contract as fei_tpu.ops.attention.attention: key position s is
    visible to the query at absolute position p iff s <= p and s < kv_length
    — and, with ``window`` (sliding-window attention), additionally
    s > p - window; the window mask and tile liveness run in the forward
    AND both backward kernels, so SWA training grads match the oracle.
    Returns [B, T, H, D] in q.dtype. Differentiable w.r.t. q/k/v via the
    Pallas flash backward (recompute; O(T·D) memory both ways).
    """
    D = q.shape[-1]
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(
        scale, block_q, block_k, interpret, window, q, k, v, q_start, kv_length
    )
