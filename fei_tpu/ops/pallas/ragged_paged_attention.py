"""Ragged paged attention: mixed prefill+decode rows in ONE kernel.

The legacy kernels (ops/pallas/paged_attention.py) compile one program per
query-block length: ``paged_attention`` (qt=1, decode) and
``paged_attention_block`` (qt=T, chunked prefill / speculative verify). A
serving iteration that interleaves one prefill chunk with one decode scan
therefore issues two programs — and byte-identical resume has to reason
about the ~1-bf16-ulp residual between their fusions (docs/ENGINE.md
"Preempt and resume").

This kernel takes per-row metadata instead: every virtual sequence row
carries ``(limit, q_len)`` scalar-prefetch entries — ``limit`` is the
first query row's causal bound (kv positions < limit are visible, i.e.
start+1 in the block wrapper's convention) and ``q_len`` is how many of
the tile's R query positions are live. Decode rows run with q_len=1,
chunked-prefill rows with q_len up to R, in the SAME invocation over the
shared page pool:

- the page-liveness predicate becomes per-row dynamic
  (``pi*page_size < limit + (q_len-1)`` instead of the static ``qt``),
  so decode rows stop DMAing pages exactly where the single-token kernel
  would and prefill rows read exactly the pages their chunk group covers;
- everything else — online-softmax (m, l, acc) scratch, per-row causal
  mask ``pos < limit + row_t``, int8 scale folding, sliding-window page
  clamp — is the legacy body unchanged, so each row's arithmetic is
  bitwise the row the legacy kernel computes (tests/test_ragged_attention
  pins this per row, greedy and seeded, ms1 and tp2).

Pad rows (t >= q_len) compute garbage that is confined to their own
(m, l, acc) rows and never read back — the same argument the legacy
block kernel already relies on for its padded head groups.

Sharding composes exactly as the legacy kernel: kv heads shard over the
tp axis inside shard_map, and the head outputs are all-gathered INSIDE
the body so the result leaves replicated — GSPMD can never reorder the
downstream ``wo`` psum (see _sharded_paged in paged_attention.py for the
full argument; this module mirrors it verbatim).

Interpret mode on CPU; compiled under Mosaic on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fei_tpu.ops.pallas.paged_attention import NEG_INF, _CompilerParams
from fei_tpu.utils.platform import shard_map


def _ragged_kernel(
    # scalar prefetch
    block_table_ref,  # [Bv, max_pages] page index per (row, slot)
    limit_ref,  # [Bv] first query row's causal bound (kv pos < limit)
    qlen_ref,  # [Bv] live query positions in this row's tile (1..R)
    mode_ref,  # [Bv] 1 = decode row (qt=1 program arithmetic), 0 = prefill
    # blocks: q [1,1,R*G,D], k/v [1,1,page_size,D]; int8 pools add
    # ks/vs [1,1,1,page_size] per-slot scale rows before o [1,1,R*G,D]
    *refs,
    page_size: int,
    scale: float,
    kv_int8: bool,
    g: int = 1,
    window: int = 0,
):
    """Online-softmax ragged attention over one (virtual seq, kv-head)
    tile. Identical to paged_attention._decode_kernel except the static
    ``qt`` becomes the per-row dynamic ``qlen_ref[b]`` — a decode row
    (q_len=1) and a chunk row (q_len=R) predicate their pages
    independently inside one grid.

    ``mode``: the two legacy programs run their dots at different row
    counts (qt=1 → g rows, block → qt*g rows), and small-row matmuls can
    take a different micro-kernel whose accumulation order rounds ~1 ulp
    apart. Bitwise identity to BOTH therefore needs per-row arithmetic
    shape, not just per-row masking: mode=1 rows run the online update
    on the tile's first g rows only (exactly the decode token's head
    group) at the qt=1 program's [g]-row shapes, branch-selected per row
    so neither side pays the other's matmul. mode=0 rows run the
    full-tile update, whose R*g-row blocks are bitwise the block
    program's qt*g-row blocks."""
    if kv_int8:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    pi = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(pi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    limit = limit_ref[b]
    qlive = qlen_ref[b]

    # per-row page liveness: the LAST live query row's causal bound
    page_live = pi * page_size < limit + (qlive - 1)
    if window:  # pages entirely below every row's window are dead
        page_live = jnp.logical_and(
            page_live, (pi + 1) * page_size > limit - window
        )

    @pl.when(page_live)
    def _compute():
        k = k_ref[0, 0]  # [page_size, D]
        v = v_ref[0, 0]
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        acc_prev = acc_ref[:]

        def online(q, m_p, l_p, acc_p):
            """One page's online-softmax update — the legacy kernel body
            verbatim, at whatever row count ``q`` carries."""
            s = jax.lax.dot_general(
                q, k.astype(q.dtype) if kv_int8 else k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [rows, page_size]
            if kv_int8:
                # dequant folds into the score row: k_slot scale is
                # constant along the contracted D axis, so
                # (q·k_int8)·ks == q·(k_int8·ks)
                s = s * ks_ref[0, 0]  # [1, page_size] broadcasts over rows

            pos = pi * page_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            # per-row causal limit: row r is query position (limit-1) + r//g
            row_t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // g
            visible = pos < limit + row_t
            if window:  # sliding window: only the last `window` positions
                visible = jnp.logical_and(
                    visible, pos > limit - 1 + row_t - window
                )
            s = jnp.where(visible, s, NEG_INF)

            m_n = jnp.maximum(m_p, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_n)
            correction = jnp.exp(m_p - m_n)

            l_n = correction * l_p + jnp.sum(p, axis=-1, keepdims=True)
            if kv_int8:
                # fold v's per-slot scale into p (constant along the
                # contracted slot axis per output channel):
                # (p·vs)·v_int8 == p·(v_int8·vs)
                pv = (p * vs_ref[0, 0]).astype(jnp.float32)
                vv = v.astype(jnp.float32)
            else:
                pv = p.astype(v.dtype)
                vv = v
            acc_n = correction * acc_p + jax.lax.dot_general(
                pv, vv,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_n, l_n, acc_n

        q = q_ref[0, 0]  # [R*G, D]
        dec = mode_ref[b] == 1

        def decode_path(_):
            # rows 0..g-1 are the decode token's head group (row_t = 0,
            # same mask) — run exactly the qt=1 program's [g]-row shapes.
            # The tile's padding rows keep their init state: they are
            # never read downstream, and skipping them keeps a decode
            # row's per-page cost at the legacy kernel's, not the tile's.
            m_d, l_d, acc_d = online(
                q[:g], m_prev[:g], l_prev[:g], acc_prev[:g]
            )
            return (
                jnp.concatenate([m_d, m_prev[g:]]),
                jnp.concatenate([l_d, l_prev[g:]]),
                jnp.concatenate([acc_d, acc_prev[g:]]),
            )

        def block_path(_):
            return online(q, m_prev, l_prev, acc_prev)

        m_n, l_n, acc_n = jax.lax.cond(dec, decode_path, block_path, None)
        m_ref[:] = m_n
        l_ref[:] = l_n
        acc_ref[:] = acc_n

    @pl.when(pi == num_pages - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _ragged_call(
    qg: jnp.ndarray,  # [Bv, K, R*g, D] position-major, group-minor rows
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    limits: jnp.ndarray,  # [Bv] first-row causal limit (kv positions < it)
    q_lens: jnp.ndarray,  # [Bv] live query positions per row tile
    modes: jnp.ndarray,  # [Bv] 1 = decode-row arithmetic, 0 = prefill
    *,
    g: int,
    scale: float,
    interpret: bool,
    k_scales: jnp.ndarray | None,
    v_scales: jnp.ndarray | None,
    window: int = 0,
) -> jnp.ndarray:
    """pallas_call plumbing — mirrors paged_attention._paged_call with the
    per-row metadata as scalar-prefetch arrays so the two modules cannot
    drift far."""
    Bv, K, rows, D = qg.shape
    page_size = k_pages.shape[2]
    max_pages = block_table.shape[1]
    kv_int8 = k_scales is not None

    kernel = functools.partial(
        _ragged_kernel, page_size=page_size, scale=scale, kv_int8=kv_int8,
        g=g, window=window,
    )
    if window:
        # clamp dead leading grid steps to the FIRST in-window page:
        # Pallas elides a block copy when consecutive steps map the same
        # index, so pages entirely below every row's window are never
        # DMA'd (see paged_attention._paged_call)
        def _page_idx(b, kh, pi, bt, ln, ql, md):
            first = jnp.maximum((ln[b] - window) // page_size, 0)
            return (bt[b, jnp.maximum(pi, first)], kh, 0, 0)
    else:
        def _page_idx(b, kh, pi, bt, ln, ql, md):
            return (bt[b, pi], kh, 0, 0)

    page_spec = pl.BlockSpec((1, 1, page_size, D), _page_idx)
    scale_spec = pl.BlockSpec((1, 1, 1, page_size), _page_idx)
    row_spec = pl.BlockSpec(
        (1, 1, rows, D),
        lambda b, kh, pi, bt, ln, ql, md: (b, kh, 0, 0),
    )
    in_specs = [row_spec, page_spec, page_spec]
    args = [qg, k_pages, v_pages]
    if kv_int8:
        in_specs += [scale_spec, scale_spec]
        args += [k_scales, v_scales]

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(Bv, K, max_pages),
            in_specs=in_specs,
            out_specs=row_spec,
            scratch_shapes=[
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((Bv, K, rows, D), qg.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        block_table.astype(jnp.int32), limits.astype(jnp.int32),
        q_lens.astype(jnp.int32), modes.astype(jnp.int32), *args,
    )


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "window")
)
def ragged_paged_attention(
    q: jnp.ndarray,  # [Bv, R, H, D] — R query positions per virtual row
    k_pages: jnp.ndarray,  # [P, K, page_size, D] shared page pool
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,  # [Bv, max_pages] int32
    limits: jnp.ndarray,  # [Bv] int32 first-row causal limit (start + 1)
    q_lens: jnp.ndarray,  # [Bv] int32 live query positions (1..R; 0 = dead)
    modes: jnp.ndarray | None = None,  # [Bv] int32 1 = decode row
    scale: float | None = None,
    interpret: bool | None = None,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Mixed prefill+decode paged attention in one invocation.

    Row ``b`` attends its first live query position against kv positions
    ``< limits[b]`` (the block-kernel convention: kv length before the
    row's tokens, plus one), each later position t against
    ``< limits[b] + t``; only positions ``t < q_lens[b]`` are meaningful
    — the rest of the R-row tile computes garbage that callers must not
    read. A decode row is (limits=length+1, q_lens=1, modes=1); a
    prefill-chunk group starting at absolute position ``s`` is
    (limits=s+1, q_lens<=R, modes=0). ``modes`` selects which legacy
    program's arithmetic SHAPE a row reproduces bitwise — mode-1 rows the
    qt=1 decode program's, mode-0 rows the block program's (see
    _ragged_kernel; modes=None means all-prefill). All rows' K/V must
    already be written to the pool. Returns [Bv, R, H, D].
    """
    Bv, R, H, D = q.shape
    K = k_pages.shape[1]
    G = H // K
    if scale is None:
        scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if modes is None:
        modes = jnp.zeros((Bv,), dtype=jnp.int32)

    # rows = t*G + g: position-major, head-group-minor — the kernel's
    # row//G recovers t for the per-row causal limit (same layout as
    # paged_attention_block)
    qg = jnp.swapaxes(q.reshape(Bv, R, K, G, D), 1, 2).reshape(Bv, K, R * G, D)
    out = _ragged_call(
        qg, k_pages, v_pages, block_table, limits, q_lens, modes,
        g=G, scale=scale, interpret=interpret,
        k_scales=k_scales, v_scales=v_scales, window=window,
    )
    return jnp.swapaxes(out.reshape(Bv, K, R, G, D), 1, 2).reshape(Bv, R, H, D)


def ragged_paged_attention_sharded(
    q: jnp.ndarray,  # [Bv, R, H, D]
    k_pages: jnp.ndarray,  # [P, K, page_size, D] (kv-head sharded over tp)
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,
    limits: jnp.ndarray,
    q_lens: jnp.ndarray,
    modes: jnp.ndarray | None = None,
    mesh=None,
    axis_name: str = "tp",
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    window: int = 0,
    dp_axis: str = "dp",
) -> jnp.ndarray:
    """Tensor-parallel ragged attention. kv heads shard over ``axis_name``
    and the head outputs all-gather INSIDE the shard_map body so the
    result leaves replicated — the same GSPMD-psum-ordering defence as
    paged_attention._sharded_paged, which this mirrors. A dp axis splits
    the virtual rows only when they divide evenly (they rarely do for a
    merged prefill+decode batch; rows are independent either way)."""
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        raise ValueError("ragged_paged_attention_sharded needs a mesh")
    if modes is None:
        modes = jnp.zeros((q.shape[0],), dtype=jnp.int32)
    n = mesh.shape.get(axis_name, 1)
    K = k_pages.shape[1]
    if K % n:
        raise ValueError(f"kv heads {K} must divide {axis_name} axis {n}")
    dp = mesh.shape.get(dp_axis, 1)
    batch_axis = dp_axis if (dp > 1 and q.shape[0] % dp == 0) else None
    head_axis = 2  # q's head dim position in [Bv, R, H, D]
    row_spec = P(batch_axis, None, axis_name, None)
    out_spec = P(batch_axis)  # heads replicated after the in-body gather
    page_spec = P(None, axis_name, None, None)
    in_specs = [row_spec, page_spec, page_spec,
                P(batch_axis), P(batch_axis), P(batch_axis), P(batch_axis)]
    args = [q, k_pages, v_pages, block_table, limits, q_lens, modes]
    if k_scales is not None:
        in_specs += [page_spec, page_spec]
        args += [k_scales, v_scales]

    def body(q, kp, vp, bt, ln, ql, md, *scales):
        ks, vs = scales if scales else (None, None)
        out = ragged_paged_attention(
            q, kp, vp, bt, ln, ql, md,
            k_scales=ks, v_scales=vs, window=window,
        )
        if n > 1:
            out = jax.lax.all_gather(
                out, axis_name, axis=head_axis, tiled=True
            )
        return out

    fn = shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_spec,
        # the vma checker can't see through a pallas_call's output
        check_vma=False,
    )
    return fn(*args)
