"""Weight-only int4 matmul as a Pallas TPU kernel.

Decode is weight-streaming-bound: at 8B the int8 weights (~7.5 GB/token)
set the per-token floor, so halving the stream again is the single biggest
single-chip lever. XLA cannot express this well — any jnp formulation of a
nibble-packed matmul reads the packed tensor once per nibble plane (two
dots → int8-equivalent traffic), so the fused kernel is what buys the
bandwidth: each packed block is copied to VMEM once, both nibbles are
sign-extended and group-scaled on the VPU, and two MXU dots accumulate into
a float32 scratch tile.

Layout contract (ops/quant.QTensor4): byte i of ``p`` packs logical
contraction rows i (low nibble) and i + K/2 (high nibble), so the logical
matmul splits into half-contractions with no interleave anywhere:

    out = x[:, :K/2] @ unpack_lo(p) + x[:, K/2:] @ unpack_hi(p)

with per-(group, out-channel) scales applied to the unpacked planes before
the dot (the lo half reads scale rows [:K/(2g)], the hi half the rest —
group boundaries never straddle the half split).

The kernel has no VJP: weight-only quantization is an inference-path
feature (training runs bf16; the reference has no quantization at all —
its LLM sits behind an HTTP API, fei/core/assistant.py:524-530).

Degradation ladder (matches the other serving kernels): CPU runs interpret
mode automatically; FEI_TPU_INT4_KERNEL=0 or a Mosaic compile failure falls
back to the XLA two-dot formulation (correct, half the memory footprint,
int8-equivalent streaming) with a one-time warning.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> CompilerParams (jax 0.5); alias so
# the kernels run on both API generations
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

from fei_tpu.ops.quant import QTensor4, unpack4
from fei_tpu.utils.logging import get_logger
from fei_tpu.utils.platform import shard_map

log = get_logger("ops.int4")

_BLOCK_M = 128
_BLOCK_N = 256
# packed rows per k step. Constraint: the per-block scale slab must have
# >= 8 sublanes (Mosaic block divisibility), i.e. block_k2 % (8*gs) == 0 —
# with gs=128 that means a multiple of 1024. Chosen per-shape below.
_BLOCK_K2_CANDIDATES = (4096, 2048, 1024)

_mosaic_probe_cache: dict[tuple, bool] = {}  # per-(bm,bn,bk2,gs) preflight


def _try(fn) -> Exception | None:
    """Run ``fn``, returning the exception instead of raising (threads
    swallow exceptions; the preflight needs them back on the caller)."""
    try:
        fn()
        return None
    except Exception as e:  # noqa: BLE001 — preflight must never raise
        return e
_kernel_invocations = 0  # fused-kernel dispatches (tests pin kernel vs fallback)


def _kernel(x1_ref, x2_ref, p_ref, slo_ref, shi_ref, o_ref, acc_ref, *, nk: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    lo, hi = unpack4(p_ref[...])  # int32 [bk2, bn] nibble planes
    bk2, bn = lo.shape
    g = slo_ref.shape[0]  # scale rows in this block
    gs = bk2 // g

    def scaled(plane, s_ref):
        w = plane.astype(jnp.float32).reshape(g, gs, bn)
        return (w * s_ref[...][:, None, :]).reshape(bk2, bn).astype(jnp.bfloat16)

    acc_ref[...] += jnp.dot(
        x1_ref[...], scaled(lo, slo_ref), preferred_element_type=jnp.float32
    ) + jnp.dot(
        x2_ref[...], scaled(hi, shi_ref), preferred_element_type=jnp.float32
    )

    @pl.when(k == nk - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k2", "interpret")
)
def _int4_mm_kernel(
    x: jnp.ndarray,  # [M, K] (M already padded to block_m)
    p: jnp.ndarray,  # [K/2, N] packed int8
    s: jnp.ndarray,  # [K/gs, N] fp32
    *,
    block_m: int,
    block_n: int,
    block_k2: int,
    interpret: bool,
) -> jnp.ndarray:
    M, K = x.shape
    K2, N = p.shape
    G = s.shape[0]
    G2 = G // 2
    gs = 2 * K2 // G
    x = x.astype(jnp.bfloat16)
    grid = (M // block_m, N // block_n, K2 // block_k2)
    gblk = block_k2 // gs  # scale rows per k-block

    return pl.pallas_call(
        functools.partial(_kernel, nk=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k2), lambda m, n, k: (m, k)),  # x1
            pl.BlockSpec((block_m, block_k2), lambda m, n, k: (m, k)),  # x2
            pl.BlockSpec((block_k2, block_n), lambda m, n, k: (k, n)),  # p
            pl.BlockSpec((gblk, block_n), lambda m, n, k: (k, n)),  # s_lo
            pl.BlockSpec((gblk, block_n), lambda m, n, k: (k, n)),  # s_hi
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda m, n, k: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(x[:, : K // 2], x[:, K // 2 :], p, s[:G2], s[G2:])


def int4_mm_xla(x: jnp.ndarray, w: QTensor4) -> jnp.ndarray:
    """XLA fallback: two half-contraction dots. Reads the packed bytes once
    per nibble plane (int8-equivalent HBM traffic) but keeps the half-size
    residency; numerics match the kernel (fp32 group-scale, bf16 dot)."""
    K2, N = w.p.shape[-2:]
    G = w.s.shape[-2]
    gs = 2 * K2 // G
    lo, hi = unpack4(w.p)

    def scaled(plane, s_half):
        g_half = s_half.shape[-2]
        grouped = plane.astype(jnp.float32).reshape(
            *plane.shape[:-2], g_half, gs, N
        )
        return (grouped * s_half[..., :, None, :]).reshape(plane.shape).astype(
            jnp.bfloat16
        )

    xb = x.astype(jnp.bfloat16)
    out = jax.lax.dot_general(
        xb[..., :K2], scaled(lo, w.s[..., : G // 2, :]),
        (((xb.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + jax.lax.dot_general(
        xb[..., K2:], scaled(hi, w.s[..., G // 2 :, :]),
        (((xb.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out.astype(x.dtype)


def _pick_blocks(K2: int, N: int, gs: int) -> tuple[int, int] | None:
    """(block_k2, block_n) satisfying Mosaic tiling, or None -> fallback."""
    bn = next((b for b in (_BLOCK_N, 128) if N % b == 0), None)
    bk2 = next(
        (b for b in _BLOCK_K2_CANDIDATES if b <= K2 and K2 % b == 0
         and b % (8 * gs) == 0),
        None,
    )
    return (bk2, bn) if bk2 and bn else None


def _mosaic_ok(block_m: int, block_n: int, block_k2: int, gs: int) -> bool:
    """Per-block-config Mosaic preflight: eagerly compile a one-block
    kernel instance with EXACTLY the requested block shapes OUTSIDE any
    enclosing jit. int4_mm is usually traced inside the engine's jitted
    prefill/decode programs, where pallas_call only *traces* — Mosaic
    compilation happens later at outer-jit compile time, outside any
    try/except here. The probe is ordinary Python at trace time, so a
    Mosaic rejection (VMEM overflow at large blocks, a layout restriction
    at a particular tiling) latches the fallback for that config instead
    of crashing the engine's compiled-call site. Probing the exact
    (bm, bn, bk2, gs) matters: a minimal shape compiling says nothing
    about a 4096-row block's VMEM footprint."""
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu and os.environ.get("FEI_TPU_INT4_PREFLIGHT") != "1":
        return True  # interpret mode: no Mosaic involved
    key = (block_m, block_n, block_k2, gs)
    hit = _mosaic_probe_cache.get(key)
    if hit is not None:
        return hit
    # int4_mm is usually TRACED inside the engine's jitted programs; run
    # mid-trace, the probe arrays would be tracers and block_until_ready
    # would raise AttributeError, silently latching the XLA fallback for
    # every real run (the round-5 chip window measured int4 SLOWER than
    # int8 for exactly this reason). JAX's trace stack is thread-local, so
    # a fresh thread gives the probe a guaranteed-eager context no matter
    # what the caller is tracing. FEI_TPU_INT4_PREFLIGHT=1 forces the probe
    # off-TPU (interpret mode) so the mid-trace path stays testable on CPU.
    def probe():
        x = jnp.zeros((block_m, 2 * block_k2), jnp.bfloat16)
        p = jnp.zeros((block_k2, block_n), jnp.int8)
        s = jnp.zeros((2 * block_k2 // gs, block_n), jnp.float32)
        jax.block_until_ready(_int4_mm_kernel(
            x, p, s, block_m=block_m, block_n=block_n, block_k2=block_k2,
            interpret=not on_tpu,
        ))

    try:
        import threading

        box: list = []
        t = threading.Thread(
            target=lambda: box.append(_try(probe)), name="int4-preflight"
        )
        t.start()
        t.join()
        if box and box[0] is not None:
            raise box[0]
        _mosaic_probe_cache[key] = True
    except Exception as e:
        _mosaic_probe_cache[key] = False
        log.warning(
            "int4 Pallas kernel failed Mosaic preflight for blocks %s (%s); "
            "this config uses the XLA fallback", key, e,
        )
    return _mosaic_probe_cache[key]


def int4_mm_sharded(
    x: jnp.ndarray, w: QTensor4, mesh, axis_name: str = "tp"
) -> jnp.ndarray:
    """Tensor-parallel int4 matmul for OUT-channel-sharded weights.

    XLA cannot auto-partition a pallas_call — under a mesh the global-view
    kernel would all-gather the full packed weight to every device (13
    collectives measured on a tp=2 probe). Same fix as the paged kernels
    (_sharded_paged): shard_map over the tp axis, each device running the
    fused kernel on its local N-shard. The Megatron column-parallel
    contract holds: x replicates over tp, out is N-sharded. The batch dim
    rides dp when it divides (mirroring cache_shardings' conditional).

    Contract-axis-sharded weights (row-parallel wo/w_down) must not be
    QTensor4 at all — eligibility keeps them int8 (nibble pairs span K).
    """
    from jax.sharding import PartitionSpec as P

    batch_axis = (
        "dp"
        if "dp" in mesh.axis_names
        and mesh.shape["dp"] > 1
        and x.shape[0] % mesh.shape["dp"] == 0
        else None
    )
    x_spec = P(batch_axis, *([None] * (x.ndim - 1)))
    w_spec = P(None, axis_name)
    out_spec = P(batch_axis, *([None] * (x.ndim - 2)), axis_name)

    def body(x_loc, p_loc, s_loc):  # names must not shadow the pallas `pl`
        return int4_mm(x_loc, QTensor4(p=p_loc, s=s_loc))

    fn = shard_map(
        body, mesh=mesh, in_specs=(x_spec, w_spec, w_spec),
        out_specs=out_spec,
        check_vma=False,  # the vma checker can't see through a pallas_call
    )
    return fn(x, w.p, w.s)


def int4_mm(x: jnp.ndarray, w: QTensor4) -> jnp.ndarray:
    """``x @ dequant(w)`` for a 2D QTensor4 (leading x dims flattened).

    Routes to the fused Pallas kernel when the shapes tile; XLA fallback
    otherwise (odd shapes, FEI_TPU_INT4_KERNEL=0, or a failed Mosaic
    preflight).
    """
    global _kernel_invocations
    if w.p.ndim != 2:
        raise ValueError(
            f"int4_mm expects a per-layer [K/2, N] QTensor4, got {w.p.shape}"
        )
    *lead, K = x.shape
    K2, N = w.p.shape
    if K != 2 * K2:
        raise ValueError(f"contraction mismatch: x {K} vs packed {2 * K2}")

    blocks = (
        _pick_blocks(K2, N, w.group_size)
        if os.environ.get("FEI_TPU_INT4_KERNEL", "1") != "0"
        else None
    )
    if blocks is None:
        return int4_mm_xla(x, w)
    block_k2, block_n = blocks

    x2d = x.reshape(-1, K)
    M = x2d.shape[0]
    block_m = min(_BLOCK_M, max(8, -(-M // 8) * 8))
    if not _mosaic_ok(block_m, block_n, block_k2, w.group_size):
        return int4_mm_xla(x, w)
    _kernel_invocations += 1
    Mp = -(-M // block_m) * block_m
    if Mp != M:
        x2d = jnp.pad(x2d, ((0, Mp - M), (0, 0)))
    out = _int4_mm_kernel(
        x2d, w.p, w.s,
        block_m=block_m, block_n=block_n, block_k2=block_k2,
        interpret=jax.default_backend() != "tpu",
    )
    return out[:M].reshape(*lead, N).astype(x.dtype)
