"""Pallas TPU kernels — the performance core (SURVEY.md §7 step 4).

The reference has no kernels at all (its FLOPs leave the process over HTTP,
fei/core/assistant.py:524-530); these are the greenfield TPU-native hot ops:

- flash_attention: blockwise causal attention for prefill — O(T) memory,
  online softmax, MXU-shaped [block_q, block_k] score tiles.
- paged_attention: paged-KV decode attention over a block table (legacy
  fixed-query-block programs, kept behind FEI_TPU_ATTENTION=paged).
- ragged_paged_attention: mixed prefill+decode rows — per-row
  (limit, q_len) metadata — in ONE invocation over the paged pool.

Every kernel runs in interpret mode on CPU (the hermetic test mesh) and
compiled on TPU; the XLA-native fei_tpu.ops.attention is the correctness
oracle for both.
"""

from fei_tpu.ops.pallas.flash_attention import flash_attention
from fei_tpu.ops.pallas.paged_attention import paged_attention
from fei_tpu.ops.pallas.ragged_paged_attention import ragged_paged_attention

__all__ = ["flash_attention", "paged_attention", "ragged_paged_attention"]
