"""Grouped-query attention over a static KV cache.

The XLA-native reference path: one batched einsum per score/output contraction
so the MXU sees large matmuls, with causal + cache-length masking folded into
the softmax. The Pallas flash path (fei_tpu.ops.pallas.flash_attention)
replaces this for long prefills; this version is the correctness oracle and
the fallback on CPU test meshes.

Shapes (B=batch, T=query len, S=cache len, H=q heads, K=kv heads, D=head dim):
  q: [B, T, H, D]   k,v: [B, S, K, D]   out: [B, T, H, D]
"""

from __future__ import annotations

import jax.numpy as jnp


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,  # [B, T] absolute position of each query token
    kv_length: jnp.ndarray | int,  # [B] or scalar: valid prefix length of cache
    scale: float | None = None,
    window: int = 0,  # sliding window: 0 = full causal; w = last w positions
) -> jnp.ndarray:
    B, T, H, D = q.shape
    S, K = k.shape[1], k.shape[2]
    groups = H // K
    if scale is None:
        scale = D ** -0.5

    # [B, T, K, G, D] query grouped by kv head
    qg = q.reshape(B, T, K, groups, D)
    scores = jnp.einsum("btkgd,bskd->btkgs", qg.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores * scale

    # mask: key position s is visible to query at absolute position p iff
    # s <= p and s < kv_length (and, sliding-window, s > p - window)
    s_pos = jnp.arange(S)[None, None, :]  # [1, 1, S]
    causal = s_pos <= q_positions[:, :, None]  # [B, T, S]
    if window:
        causal &= s_pos > q_positions[:, :, None] - window
    if isinstance(kv_length, int):
        valid = s_pos < kv_length
    else:
        valid = s_pos < kv_length[:, None, None]
    mask = (causal & valid)[:, :, None, None, :]  # [B, T, 1, 1, S]
    scores = jnp.where(mask, scores, -1e30)

    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("btkgs,bskd->btkgd", probs, v.astype(jnp.float32))
    return out.reshape(B, T, H, D).astype(q.dtype)
