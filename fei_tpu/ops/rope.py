"""Rotary position embeddings (RoPE).

Split-half convention (as used by Llama/Mixtral): the head dim is split into
two halves rotated against each other. Frequencies are precomputed once per
model and indexed by absolute position, so the same function serves prefill
(positions 0..T-1) and decode (a single absolute position per sequence).
"""

from __future__ import annotations

import jax.numpy as jnp


def compute_rope_freqs(head_dim: int, max_seq_len: int, theta: float = 500000.0):
    """Return (cos, sin) tables of shape [max_seq_len, head_dim // 2], fp32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [T, D/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray,  # [B, T, H, D]
    cos: jnp.ndarray,  # [max_seq, D/2]
    sin: jnp.ndarray,
    positions: jnp.ndarray,  # [B, T] absolute positions
) -> jnp.ndarray:
    """Rotate q or k by position-dependent phases. Shape-preserving."""
    d2 = x.shape[-1] // 2
    c = cos[positions][:, :, None, :]  # [B, T, 1, D/2]
    s = sin[positions][:, :, None, :]
    x1, x2 = x[..., :d2], x[..., d2:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = xf1 * c - xf2 * s
    out2 = xf2 * c + xf1 * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
