from fei_tpu.ops.rmsnorm import rms_norm
from fei_tpu.ops.rope import compute_rope_freqs, apply_rope
from fei_tpu.ops.attention import attention

__all__ = ["rms_norm", "compute_rope_freqs", "apply_rope", "attention"]
