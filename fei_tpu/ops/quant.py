"""Weight-only int8/int4 quantization for the stacked Llama/Mixtral pytree.

SURVEY.md §7 hard-part #4: 70B bf16 weights are ~140 GB but a v5e chip has
16 GB HBM — even across a v5e-64 the bf16 layer weights leave little headroom
for KV pages. Weight-only int8 halves weight HBM (and doubles effective
weight-streaming bandwidth, the decode bottleneck) at <0.5% logit error.
Weight-only int4 (QTensor4: nibble-packed, per-group-of-128 scales) halves
it again; the packed matmul is a Pallas kernel (ops/pallas/int4_matmul.py)
because an XLA formulation necessarily reads the packed bytes once per
nibble plane — only a fused kernel streams them once.

Scheme (TPU-first; the reference has no quantization — its LLM runs behind
an HTTP API, fei/core/assistant.py:524-530):

- symmetric per-out-channel scales over the contraction axis (always -2 in
  our [.., in, out] layout), so ``(x @ q) * s == x @ (q * s)`` exactly —
  dequantization commutes with the matmul and is applied to the [.., out]
  result, never materializing a bf16 weight copy.
- int8 values are exactly representable in bf16, so the cast inside ``mm``
  loses nothing; XLA fuses the convert into the dot's weight-stream read.
- norms, router, and embed stay bf16 (tiny, or gather-indexed).

``QTensor`` is a NamedTuple (hence a pytree): it flows through jit/scan/
pjit like any other leaf, and sharding rules apply per-field
(parallel/sharding.py handles the scale's collapsed contraction dim).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# stacked-pytree keys that hold big linear weights (contraction axis -2)
QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}
)

# int4 default group size along the contraction axis (GPTQ/AWQ-standard 128:
# small enough that one outlier can't blow a whole channel's scale, large
# enough that scale bytes stay ~3% of the packed weight)
INT4_GROUP = 128


class QTensor(NamedTuple):
    """int8 weight + per-out-channel scale.

    q: int8, same shape as the original weight [.., in, out]
    s: fp32 scale, original shape with the contraction axis collapsed to 1
       ([.., 1, out]) so it broadcasts over the matmul result.
    """

    q: jnp.ndarray
    s: jnp.ndarray

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # the *logical* dtype callers compute in
        return self.s.dtype


class QTensor4(NamedTuple):
    """Weight-only int4: nibble-packed int8 + per-group scale.

    p: int8, [.., K/2, out] — byte i packs logical contraction rows i (low
       nibble) and i + K/2 (high nibble). Pairing rows a half apart (not
       adjacent rows) means unpacking never interleaves: the matmul is
       ``x[:, :K/2] @ lo + x[:, K/2:] @ hi``, so both the Pallas kernel and
       the XLA fallback split cleanly into two half-contractions while the
       packed bytes stream from HBM once (kernel) at half int8's footprint.
    s: fp32 scale, [.., K/group, out] — row g scales logical contraction
       rows [g*group, (g+1)*group). Group boundaries never straddle the
       half split (K/2 is kept a multiple of the group size), so the lo
       half reads scale rows [:K/(2g)] and the hi half the rest.

    The group size is not stored: it is recovered as
    ``2 * p.shape[-2] // s.shape[-2]``. Distinguished from QTensor
    structurally by the grouped scale axis (QTensor's is collapsed to 1).
    """

    p: jnp.ndarray
    s: jnp.ndarray

    @property
    def shape(self):  # the *logical* unpacked shape
        return (*self.p.shape[:-2], self.p.shape[-2] * 2, self.p.shape[-1])

    @property
    def dtype(self):  # the *logical* dtype callers compute in
        return self.s.dtype

    @property
    def group_size(self) -> int:
        return 2 * self.p.shape[-2] // self.s.shape[-2]


def quantize(w: jnp.ndarray, contract_axis: int = -2) -> QTensor:
    """Symmetric int8 with per-out-channel scale over ``contract_axis``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=contract_axis, keepdims=True)
    s = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


def quantize4(w: jnp.ndarray, group: int = INT4_GROUP) -> QTensor4:
    """Symmetric int4 (±7) with per-(group, out-channel) scale over the
    contraction axis (-2). Requires K divisible by 2*group so nibble pairs
    and scale groups both split cleanly at K/2."""
    K = w.shape[-2]
    if K % (2 * group) != 0:
        raise ValueError(
            f"int4 contraction dim {K} must be divisible by 2*group={2 * group}"
        )
    G = K // group
    w32 = w.astype(jnp.float32)
    grouped = w32.reshape(*w.shape[:-2], G, group, w.shape[-1])
    amax = jnp.max(jnp.abs(grouped), axis=-2)  # [.., G, out]
    s = jnp.where(amax == 0.0, 1.0, amax / 7.0)
    q = jnp.clip(
        jnp.round(grouped / s[..., :, None, :]), -7, 7
    ).astype(jnp.int8).reshape(w.shape)
    lo, hi = q[..., : K // 2, :], q[..., K // 2 :, :]
    packed = ((hi << 4) | (lo & 0xF)).astype(jnp.int8)
    return QTensor4(p=packed, s=s)


def unpack4(p: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Packed int8 -> (lo, hi) int32 nibble planes, sign-extended."""
    p32 = p.astype(jnp.int32)
    return (p32 << 28) >> 28, p32 >> 4


def quantize_embed(w: jnp.ndarray) -> QTensor:
    """Symmetric int8 embedding table with per-ROW scales (q [V, h],
    s [V, 1]). The lookup is a gather (row + its scale); a tied LM head
    consumes it exactly via result-side column scaling:
    ``x @ (q*s).T == (x @ q.T) * s.T`` since each scale is constant along
    the contraction. Halves embed HBM — and for tie_embeddings models,
    halves the LM-head weight stream (the decode bottleneck's last bf16
    holdout)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-1, keepdims=True)
    s = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


def embed_lookup(embed, ids, dtype):
    """Row gather for a plain or row-quantized (quantize_embed) table."""
    if isinstance(embed, QTensor):
        rows = embed.q[ids].astype(jnp.float32) * embed.s[ids]
        return rows.astype(dtype)
    return embed[ids].astype(dtype)


def tied_logits(x, embed):
    """``x @ embed.T`` for a plain or row-quantized table (fp32 out)."""
    if isinstance(embed, QTensor):
        out = x @ embed.q.T.astype(x.dtype)
        return out.astype(jnp.float32) * embed.s[:, 0].astype(jnp.float32)
    return (x @ embed.T.astype(x.dtype)).astype(jnp.float32)


def dequantize(w, dtype=jnp.bfloat16):
    """QTensor/QTensor4 -> dense array; identity on plain arrays."""
    if isinstance(w, QTensor):
        return (w.q.astype(jnp.float32) * w.s).astype(dtype)
    if isinstance(w, QTensor4):
        lo, hi = unpack4(w.p)
        q = jnp.concatenate([lo, hi], axis=-2).astype(jnp.float32)
        G, gs = w.s.shape[-2], w.group_size
        grouped = q.reshape(*q.shape[:-2], G, gs, q.shape[-1])
        return (grouped * w.s[..., :, None, :]).reshape(q.shape).astype(dtype)
    return w if w.dtype == dtype else w.astype(dtype)


def mm(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for plain or quantized weights.

    For QTensor the scale is applied to the matmul *result* (exact, since
    the scale is constant along the contraction), so only the int8 tensor
    streams from HBM.
    """
    if isinstance(w, QTensor):
        out = x @ w.q.astype(x.dtype)
        # s: [.., 1, out] -> broadcast over x's leading dims on the result
        return out * jnp.squeeze(w.s, axis=-2).astype(x.dtype)
    if isinstance(w, QTensor4):
        from fei_tpu.ops.pallas.int4_matmul import int4_mm

        return int4_mm(x, w)
    return x @ w


def wcast(w, dtype) -> jnp.ndarray:
    """The raw weight for an einsum/ragged_dot operand: int8 cast to the
    compute dtype for QTensor (scale applied separately to the result via
    scale_expert_out / scale_rows), passthrough otherwise."""
    if isinstance(w, QTensor):
        return w.q.astype(dtype)
    if isinstance(w, QTensor4):  # moe experts are kept int8 (_int4_ok)
        raise TypeError("QTensor4 has no raw-operand form; use mm/dequantize")
    return w


def scale_expert_out(out: jnp.ndarray, w, expert_axis: int) -> jnp.ndarray:
    """Apply a stacked-expert QTensor scale ([E, 1, out]) to an einsum
    result whose last axis is the out dim and ``expert_axis`` indexes
    experts. Exact (scale is constant along the contraction); no-op for
    plain arrays. Must run BEFORE any nonlinearity."""
    if not isinstance(w, QTensor):
        return out
    s = jnp.squeeze(w.s, axis=-2)  # [E, out]
    shape = [1] * out.ndim
    shape[expert_axis] = s.shape[0]
    shape[-1] = s.shape[1]
    return out * s.reshape(shape).astype(out.dtype)


def scale_rows(out: jnp.ndarray, w, expert_ids: jnp.ndarray) -> jnp.ndarray:
    """Per-row scale for grouped-GEMM (ragged_dot) results: row i belongs
    to expert ``expert_ids[i]``, so it picks that expert's [out] scale."""
    if not isinstance(w, QTensor):
        return out
    s = jnp.squeeze(w.s, axis=-2)  # [E, out]
    return out * jnp.take(s, expert_ids, axis=0).astype(out.dtype)


def _int4_ok(key: str, w, moe: bool) -> bool:
    """Whether a big-linear leaf takes int4 in mixed int4/int8 mode.

    lm_head stays int8 by default (the final projection is the most
    scale-sensitive linear — standard GPTQ/AWQ practice;
    FEI_TPU_INT4_LM_HEAD=1 opts it in for another ~6% off the 8B stream)
    and stacked MoE experts stay int8 (the einsum/ragged-dot expert paths
    consume raw int8 planes via wcast; a nibble-packed operand has no
    ragged_dot formulation). Both still halve bf16; everything else halves
    again.
    """
    import os

    if key == "lm_head" and os.environ.get("FEI_TPU_INT4_LM_HEAD") != "1":
        return False
    if moe and key in ("w_gate", "w_up", "w_down"):
        return False
    return w.shape[-2] % (2 * INT4_GROUP) == 0


def quantize_params(params: dict, bits: int = 8) -> dict:
    """Quantize the big linear weights of a stacked param pytree in place
    of their bf16 leaves. Norms/router/embed are left untouched.
    ``bits=4``: int4 where eligible (see _int4_ok), int8 elsewhere."""
    moe = isinstance(params.get("layers"), dict) and "router" in params["layers"]

    def walk(tree):
        if isinstance(tree, dict):
            return {
                k: (
                    quantize4(v)
                    if bits == 4 and _int4_ok(k, v, moe)
                    else quantize(v)
                )
                if k in QUANT_KEYS and not isinstance(v, (QTensor, QTensor4))
                else walk(v)
                for k, v in tree.items()
            }
        return tree

    return walk(params)


def dequantize_params(params: dict, dtype=jnp.bfloat16) -> dict:
    def walk(tree):
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return (
            dequantize(tree, dtype)
            if isinstance(tree, (QTensor, QTensor4))
            else tree
        )

    return walk(params)


def param_bytes(params) -> int:
    """Total device bytes of a (possibly quantized) param pytree."""
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
    )
