"""Weight-only int8 quantization for the stacked Llama/Mixtral pytree.

SURVEY.md §7 hard-part #4: 70B bf16 weights are ~140 GB but a v5e chip has
16 GB HBM — even across a v5e-64 the bf16 layer weights leave little headroom
for KV pages. Weight-only int8 halves weight HBM (and doubles effective
weight-streaming bandwidth, the decode bottleneck) at <0.5% logit error.

Scheme (TPU-first; the reference has no quantization — its LLM runs behind
an HTTP API, fei/core/assistant.py:524-530):

- symmetric per-out-channel scales over the contraction axis (always -2 in
  our [.., in, out] layout), so ``(x @ q) * s == x @ (q * s)`` exactly —
  dequantization commutes with the matmul and is applied to the [.., out]
  result, never materializing a bf16 weight copy.
- int8 values are exactly representable in bf16, so the cast inside ``mm``
  loses nothing; XLA fuses the convert into the dot's weight-stream read.
- norms, router, and embed stay bf16 (tiny, or gather-indexed).

``QTensor`` is a NamedTuple (hence a pytree): it flows through jit/scan/
pjit like any other leaf, and sharding rules apply per-field
(parallel/sharding.py handles the scale's collapsed contraction dim).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# stacked-pytree keys that hold big linear weights (contraction axis -2)
QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}
)


class QTensor(NamedTuple):
    """int8 weight + per-out-channel scale.

    q: int8, same shape as the original weight [.., in, out]
    s: fp32 scale, original shape with the contraction axis collapsed to 1
       ([.., 1, out]) so it broadcasts over the matmul result.
    """

    q: jnp.ndarray
    s: jnp.ndarray

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # the *logical* dtype callers compute in
        return self.s.dtype


def quantize(w: jnp.ndarray, contract_axis: int = -2) -> QTensor:
    """Symmetric int8 with per-out-channel scale over ``contract_axis``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=contract_axis, keepdims=True)
    s = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


def dequantize(w, dtype=jnp.bfloat16):
    """QTensor -> dense array; identity on plain arrays."""
    if isinstance(w, QTensor):
        return (w.q.astype(jnp.float32) * w.s).astype(dtype)
    return w if w.dtype == dtype else w.astype(dtype)


def mm(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for plain or quantized weights.

    For QTensor the scale is applied to the matmul *result* (exact, since
    the scale is constant along the contraction), so only the int8 tensor
    streams from HBM.
    """
    if isinstance(w, QTensor):
        out = x @ w.q.astype(x.dtype)
        # s: [.., 1, out] -> broadcast over x's leading dims on the result
        return out * jnp.squeeze(w.s, axis=-2).astype(x.dtype)
    return x @ w


def wcast(w, dtype) -> jnp.ndarray:
    """The raw weight for an einsum/ragged_dot operand: int8 cast to the
    compute dtype for QTensor (scale applied separately to the result via
    scale_expert_out / scale_rows), passthrough otherwise."""
    if isinstance(w, QTensor):
        return w.q.astype(dtype)
    return w


def scale_expert_out(out: jnp.ndarray, w, expert_axis: int) -> jnp.ndarray:
    """Apply a stacked-expert QTensor scale ([E, 1, out]) to an einsum
    result whose last axis is the out dim and ``expert_axis`` indexes
    experts. Exact (scale is constant along the contraction); no-op for
    plain arrays. Must run BEFORE any nonlinearity."""
    if not isinstance(w, QTensor):
        return out
    s = jnp.squeeze(w.s, axis=-2)  # [E, out]
    shape = [1] * out.ndim
    shape[expert_axis] = s.shape[0]
    shape[-1] = s.shape[1]
    return out * s.reshape(shape).astype(out.dtype)


def scale_rows(out: jnp.ndarray, w, expert_ids: jnp.ndarray) -> jnp.ndarray:
    """Per-row scale for grouped-GEMM (ragged_dot) results: row i belongs
    to expert ``expert_ids[i]``, so it picks that expert's [out] scale."""
    if not isinstance(w, QTensor):
        return out
    s = jnp.squeeze(w.s, axis=-2)  # [E, out]
    return out * jnp.take(s, expert_ids, axis=0).astype(out.dtype)


def quantize_params(params: dict) -> dict:
    """Quantize the big linear weights of a stacked param pytree in place
    of their bf16 leaves. Norms/router/embed are left untouched."""

    def walk(tree):
        if isinstance(tree, dict):
            return {
                k: quantize(v)
                if k in QUANT_KEYS and not isinstance(v, QTensor)
                else walk(v)
                for k, v in tree.items()
            }
        return tree

    return walk(params)


def dequantize_params(params: dict, dtype=jnp.bfloat16) -> dict:
    def walk(tree):
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return dequantize(tree, dtype) if isinstance(tree, QTensor) else tree

    return walk(params)


def param_bytes(params) -> int:
    """Total device bytes of a (possibly quantized) param pytree."""
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
    )
