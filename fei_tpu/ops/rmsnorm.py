"""RMSNorm. XLA fuses this into neighbouring ops on TPU; the Pallas fused
variant (ops/pallas/) is only used where fusion boundaries block it."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5,
    offset: bool = False,
) -> jnp.ndarray:
    """y = x / rms(x) * weight, computed in fp32 for stability, cast back.

    ``offset=True`` multiplies by (1 + weight) instead — the Gemma-family
    convention, whose checkpoints store norm weights zero-centered."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if offset:
        w = 1.0 + w
    return (y * w).astype(dtype)
