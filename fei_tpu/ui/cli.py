"""CLI: interactive REPL, one-shot message, continuous task mode, subcommands.

Parity with the reference's fei/ui/cli.py:60-786 (REPL with exit/clear/
history commands, history persistence, --task mode wrapping TaskExecutor,
history/mcp subcommands), with the provider defaulting to the in-tree
``jax_local`` TPU backend and tokens streamed to stdout as they decode.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from collections import deque

from fei_tpu.utils.logging import get_logger, setup_logging

log = get_logger("ui.cli")

HISTORY_DIR = os.path.expanduser("~/.fei_tpu")
HISTORY_FILE = os.path.join(HISTORY_DIR, "history.json")
HISTORY_MAX = 100


class History:
    """Rolling JSON history of prompts/responses (parity: cli.py:68-137)."""

    def __init__(self, path: str | None = None, maxlen: int = HISTORY_MAX):
        # resolved at call time so tests can repoint HISTORY_FILE
        self.path = path or HISTORY_FILE
        self.entries: deque = deque(maxlen=maxlen)
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as fh:
                for entry in json.load(fh):
                    self.entries.append(entry)
        except (OSError, ValueError):
            pass

    def save(self) -> None:
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            with open(self.path, "w") as fh:
                json.dump(list(self.entries), fh, indent=1)
        except OSError as exc:
            log.warning("could not persist history: %s", exc)

    def add(self, prompt: str, response: str) -> None:
        self.entries.append(
            {"ts": time.time(), "prompt": prompt, "response": response[:4000]}
        )
        self.save()


def build_assistant(args):
    from fei_tpu.agent import Assistant
    from fei_tpu.tools import ToolRegistry, create_code_tools

    registry = ToolRegistry()
    create_code_tools(registry)
    if getattr(args, "memory_tools", False):
        from fei_tpu.tools.memory_tools import create_memory_tools

        create_memory_tools(registry)
    try:
        from fei_tpu.agent.mcp import MCPManager, register_mcp_tools

        register_mcp_tools(registry, MCPManager())
    except Exception as exc:  # noqa: BLE001 — MCP is optional at startup
        log.warning("mcp tools unavailable: %s", exc)
    streamed: list[str] = []
    on_text = None
    if not getattr(args, "no_stream", False):
        def on_text(delta: str) -> None:
            streamed.append(delta)
            sys.stdout.write(delta)
            sys.stdout.flush()
    assistant = Assistant(
        provider=args.provider,
        model=args.model,
        tool_registry=registry,
        max_tool_rounds=args.max_tool_rounds,
        max_tokens=args.max_tokens,
        on_text=on_text,
    )
    assistant._streamed = streamed
    return assistant


def emit_final(assistant, response: str) -> None:
    """Print a turn's final text, accounting for what streaming already
    showed: in streaming mode, text that never went through on_text (salvaged
    tool output, post-tool-round content) is still printed."""
    if assistant.on_text is None:
        print(response)
        return
    streamed = "".join(getattr(assistant, "_streamed", []))
    print()
    # whitespace-normalized containment: multi-round responses join with
    # newlines the stream never carried
    norm = lambda s: " ".join(s.split())  # noqa: E731
    if response.strip() and norm(response) not in norm(streamed):
        print(response)
    getattr(assistant, "_streamed", []).clear()


def process_single_message(assistant, message: str, history: History) -> int:
    response = asyncio.run(assistant.chat(message))
    emit_final(assistant, response)
    history.add(message, response)
    return 0


def process_continuous_task(assistant, task: str, max_iterations: int,
                            history: History) -> int:
    from fei_tpu.agent import TaskExecutor

    # Task mode prints each iteration's cleaned response instead of streaming
    # raw text — streaming would show the [TASK_COMPLETE] protocol marker.
    assistant.on_text = None
    executor = TaskExecutor(assistant, max_iterations=max_iterations)
    ctx = asyncio.run(executor.execute_task(task))
    for i, resp in enumerate(ctx.responses, 1):
        print(f"--- iteration {i} ---\n{resp}")
    print(
        f"\n[task {'completed' if ctx.completed else 'stopped'} after "
        f"{ctx.iterations} iteration(s), {ctx.duration_s:.1f}s]",
        file=sys.stderr,
    )
    history.add(f"[task] {task}", ctx.final_response)
    return 0 if ctx.completed else 1


def chat_loop(assistant, history: History) -> int:
    print("fei_tpu interactive chat — 'exit' to quit, 'clear' to reset, "
          "'history' to list past prompts.")
    while True:
        try:
            line = input("\nyou> ").strip()
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not line:
            continue
        if line in ("exit", "quit"):
            return 0
        if line == "clear":
            assistant.reset()
            print("[conversation cleared]")
            continue
        if line == "history":
            for i, e in enumerate(history.entries):
                print(f"{i:3d}. {e['prompt'][:80]}")
            continue
        print("fei> ", end="", flush=True)
        try:
            response = asyncio.run(assistant.chat(line))
            emit_final(assistant, response)
            history.add(line, response)
        except KeyboardInterrupt:
            print("\n[interrupted]")


def handle_history_command(args) -> int:
    history = History()
    if args.history_action == "list":
        for i, e in enumerate(history.entries):
            stamp = time.strftime("%Y-%m-%d %H:%M", time.localtime(e["ts"]))
            print(f"{i:3d}. [{stamp}] {e['prompt'][:100]}")
    elif args.history_action == "show":
        idx = args.index
        if 0 <= idx < len(history.entries):
            e = list(history.entries)[idx]
            print(f"prompt: {e['prompt']}\n\nresponse:\n{e['response']}")
        else:
            print(f"no history entry {idx}", file=sys.stderr)
            return 1
    elif args.history_action == "clear":
        history.entries.clear()
        history.save()
        print("history cleared")
    elif args.history_action == "load":
        # replay an entry into a fresh conversation, then continue
        # interactively (parity: reference cli.py:479-515)
        idx = args.index
        if not 0 <= idx < len(history.entries):
            print(f"no history entry {idx}", file=sys.stderr)
            return 1
        entry = list(history.entries)[idx]
        try:
            assistant = build_assistant(args)
        except Exception as exc:  # noqa: BLE001
            print(f"error: {exc}", file=sys.stderr)
            return 2
        assistant.conversation.add_user_message(entry["prompt"])
        assistant.conversation.add_assistant_message(entry["response"])
        print(f"[loaded entry {idx}]\nyou> {entry['prompt']}\n"
              f"fei> {entry['response']}")
        return chat_loop(assistant, history)
    return 0


# discovery methods MCP servers variously answer (parity: the reference's
# check_mcp_methods.py:1-102 probe script, without its hardcoded API key)
_PROBE_METHODS = [
    "initialize", "tools/list", "listTools", "list_tools",
    "resources/list", "prompts/list", "rpc.discover", "system.listMethods",
]


def handle_mcp_probe(args, manager) -> int:
    """Probe which discovery methods a configured MCP service answers."""
    service = args.service
    if not service:
        print("usage: fei mcp probe <service>", file=sys.stderr)
        return 2
    found = 0
    for method in _PROBE_METHODS:
        try:
            result = manager.client.call_service(service, method, {})
            found += 1
            blob = json.dumps(result, default=str)
            print(f"✓ {method}: {blob[:200]}{'…' if len(blob) > 200 else ''}")
        except Exception as exc:  # noqa: BLE001 — probing expects failures
            print(f"✗ {method}: {exc}")
    print(f"\n{found}/{len(_PROBE_METHODS)} discovery methods answered")
    return 0 if found else 1


def handle_mcp_command(args) -> int:
    from fei_tpu.agent.mcp import MCPManager

    manager = MCPManager()
    if args.mcp_action == "probe":
        try:
            return handle_mcp_probe(args, manager)
        finally:
            manager.close()
    if args.mcp_action == "list":
        if not manager.client.servers:
            print("no mcp servers configured (set FEI_TPU_MCP_SERVER_<NAME> "
                  "or [mcp] server_<name> in the config file)")
        for name, spec in manager.client.servers.items():
            target = spec.url or " ".join(spec.command)
            print(f"{name:20s} {spec.type:6s} {target}")
    elif args.mcp_action == "call":
        if not args.service or not args.method:
            print("usage: fei mcp call <service> <method> [--params JSON]",
                  file=sys.stderr)
            return 2
        params = json.loads(args.params) if args.params else {}
        result = manager.client.call_service(args.service, args.method, params)
        print(json.dumps(result, indent=2, default=str))
    manager.close()
    return 0


def _extract_search_results(result) -> list[dict]:
    """Normalize brave/MCP search payload shapes into [{title,url,description}].
    (The reference parsed the same two shapes, fei/ui/cli.py:640-672.)"""
    if not isinstance(result, dict):
        return []
    web = result.get("web")
    rows = web.get("results") if isinstance(web, dict) else None
    if rows is None:
        rows = result.get("results")
    if rows is None and "content" in result:
        # MCP text content envelope: one blob, keep as a single pseudo-result
        content = result["content"]
        if isinstance(content, list):
            text = "\n".join(
                c.get("text", "") for c in content if isinstance(c, dict)
            )
        else:
            text = str(content)
        return [{"title": "search results", "url": "", "description": text}]
    out = []
    for r in rows or []:
        if isinstance(r, dict):
            out.append({
                "title": str(r.get("title", "")),
                "url": str(r.get("url", "")),
                "description": str(r.get("description", r.get("snippet", ""))),
            })
    return out


def run_search(query: str, count: int = 5, manager=None) -> list[dict]:
    """Direct web search through the MCP brave service (falls back to the
    direct REST API inside the service when no MCP server is configured;
    unlike the reference there is NO hardcoded fallback API key —
    ref fei/ui/cli.py:589 is a catalogued defect)."""
    from fei_tpu.agent.mcp import MCPManager

    own = manager is None
    manager = manager or MCPManager()
    try:
        result = manager.brave_search.web_search(query, count=count)
        return _extract_search_results(result)
    finally:
        if own:
            manager.close()


def handle_search_command(args) -> int:
    try:
        results = run_search(args.query, count=args.count)
    except Exception as exc:  # noqa: BLE001 — network/MCP errors must be readable
        print(f"search failed: {exc}", file=sys.stderr)
        return 1
    if not results:
        print("no results")
        return 0
    for i, r in enumerate(results, 1):
        print(f"{i}. {r['title']}\n   {r['url']}\n   {r['description']}\n")
    return 0


ASK_PROMPT = """Answer the question using the web search results below.
Cite result numbers like [1] where they support your answer. If the results
are insufficient, say what is missing.

Search results for: {query}
{results}

Question: {query}"""


def handle_ask_command(args) -> int:
    """Search-stuffed one-shot ask (parity: ref fei/ui/cli.py:623-728)."""
    results: list[dict] = []
    if not args.no_search:
        try:
            results = run_search(args.query, count=args.count)
        except Exception as exc:  # noqa: BLE001
            print(f"[search unavailable: {exc}]", file=sys.stderr)
    if results:
        blob = "\n".join(
            f"[{i}] {r['title']} — {r['url']}\n    {r['description']}"
            for i, r in enumerate(results, 1)
        )
        prompt = ASK_PROMPT.format(query=args.query, results=blob)
    else:
        prompt = args.query
    try:
        assistant = build_assistant(args)
    except Exception as exc:  # noqa: BLE001
        print(f"error: {exc}", file=sys.stderr)
        return 2
    response = asyncio.run(assistant.chat(prompt))
    emit_final(assistant, response)
    History().add(f"[ask] {args.query}", response)
    return 0


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="fei", description="fei_tpu — TPU-native coding assistant"
    )
    p.add_argument("--message", "-m", help="one-shot message, print reply and exit")
    p.add_argument("--task", "-t", help="continuous task executed until [TASK_COMPLETE]")
    p.add_argument("--provider", default=None,
                   help="jax_local (default, in-tree TPU), mock, or a remote provider name")
    p.add_argument("--model", default=None, help="model name/config for the provider")
    p.add_argument("--max-iterations", type=int, default=10, help="task mode iteration cap")
    p.add_argument("--max-tool-rounds", type=int, default=8)
    p.add_argument("--max-tokens", type=int, default=4000)
    p.add_argument("--no-stream", action="store_true", help="print whole replies, not token stream")
    p.add_argument("--stats", action="store_true",
                   help="print engine/agent span timings and counters after the turn")
    p.add_argument("--memory-tools", action="store_true", help="register memdir memory tools")
    p.add_argument("--log-level", default=None)
    sub = p.add_subparsers(dest="command")
    hist = sub.add_parser("history", help="inspect saved prompt history")
    hist.add_argument("history_action", choices=["list", "show", "clear", "load"])
    hist.add_argument("index", nargs="?", type=int, default=0)
    mcp = sub.add_parser("mcp", help="MCP service operations")
    mcp.add_argument("mcp_action", choices=["list", "call", "probe"])
    mcp.add_argument("service", nargs="?")
    mcp.add_argument("method", nargs="?")
    mcp.add_argument("--params", help="JSON params for mcp call")
    search = sub.add_parser("search", help="direct web search (brave via MCP)")
    search.add_argument("query")
    search.add_argument("--count", type=int, default=5)
    ask = sub.add_parser(
        "ask", help="one-shot question answered with web-search context"
    )
    ask.add_argument("query")
    ask.add_argument("--count", type=int, default=5)
    ask.add_argument("--no-search", action="store_true",
                     help="skip the search step, ask the model directly")
    serve = sub.add_parser(
        "serve",
        help="OpenAI-compatible /v1/chat/completions endpoint over the "
             "jax_local serving stack (model via the root --model flag: "
             "fei --model NAME serve)",
    )
    serve.add_argument("--host", default=None)
    serve.add_argument("--port", type=int, default=None)
    serve.add_argument("--api-key", default=None)
    return p.parse_args(argv)


def main(argv: list[str] | None = None) -> int:
    args = parse_args(argv)
    setup_logging(level=args.log_level)
    if args.command == "history":
        return handle_history_command(args)
    if args.command == "mcp":
        return handle_mcp_command(args)
    if args.command == "search":
        return handle_search_command(args)
    if args.command == "ask":
        return handle_ask_command(args)
    if args.command == "serve":
        # thin passthrough: server.py owns the flag defaults
        from fei_tpu.ui.server import main as serve_main

        serve_argv = []
        if args.host:
            serve_argv += ["--host", args.host]
        if args.port is not None:
            serve_argv += ["--port", str(args.port)]
        if args.model:
            serve_argv += ["--model", args.model]
        if args.api_key:
            serve_argv += ["--api-key", args.api_key]
        return serve_main(serve_argv)
    history = History()
    try:
        assistant = build_assistant(args)
    except Exception as exc:  # noqa: BLE001 — startup errors must be readable
        print(f"error: {exc}", file=sys.stderr)
        return 2
    try:
        if args.message:
            return process_single_message(assistant, args.message, history)
        if args.task:
            return process_continuous_task(
                assistant, args.task, args.max_iterations, history
            )
        return chat_loop(assistant, history)
    finally:
        if getattr(args, "stats", False):
            print_stats(assistant)


def print_stats(assistant=None) -> None:
    """Span timings + histograms + counters + token usage to stderr
    (observability the reference lacks entirely — SURVEY §5 'Tracing/
    profiling: none'). Rendering is shared with the TUI's /metrics command
    (fei_tpu/obs/render.py) so both UIs show the same table."""
    from fei_tpu.obs.render import snapshot_lines
    from fei_tpu.utils.metrics import METRICS

    print("\n-- stats ----------------------------------------", file=sys.stderr)
    if assistant is not None and getattr(assistant, "last_usage", None):
        u = assistant.last_usage
        print(f"tokens: prompt={u.get('prompt_tokens', 0)} "
              f"completion={u.get('completion_tokens', 0)}", file=sys.stderr)
    for line in snapshot_lines(METRICS.snapshot()):
        print(line, file=sys.stderr)
