"""CLI placeholder — replaced by the full REPL/task CLI later this build.

Exists so the ``fei`` console script and ``python -m fei_tpu`` fail with a
clear message instead of ModuleNotFoundError while the agent/UI layers land.
"""

from __future__ import annotations

import sys


def main(argv: list[str] | None = None) -> int:
    sys.stderr.write(
        "fei_tpu CLI: agent/UI layer not built yet in this checkout; "
        "the engine is available via fei_tpu.engine.InferenceEngine\n"
    )
    return 2
