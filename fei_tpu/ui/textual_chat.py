"""Full-screen TUI chat (reference: fei/ui/textual_chat.py:231-1070).

Same capability contract as the reference's Textual app — message panels,
slash-command autocomplete, the full ``/mem`` memory-command suite, async
assistant calls with a live streaming panel — built on prompt_toolkit's
full-screen Application + rich rendering (both in the base image; Textual is
not, and a TUI must not drag in new deps per the build constraints).

Key design points:
- The chat log is a list of ChatMessage records rendered through rich
  (Markdown inside Panels) into ANSI text that prompt_toolkit displays; a
  render cache keeps scrolling cheap.
- Assistant calls run as asyncio tasks on prompt_toolkit's own event loop;
  the decoder's on_text stream appends to a live "typing" message and
  invalidates the app, so tokens appear as they decode (the reference renders
  only whole messages — streaming is the north-star addition).
- ``/mem`` commands dispatch to MemoryToolHandlers directly (same layer the
  reference TUI calls, textual_chat.py:557-970), with the Memdir server
  auto-started on first use via the connector.
"""

from __future__ import annotations

import argparse
import asyncio
import io
import json
import shlex
from dataclasses import dataclass, field

from fei_tpu.utils.logging import get_logger, setup_logging

log = get_logger("ui.textual_chat")

MEM_COMMANDS = {
    "help": "show /mem usage",
    "list": "[folder] [status] — list memories",
    "search": "<query> — search memories (memdir query language)",
    "view": "<id> — show one memory with content",
    "save": "<content...> [#tag1,tag2] [subject=...] — save a memory",
    "tag": "<tag> — search by tag",
    "delete": "<id> [--hard] — trash (or purge) a memory",
    "server": "start|stop|status — manage the memdir server",
}


@dataclass
class ChatMessage:
    """One chat panel (reference ChatMessage textual_chat.py:48-92)."""

    role: str  # 'user' | 'assistant' | 'system' | 'memory'
    content: str
    live: bool = False  # still streaming

    _cache: tuple[str, str] | None = field(default=None, repr=False)

    def render_ansi(self, width: int) -> str:
        key = (self.content, width)
        if self._cache and self._cache[0] == key:
            return self._cache[1]
        try:
            from rich.console import Console
            from rich.markdown import Markdown
            from rich.panel import Panel

            styles = {
                "user": ("bold cyan", "you"),
                "assistant": ("bold green", "fei"),
                "memory": ("bold magenta", "mem"),
                "system": ("bold yellow", "sys"),
            }
            style, title = styles.get(self.role, ("white", self.role))
            body = Markdown(self.content) if self.role == "assistant" else self.content
            buf = io.StringIO()
            console = Console(file=buf, force_terminal=True, width=max(20, width))
            console.print(Panel(body, title=title, border_style=style, expand=True))
            out = buf.getvalue()
        except Exception:  # rendering must never kill the UI
            out = f"[{self.role}] {self.content}\n"
        self._cache = (key, out)
        return out


class MemCommandCompleter:
    """Slash-command completion (reference MemoryCommandSuggester :119-198).

    prompt_toolkit Completer duck-type: yields completions for '/mem <sub>'.
    """

    def get_completions(self, document, complete_event):
        from prompt_toolkit.completion import Completion

        text = document.text_before_cursor
        if not text.startswith("/"):
            return
        if " " not in text:
            for cand in ("/mem", "/metrics", "/clear", "/quit", "/help"):
                if cand.startswith(text):
                    yield Completion(cand, start_position=-len(text))
            return
        head, _, rest = text.partition(" ")
        if head != "/mem" or " " in rest.strip():
            return
        for sub in MEM_COMMANDS:
            if sub.startswith(rest):
                yield Completion(sub, start_position=-len(rest))


class FeiChatApp:
    """The TUI application shell.

    Headless-testable: all state and command dispatch live on this object;
    ``run()`` is the only method that needs a terminal.
    """

    def __init__(self, assistant=None, memory_handlers=None, width: int = 100):
        self.assistant = assistant
        self._memory = memory_handlers  # lazy MemoryToolHandlers
        self.messages: list[ChatMessage] = [
            ChatMessage(
                "system",
                "fei_tpu chat — /mem for memory commands, /help for help, "
                "Ctrl-C or /quit to exit.",
            )
        ]
        self.width = width
        self._busy = False
        self._app = None

    # ---------------------------------------------------------------- state

    def add_message(self, role: str, content: str, live: bool = False) -> ChatMessage:
        msg = ChatMessage(role, content, live=live)
        self.messages.append(msg)
        self.invalidate()
        return msg

    def invalidate(self) -> None:
        if self._app is not None:
            self._app.invalidate()

    def render_log(self) -> str:
        return "".join(m.render_ansi(self.width) for m in self.messages)

    @property
    def memory(self):
        if self._memory is None:
            from fei_tpu.tools.memory_tools import MemoryToolHandlers

            self._memory = MemoryToolHandlers()
        return self._memory

    # ------------------------------------------------------------- commands

    async def handle_user_message(self, line: str) -> None:
        """Dispatch one submitted line (reference :535-555)."""
        line = line.strip()
        if not line:
            return
        if line in ("/quit", "/exit"):
            self.exit()
            return
        if line == "/clear":
            if self.assistant is not None:
                self.assistant.reset()
            self.messages = self.messages[:1]
            self.invalidate()
            return
        if line == "/help":
            self.add_message("system", self._help_text())
            return
        if line == "/metrics":
            # live counters/histograms, same table the CLI's --stats prints
            from fei_tpu.obs.render import snapshot_lines
            from fei_tpu.utils.metrics import METRICS

            self.add_message(
                "system", "\n".join(snapshot_lines(METRICS.snapshot()))
            )
            return
        if line == "/mem" or line.startswith("/mem "):
            self.add_message("user", line)
            out = self.handle_memory_command(line[len("/mem"):].strip())
            self.add_message("memory", out)
            return
        self.add_message("user", line)
        await self._process_with_assistant(line)

    def _help_text(self) -> str:
        rows = "\n".join(f"  /mem {k:7s} {v}" for k, v in MEM_COMMANDS.items())
        return (
            "commands:\n  /clear    reset the conversation\n"
            "  /metrics  live engine/agent metrics snapshot\n"
            "  /quit     exit\n" + rows
        )

    def handle_memory_command(self, cmdline: str) -> str:
        """The /mem suite (reference handle_memory_command :557-970).

        Returns display text; never raises (errors render as text).
        """
        try:
            parts = shlex.split(cmdline) if cmdline else []
        except ValueError as exc:
            return f"parse error: {exc}"
        if not parts or parts[0] == "help":
            return self._help_text()
        sub, args = parts[0], parts[1:]
        h = self.memory
        try:
            if sub == "list":
                folder = args[0] if args else ""
                status = args[1] if len(args) > 1 else "new"
                out = h.memory_list(folder=folder, status=status)
                if "error" in out:
                    return f"error: {out['error']}"
                lines = [
                    f"{m.get('id', '?'):34s} {m.get('headers', {}).get('Subject', '')[:50]}"
                    for m in out.get("memories", [])
                ]
                return f"{out.get('count', 0)} memories\n" + "\n".join(lines)
            if sub == "search":
                if not args:
                    return "usage: /mem search <query>"
                out = h.memory_search(" ".join(args))
                if "error" in out:
                    return f"error: {out['error']}"
                hits = out.get("results", out.get("memories", []))
                return json.dumps(hits, indent=2, default=str)[:4000]
            if sub == "view":
                if not args:
                    return "usage: /mem view <id>"
                out = h.memory_view(args[0])
                return json.dumps(out, indent=2, default=str)[:4000]
            if sub == "save":
                if not args:
                    return "usage: /mem save <content...> [#tags] [subject=...]"
                tags, subject, words = None, None, []
                for w in args:
                    if w.startswith("#"):
                        tags = w.lstrip("#")
                    elif w.startswith("subject="):
                        subject = w[len("subject="):]
                    else:
                        words.append(w)
                out = h.memory_create(
                    " ".join(words), subject=subject, tags=tags
                )
                if "error" in out:
                    return f"error: {out['error']}"
                return f"saved: {out.get('created')}"
            if sub == "tag":
                if not args:
                    return "usage: /mem tag <tag>"
                out = h.memory_search_by_tag(args[0])
                if "error" in out:
                    return f"error: {out['error']}"
                hits = out.get("results", out.get("memories", []))
                return json.dumps(hits, indent=2, default=str)[:4000]
            if sub == "delete":
                if not args:
                    return "usage: /mem delete <id> [--hard]"
                out = h.memory_delete(args[0], hard="--hard" in args)
                return json.dumps(out, default=str)
            if sub == "server":
                action = args[0] if args else "status"
                if action == "start":
                    return json.dumps(h.memory_server_start())
                if action == "stop":
                    return json.dumps(h.memory_server_stop())
                return json.dumps(h.memory_server_status(), indent=2, default=str)
        except Exception as exc:  # noqa: BLE001 — UI must survive anything
            return f"error: {exc}"
        return f"unknown /mem subcommand: {sub!r}\n" + self._help_text()

    # ------------------------------------------------------ assistant calls

    async def _process_with_assistant(self, line: str) -> None:
        """Run the assistant with live token streaming (reference :1002-1031)."""
        if self.assistant is None:
            self.add_message("system", "no assistant configured")
            return
        if self._busy:
            self.add_message("system", "still working on the previous message…")
            return
        self._busy = True
        live = self.add_message("assistant", "", live=True)
        loop = asyncio.get_running_loop()

        def on_text(delta: str) -> None:
            # called from the decode thread: hop to the UI loop
            def apply():
                live.content += delta
                live._cache = None
                self.invalidate()

            loop.call_soon_threadsafe(apply)

        prev = self.assistant.on_text
        self.assistant.on_text = on_text
        try:
            response = await self.assistant.chat(line)
            # let queued call_soon_threadsafe deltas land before deciding
            # whether streaming already showed this response
            await asyncio.sleep(0)
            if response and response.strip() and not live.content.strip():
                live.content = response
        except Exception as exc:  # noqa: BLE001
            live.content = f"error: {exc}"
        finally:
            self.assistant.on_text = prev
            live.live = False
            live._cache = None
            self._busy = False
            self.invalidate()

    # ------------------------------------------------------------------ UI

    def _build_app(self):
        from prompt_toolkit.application import Application
        from prompt_toolkit.formatted_text import ANSI
        from prompt_toolkit.key_binding import KeyBindings
        from prompt_toolkit.layout import (
            HSplit,
            Layout,
            Window,
        )
        from prompt_toolkit.layout.controls import FormattedTextControl
        from prompt_toolkit.widgets import TextArea

        kb = KeyBindings()

        @kb.add("c-c")
        @kb.add("c-q")
        def _(event):
            event.app.exit()

        log_control = FormattedTextControl(
            lambda: ANSI(self.render_log()), focusable=False
        )
        log_window = Window(
            log_control, wrap_lines=False, always_hide_cursor=True,
            allow_scroll_beyond_bottom=False,
        )

        input_area = TextArea(
            height=2, prompt="you> ", multiline=False,
            completer=MemCommandCompleter(),
        )

        def accept(buff):
            text = buff.text
            buff.text = ""
            asyncio.get_event_loop().create_task(self.handle_user_message(text))
            return False  # keep the buffer

        input_area.accept_handler = accept

        status = Window(
            FormattedTextControl(
                lambda: " fei_tpu — Ctrl-C quit | /mem memory | /help"
                + ("  [working…]" if self._busy else "")
            ),
            height=1, style="reverse",
        )

        root = HSplit([log_window, status, input_area])
        self._app = Application(
            layout=Layout(root, focused_element=input_area),
            key_bindings=kb,
            full_screen=True,
            mouse_support=True,
        )
        return self._app

    def exit(self) -> None:
        if self._app is not None:
            self._app.exit()

    def run(self) -> None:
        self._build_app().run()


def build_assistant(args):
    """Same assistant wiring as the CLI, minus stdout streaming (the TUI
    installs its own on_text per message)."""
    from fei_tpu.agent import Assistant
    from fei_tpu.tools import ToolRegistry, create_code_tools
    from fei_tpu.tools.memory_tools import create_memory_tools

    registry = ToolRegistry()
    create_code_tools(registry)
    try:
        create_memory_tools(registry)
    except Exception as exc:  # noqa: BLE001
        log.warning("memory tools unavailable: %s", exc)
    return Assistant(
        provider=args.provider,
        model=args.model,
        tool_registry=registry,
        max_tokens=args.max_tokens,
    )


def parse_args(argv):
    p = argparse.ArgumentParser(prog="fei --textual", description="fei_tpu TUI chat")
    p.add_argument("--provider", default=None)
    p.add_argument("--model", default=None)
    p.add_argument("--max-tokens", type=int, default=4000)
    p.add_argument("--log-level", default=None)
    return p.parse_args(argv)


def main(argv: list[str] | None = None) -> int:
    args = parse_args(argv or [])
    setup_logging(level=args.log_level)
    try:
        assistant = build_assistant(args)
    except Exception as exc:  # noqa: BLE001
        print(f"error: {exc}")
        return 2
    FeiChatApp(assistant=assistant).run()
    return 0
