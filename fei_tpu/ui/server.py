"""OpenAI-compatible serving endpoint over the in-tree TPU engine.

``fei serve`` (or ``python -m fei_tpu.ui.server``) exposes the jax_local
serving stack — continuous batching, chunked prefill, prefix caching,
multi-step decode, grammar-enforced tool calls — behind the API shape the
reference consumed from outside (fei/core/assistant.py:524-530 via
LiteLLM): POST /v1/chat/completions with optional SSE streaming, plus
/v1/models and /health. Anything that speaks the OpenAI protocol (the
reference agent included, via RemoteProvider api_base) can point at it,
completing the zero-external-API-calls story.

Built on stdlib http.server like memory/memdir/server.py — no web
framework. Auth is optional (``--api-key`` / FEI_TPU_SERVER_API_KEY);
loopback deployments typically run keyless.
"""

from __future__ import annotations

import argparse
import base64
import binascii
import hmac
import json
import os
import tempfile
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from fei_tpu.obs.flight import FLIGHT
from fei_tpu.obs.trace import TRACES
from fei_tpu.utils.errors import (
    DeadlineExceededError,
    EngineDegradedError,
    EngineDrainingError,
    QueueFullError,
)
from fei_tpu.utils.logging import get_logger
from fei_tpu.utils.metrics import METRICS

log = get_logger("ui.server")

DEFAULT_PORT = 8188

# fleet role split (docs/KV.md): a prefill-heavy replica takes the long
# prompts, a decode-heavy one takes the token streams, mixed does both.
# The router reads the role off /health and the migration path hands the
# prefilled KV across (POST /kv/export -> POST /kv/import).
REPLICA_ROLES = ("mixed", "prefill-heavy", "decode-heavy")


def _content_text(content) -> str:
    """OpenAI content is a string or a parts array; extract the text."""
    if isinstance(content, list):
        return "".join(
            p.get("text", "") for p in content
            if isinstance(p, dict) and p.get("type", "text") == "text"
        )
    return str(content or "")


def _from_openai_messages(raw: list[dict]) -> tuple[list[dict], str | None]:
    """OpenAI wire messages -> (internal messages, system prompt).

    Inverse of agent/providers.RemoteProvider._to_openai_messages: tool
    calls unwrap from type/function envelopes with JSON-string arguments;
    system turns lift into the provider's ``system`` parameter."""
    if not isinstance(raw, list):
        raise ValueError("messages must be a list of message objects")
    msgs: list[dict] = []
    system_parts: list[str] = []
    for m in raw:
        if not isinstance(m, dict):
            raise ValueError(
                f"each message must be an object, got {type(m).__name__}"
            )
        role = m.get("role", "user")
        if role == "system":
            system_parts.append(_content_text(m.get("content")))
        elif role == "assistant" and m.get("tool_calls"):
            msgs.append({
                "role": "assistant",
                "content": m.get("content") or "",
                "tool_calls": [
                    {
                        "id": c.get("id", ""),
                        "name": c.get("function", {}).get("name", ""),
                        "arguments": json.loads(
                            c.get("function", {}).get("arguments") or "{}"
                        ),
                    }
                    for c in m["tool_calls"]
                ],
            })
        elif role == "tool":
            msgs.append({
                "role": "tool",
                "tool_call_id": m.get("tool_call_id", ""),
                "content": _content_text(m.get("content")),
            })
        else:
            msgs.append({"role": role, "content": _content_text(m.get("content"))})
    return msgs, ("\n\n".join(system_parts) or None)


def _from_openai_tools(raw: list[dict] | None) -> list[dict] | None:
    if not raw:
        return None
    out = []
    for t in raw:
        fn = t.get("function", t)
        out.append({
            "name": fn.get("name", ""),
            "description": fn.get("description", ""),
            "input_schema": fn.get("parameters", {}),
        })
    return out


def _gen_overrides(body: dict, headers: dict | None = None) -> dict:
    """Explicit JSON null means 'use the default' per the OpenAI spec
    (several SDKs serialize unset fields as null). ``headers`` carries
    the fleet extensions: ``X-FEI-Tenant`` / ``X-FEI-Priority`` (QoS
    labels, body fields win) and ``X-FEI-Deadline-S`` — the client's
    REMAINING deadline as propagated by the fleet router, folded in as a
    min() so a retry hop can only ever shrink the request's budget,
    never extend it."""
    over: dict = {}
    h = {str(k).lower(): v for k, v in (headers or {}).items()}
    if body.get("temperature") is not None:
        over["temperature"] = float(body["temperature"])
    if body.get("top_p") is not None:
        over["top_p"] = float(body["top_p"])
    if body.get("top_k") is not None:  # non-OpenAI extension
        over["top_k"] = int(body["top_k"])
    if body.get("min_p") is not None:  # non-OpenAI extension (vLLM-style)
        over["min_p"] = min(max(float(body["min_p"]), 0.0), 1.0)
    if body.get("seed") is not None:
        over["seed"] = int(body["seed"])
    if body.get("ignore_eos") is not None:
        # non-OpenAI extension (vLLM-style): decode the full max_tokens
        # budget — benches and the chaos crash smoke need streams long
        # enough to kill mid-flight regardless of what the model samples
        over["ignore_eos"] = bool(body["ignore_eos"])
    deadlines = []
    if body.get("deadline_s") is not None:  # non-OpenAI extension
        dl = max(0.0, float(body["deadline_s"]))
        if dl > 0:
            deadlines.append(dl)
    hd = h.get("x-fei-deadline-s")
    if hd is not None:
        try:
            # a propagated remaining budget of <= 0 means the client's
            # deadline already passed in flight; clamp to an epsilon so
            # the scheduler sheds it instead of treating 0 as "none"
            deadlines.append(max(1e-3, float(hd)))
        except (TypeError, ValueError):
            pass
    if deadlines:
        over["deadline_s"] = min(deadlines)
    tenant = body.get("tenant") or h.get("x-fei-tenant")
    if tenant:  # non-OpenAI extension (multi-tenant QoS)
        over["tenant"] = str(tenant)
    priority = body.get("priority")
    if priority is None:
        priority = h.get("x-fei-priority")
    if priority is not None:
        try:
            over["priority"] = int(priority)
        except (TypeError, ValueError):
            pass
    return over


def _to_openai_response(resp, model: str, rid: str) -> dict:
    msg: dict = {"role": "assistant", "content": resp.content}
    finish = "stop"
    if resp.tool_calls:
        msg["tool_calls"] = [
            {
                "id": c.id,
                "type": "function",
                "function": {
                    "name": c.name,
                    "arguments": json.dumps(c.arguments),
                },
            }
            for c in resp.tool_calls
        ]
        finish = "tool_calls"
    usage = resp.usage or {}
    pt = int(usage.get("prompt_tokens", 0))
    ct = int(usage.get("completion_tokens", 0))
    return {
        "id": rid,
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": 0, "message": msg, "finish_reason": finish}
        ],
        "usage": {
            "prompt_tokens": pt,
            "completion_tokens": ct,
            "total_tokens": pt + ct,
        },
    }


class ServeAPI:
    """Socket-free core so tests can drive it directly.

    ``provider`` is any agent-layer Provider (normally JaxLocalProvider —
    its paged scheduler interleaves concurrent requests; MockProvider in
    hermetic tests)."""

    def __init__(self, provider, model_name: str = "fei-tpu",
                 api_key: str | None = None, role: str | None = None):
        self.provider = provider
        self.model_name = model_name
        self.api_key = api_key or ""
        role = role or os.environ.get("FEI_TPU_REPLICA_ROLE", "") or "mixed"
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"replica role must be one of {REPLICA_ROLES}, got {role!r}"
            )
        self.role = role
        # one jax.profiler capture at a time; a second POST gets 409
        self._profile_lock = threading.Lock()

    def authorized(self, headers: dict) -> bool:
        if not self.api_key:
            return True
        provided = ""
        for k, v in headers.items():
            if k.lower() == "authorization":
                provided = v.strip()
                if provided[:7].lower() == "bearer ":  # scheme: RFC 7235 §2.1
                    provided = provided[7:].strip()
                break
        # bytes comparison: compare_digest raises on non-ASCII str input
        return hmac.compare_digest(
            provided.encode("utf-8"), self.api_key.encode("utf-8")
        )

    # -- non-streaming ------------------------------------------------------

    def handle(self, method: str, path: str, body: dict,
               headers: dict) -> tuple:
        """Route a request. Returns ``(status, payload)`` or ``(status,
        payload, extra_headers)``. A ``str`` payload means plain text
        (the Prometheus exposition); dicts serialize as JSON."""
        parts = urlsplit(path)
        route, query = parts.path, parse_qs(parts.query)
        METRICS.incr("server.requests")
        if route == "/health":
            mesh = self._mesh_tag()
            load = self._load_fields()
            base = {"model": self.model_name, "mesh": mesh,
                    "role": self.role, **self._kv_geometry(), **load}
            if self._draining():
                # a draining replica must leave the load-balancer rotation
                # while its in-flight set finishes
                return 503, {"status": "draining", **base}, \
                    {"Retry-After": "5"}
            if self._degraded():
                # surface the crash-loop breaker so load balancers eject
                # the replica instead of feeding it doomed requests
                return 503, {"status": "degraded", **base}
            return 200, {"status": "ok", **base}
        if route == "/metrics" and method == "GET":
            # pre-auth like /health: scrapers don't carry bearer tokens
            return 200, METRICS.prometheus_text()
        if not self.authorized(headers):
            return 401, {"error": {"message": "invalid or missing API key",
                                   "type": "authentication_error"}}
        if route == "/v1/models" and method == "GET":
            return 200, {
                "object": "list",
                "data": [{"id": self.model_name, "object": "model",
                          "owned_by": "fei-tpu"}],
            }
        if route == "/v1/traces" and method == "GET":
            try:
                limit = int(query.get("limit", ["50"])[0])
            except ValueError:
                return 400, {"error": {"message": "limit must be an int",
                                       "type": "invalid_request_error"}}
            limit = min(max(limit, 1), 1000)
            return 200, {"object": "list", "data": TRACES.recent(limit)}
        if route.startswith("/v1/traces/") and method == "GET":
            rid = route.rsplit("/", 1)[1]
            tr = TRACES.get(rid)
            if tr is None:
                return 404, {"error": {
                    "message": f"no trace {rid!r} (unknown or evicted)",
                    "type": "invalid_request_error"}}
            payload = tr.as_dict()
            # the request's slice of the engine flight recorder: every
            # dispatch and scheduler event tagged with this rid
            payload["flight"] = FLIGHT.for_rid(rid)
            return 200, payload
        if route == "/debug/timeline" and method == "GET":
            # Chrome-trace / Perfetto JSON of the engine flight recorder
            return 200, FLIGHT.chrome_trace()
        if route == "/v1/chat/completions" and method == "POST":
            return self._chat(body, headers)
        if route == "/drain" and method == "POST":
            return self._drain(body)
        # kv export/import stay routable while draining: migration-on-
        # drain is exactly when a replica's warm KV must leave the ship
        if route == "/kv/export" and method == "POST":
            return self._kv_export(body)
        if route == "/kv/import" and method == "POST":
            return self._kv_import(body)
        # content-addressed prefix control plane (KV CDN): list what this
        # replica can serve, probe what a prompt would admit through,
        # fetch one blob by hash, push one into the tier. All routable
        # while draining for the same reason as export/import.
        if route == "/kv/prefix" and method == "GET":
            return self._kv_prefix_list()
        if route == "/kv/prefix" and method == "POST":
            return self._kv_prefix_push(body)
        if route == "/kv/prefix/probe" and method == "POST":
            return self._kv_prefix_probe(body)
        if route.startswith("/kv/prefix/") and method == "GET":
            return self._kv_prefix_get(route.rsplit("/", 1)[1])
        if route == "/debug/profile" and method == "POST":
            return self._profile(body)
        return 404, {"error": {"message": f"no route {method} {route}",
                               "type": "invalid_request_error"}}

    def _profile(self, body: dict) -> tuple[int, dict]:
        """On-demand jax.profiler capture: trace the device for N seconds
        while live traffic keeps flowing, return the trace directory
        (open it with tensorboard / xprof)."""
        try:
            seconds = float(body.get("seconds", 2.0))
        except (TypeError, ValueError):
            return 400, {"error": {"message": "seconds must be a number",
                                   "type": "invalid_request_error"}}
        if not 0 < seconds <= 60:
            return 400, {"error": {
                "message": f"seconds must be in (0, 60], got {seconds}",
                "type": "invalid_request_error"}}
        if not self._profile_lock.acquire(blocking=False):
            return 409, {"error": {
                "message": "a profile capture is already running",
                "type": "conflict_error"}}
        try:
            import jax

            trace_dir = str(
                body.get("trace_dir")
                or tempfile.mkdtemp(prefix="fei-profile-")
            )
            jax.profiler.start_trace(trace_dir)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            METRICS.incr("server.profile_captures")
            return 200, {"object": "profile", "trace_dir": trace_dir,
                         "seconds": seconds}
        except Exception as exc:  # noqa: BLE001 — profiler issues -> JSON
            log.warning("profile capture failed: %r", exc)
            return 500, {"error": {"message": f"{type(exc).__name__}: {exc}",
                                   "type": "server_error"}}
        finally:
            self._profile_lock.release()

    def _parse_request(self, body: dict,
                       headers: dict | None = None) -> dict:
        """Decode the request into provider kwargs; raises on bad input
        BEFORE any engine work (the streaming path validates here before
        committing SSE headers)."""
        msgs, system = _from_openai_messages(body.get("messages") or [])
        mt = body.get("max_tokens")
        if mt is None:
            mt = body.get("max_completion_tokens")
        mt = 1024 if mt is None else int(mt)  # 0 is a valid explicit budget
        if mt < 0:
            raise ValueError(f"max_tokens must be >= 0, got {mt}")
        return {
            "messages": msgs,
            "system": system,
            "tools": _from_openai_tools(body.get("tools")),
            "max_tokens": mt,
            **self._overrides_kw(body, headers),
            **self._resume_kw(body),
        }

    def _resume_kw(self, body: dict) -> dict:
        """Fleet-router resurrection extension: ``"resume": {"generated":
        [ids...], "resume_key": [a, b] | null}`` teacher-forces a dead
        replica's delivered suffix so this replica's stream replays it
        byte-identically. Only providers that own a paged engine support
        it; others reject loudly (silently restarting from token 0 would
        duplicate the user-visible stream)."""
        raw = body.get("resume")
        if raw is None:
            return {}
        if not getattr(self.provider, "supports_resume", False):
            raise ValueError("resume is not supported by this provider")
        if not isinstance(raw, dict):
            raise ValueError("resume must be an object")
        gen = raw.get("generated") or []
        if not isinstance(gen, list):
            raise ValueError("resume.generated must be a list of token ids")
        resume: dict = {"generated": [int(t) for t in gen]}
        key = raw.get("resume_key")
        if key is not None:
            if not isinstance(key, list) or not key:
                raise ValueError("resume.resume_key must be a list of ints")
            resume["resume_key"] = [int(x) for x in key]
        return {"resume": resume}

    def _mesh_tag(self) -> str:
        """The backing engine's serving-mesh tag ('ms1' for single-chip
        and for non-engine providers) — load balancers and the bench
        ladder read capacity class off /health without a scrape."""
        from fei_tpu.parallel.mesh import mesh_tag

        eng = getattr(self.provider, "engine", None)
        return mesh_tag(getattr(eng, "mesh", None))

    def _kv_geometry(self) -> dict:
        """Both halves of the KV pool geometry on /health — the
        INVARIANT fingerprint (which replicas can exchange KV/sessions
        at all) and the tp shard layout (pure provenance) — so fleet
        placement can see a heterogeneous topology (a 70B tp4 rack next
        to 8B tp2 replicas) without a scrape. Empty for non-engine and
        dense providers; the router treats absence as compatible."""
        eng = getattr(self.provider, "engine", None)
        if eng is None or not hasattr(eng, "kv_fingerprint"):
            return {}
        try:
            fp = eng.kv_fingerprint()
            if fp is None:
                return {}
            return {"kv_fingerprint": fp, "kv_layout": eng.kv_layout()}
        except Exception:  # noqa: BLE001 — /health must never 500
            return {}

    def _degraded(self) -> bool:
        """True when the backing engine's crash-loop breaker is holding
        the scheduler degraded (non-engine providers: never)."""
        eng = getattr(self.provider, "engine", None)
        sched = getattr(eng, "_scheduler", None)
        return sched is not None and sched.degraded()

    def _draining(self) -> bool:
        """True when the backing engine is draining (SIGTERM or POST
        /drain); new requests 503 with Retry-After."""
        eng = getattr(self.provider, "engine", None)
        sched = getattr(eng, "_scheduler", None)
        return sched is not None and sched.draining()

    def _load_fields(self) -> dict:
        """Additive /health load fields the fleet router's least-loaded
        scoring reads: waiting-queue depth, running count, slot count.
        Empty for non-engine providers (router treats missing as 0)."""
        eng = getattr(self.provider, "engine", None)
        sched = getattr(eng, "_scheduler", None)
        if sched is None:
            return {}
        try:
            with sched._lock:
                slots = list(sched._slots)
                depth = len(sched._waiting)
            running = sum(
                1 for s in slots if s is not None and not s.finished
            )
            return {"queue_depth": depth, "running": running,
                    "slots": len(slots)}
        except Exception:  # noqa: BLE001 — /health must never 500
            return {}

    def _drain(self, body: dict) -> tuple:
        """Operator-initiated graceful drain — the HTTP twin of SIGTERM:
        stop admitting, finish in-flight requests within the deadline,
        snapshot the rest for warm restart. Idempotent."""
        try:
            deadline = body.get("deadline_s")
            deadline = None if deadline is None else max(0.0, float(deadline))
        except (TypeError, ValueError):
            return 400, {"error": {"message": "deadline_s must be a number",
                                   "type": "invalid_request_error"}}
        eng = getattr(self.provider, "engine", None)
        if eng is None or getattr(eng, "_scheduler", None) is None:
            return 200, {"status": "drained"}  # nothing in flight to drain
        eng.begin_drain(deadline_s=deadline)
        METRICS.incr("server.drains")
        return 202, {
            "status": "draining",
            "deadline_s": (
                deadline if deadline is not None
                else eng._scheduler.drain_deadline_s
            ),
        }

    # -- kv migration (fleet control plane) ---------------------------------

    def _kv_scheduler(self):
        eng = getattr(self.provider, "engine", None)
        return getattr(eng, "_scheduler", None)

    def _prompt_ids(self, body: dict) -> list[int]:
        """Token ids for the request's prompt, rendered EXACTLY like a
        real completion (same chat template, same system folding) so the
        exported prefix is the one a later /v1/chat/completions on this
        body would hit in the prefix cache."""
        msgs, system = _from_openai_messages(body.get("messages") or [])
        full = self.provider._messages_with_system(
            msgs, system, _from_openai_tools(body.get("tools"))
        )
        eng = self.provider.engine
        return list(eng.tokenizer.apply_chat_template(
            full, add_generation_prompt=True
        ))

    def _kv_export(self, body: dict) -> tuple:
        """Serialize the longest cached KV prefix for this prompt into a
        portable blob (kv/migrate.py). 404 when nothing is cached — the
        caller just re-prefills, exactly the pre-migration world."""
        sched = self._kv_scheduler()
        if sched is None or not hasattr(self.provider, "_messages_with_system"):
            return 501, {"error": {
                "message": "kv export needs an engine-backed provider",
                "type": "invalid_request_error"}}
        try:
            ids = self._prompt_ids(body)
        except (ValueError, KeyError, TypeError) as exc:
            return 400, {"error": {"message": str(exc),
                                   "type": "invalid_request_error"}}
        try:
            blob = sched.export_prefix(ids)
        except Exception as exc:  # noqa: BLE001 — control plane must
            # answer JSON, never drop the socket
            log.warning("kv export failed: %r", exc)
            return 500, {"error": {"message": f"{type(exc).__name__}: {exc}",
                                   "type": "server_error"}}
        if blob is None:
            return 404, {"error": {
                "message": "no cached prefix for this prompt",
                "type": "invalid_request_error"}}
        return 200, {"object": "kv.blob", "bytes": len(blob),
                     "blob": base64.b64encode(blob).decode("ascii")}

    def _kv_import(self, body: dict) -> tuple:
        """Scatter a migration blob into this replica's pool. Two-rung
        error ladder so the router can tell "never retry" from "bad
        bytes, refetch elsewhere": 409 with a structured
        ``{ours, theirs}`` geometry diff for an invariant-incompatible
        blob (KVGeometryError — no replica of this pool shape will EVER
        accept it; a tp layout skew resheds on scatter and never 409s),
        422 for a corrupt/truncated blob (KVTierError — these bytes are
        bad, but another copy may be fine). ``pages: 0`` when the pool
        can't spare room — best-effort by contract, never preempts."""
        from fei_tpu.utils.errors import KVGeometryError, KVTierError

        sched = self._kv_scheduler()
        if sched is None:
            return 501, {"error": {
                "message": "kv import needs an engine-backed provider",
                "type": "invalid_request_error"}}
        raw = body.get("blob")
        if not isinstance(raw, str) or not raw:
            return 400, {"error": {"message": "blob must be a base64 string",
                                   "type": "invalid_request_error"}}
        try:
            blob = base64.b64decode(raw, validate=True)
        except (binascii.Error, ValueError):
            return 400, {"error": {"message": "blob is not valid base64",
                                   "type": "invalid_request_error"}}
        try:
            pages = sched.import_prefix(blob)
        except KVGeometryError as exc:
            return 409, {"error": {"message": str(exc),
                                   "type": "invalid_request_error",
                                   "ours": exc.ours, "theirs": exc.theirs}}
        except KVTierError as exc:
            return 422, {"error": {"message": str(exc),
                                   "type": "invalid_request_error"}}
        except Exception as exc:  # noqa: BLE001
            log.warning("kv import failed: %r", exc)
            return 500, {"error": {"message": f"{type(exc).__name__}: {exc}",
                                   "type": "server_error"}}
        return 200, {"object": "kv.import", "pages": int(pages)}

    # -- content-addressed prefixes (KV CDN control plane) -------------------

    def _kv_tier_store(self):
        sched = self._kv_scheduler()
        return getattr(sched, "_kv_tier", None)

    def _kv_prefix_list(self) -> tuple:
        """Content hashes this replica's tier can serve, hottest first —
        what peers and the router's pre-warm pass read. An empty list is
        a healthy answer (tier off, or simply nothing published yet)."""
        tier = self._kv_tier_store()
        hashes = [] if tier is None else tier.advertised()
        return 200, {"object": "kv.prefix.list", "hashes": hashes}

    def _kv_prefix_get(self, key: str) -> tuple:
        """One content-addressed prefix blob by hash. 404 = not here (the
        caller tries the next peer); tier-side faults (the ``kv.fetch``
        point fires on this path too) answer 500 JSON, never a socket
        drop — the peer-fetch caller treats any non-200 as a miss."""
        tier = self._kv_tier_store()
        if tier is None:
            return 404, {"error": {
                "message": "this replica runs without a KV tier",
                "type": "invalid_request_error"}}
        from fei_tpu.kv.tier import pack_entry

        try:
            entry = tier.fetch(key)
        except Exception as exc:  # noqa: BLE001
            log.warning("kv prefix fetch %s failed: %r", key, exc)
            return 500, {"error": {"message": f"{type(exc).__name__}: {exc}",
                                   "type": "server_error"}}
        if entry is None:
            return 404, {"error": {
                "message": f"no prefix {key!r} in the tier",
                "type": "invalid_request_error"}}
        blob = pack_entry(entry)
        return 200, {"object": "kv.blob", "hash": key, "bytes": len(blob),
                     "blob": base64.b64encode(blob).decode("ascii")}

    def _kv_prefix_push(self, body: dict) -> tuple:
        """Peer push: land a content-addressed blob in this replica's
        tier WITHOUT touching the pool — thread-safe, no loop-thread
        hop, no pages consumed; the next admission over matching tokens
        fetches the pages in through ``_try_cas_admit``. The same
        409/422 ladder as /kv/import: 409 when the blob's INVARIANT
        fingerprint can never match this replica's pool (storing it
        would waste tier space on bytes no admission can use — a tp
        layout skew is fine, admission resheds); 422 for a corrupt blob
        or a non-content-addressed key. ``stored: false`` means the
        tier already held it (dedup), which is success."""
        from fei_tpu.kv.content import is_cas_key
        from fei_tpu.kv.pagesio import check_fingerprint
        from fei_tpu.kv.tier import unpack_entry
        from fei_tpu.utils.errors import KVGeometryError, KVTierError

        tier = self._kv_tier_store()
        if tier is None:
            return 501, {"error": {
                "message": "kv prefix push needs a KV tier "
                           "(FEI_TPU_KV_TIER)",
                "type": "invalid_request_error"}}
        raw = body.get("blob")
        if not isinstance(raw, str) or not raw:
            return 400, {"error": {"message": "blob must be a base64 string",
                                   "type": "invalid_request_error"}}
        try:
            blob = base64.b64decode(raw, validate=True)
        except (binascii.Error, ValueError):
            return 400, {"error": {"message": "blob is not valid base64",
                                   "type": "invalid_request_error"}}
        try:
            entry, _ = unpack_entry(blob)
        except KVTierError as exc:
            return 422, {"error": {"message": str(exc),
                                   "type": "invalid_request_error"}}
        key = body.get("hash") or entry.key
        if not is_cas_key(key) or key != entry.key:
            return 422, {"error": {
                "message": "hash does not name a content-addressed "
                           "prefix blob",
                "type": "invalid_request_error"}}
        # a well-formed blob whose INVARIANT geometry can never match
        # this pool is refused up front (409): storing it would spend
        # tier budget on bytes no admission here can ever use
        want = self._kv_geometry().get("kv_fingerprint")
        if want is not None:
            try:
                check_fingerprint(want, entry.fingerprint,
                                  what="pushed prefix blob")
            except KVGeometryError as exc:
                return 409, {"error": {
                    "message": str(exc), "type": "invalid_request_error",
                    "ours": exc.ours, "theirs": exc.theirs}}
        try:
            stored = tier.put_if_absent(key, entry)
        except Exception as exc:  # noqa: BLE001 — injected spill faults
            # and disk errors answer JSON; the pusher counts and moves on
            log.warning("kv prefix push %s failed: %r", key, exc)
            return 500, {"error": {"message": f"{type(exc).__name__}: {exc}",
                                   "type": "server_error"}}
        return 200, {"object": "kv.prefix.push", "hash": key,
                     "stored": bool(stored), "bytes": entry.nbytes}

    def _kv_prefix_probe(self, body: dict) -> tuple:
        """Which content hashes would this prompt admit through (longest
        first), and which are already local — the router's fetch-on-miss
        oracle. Renders the prompt exactly like a completion would, so
        the hashes name the prefix a later ``/v1/chat/completions`` on
        this body actually hits."""
        sched = self._kv_scheduler()
        if sched is None or not hasattr(self.provider,
                                        "_messages_with_system"):
            return 501, {"error": {
                "message": "kv prefix probe needs an engine-backed "
                           "provider",
                "type": "invalid_request_error"}}
        if (getattr(sched, "_kv_tier", None) is None
                or not getattr(sched, "_cas_enabled", False)):
            return 200, {"object": "kv.prefix.probe",
                         "hashes": [], "have": []}
        try:
            ids = self._prompt_ids(body)
        except (ValueError, KeyError, TypeError) as exc:
            return 400, {"error": {"message": str(exc),
                                   "type": "invalid_request_error"}}
        try:
            st = sched.content_prefix_status(ids)
        except Exception as exc:  # noqa: BLE001
            log.warning("kv prefix probe failed: %r", exc)
            return 500, {"error": {"message": f"{type(exc).__name__}: {exc}",
                                   "type": "server_error"}}
        return 200, {"object": "kv.prefix.probe", **st}

    @staticmethod
    def _retry_after(exc) -> dict:
        return {"Retry-After": str(max(1, round(
            getattr(exc, "retry_after_s", 1.0)
        )))}

    def _chat(self, body: dict, headers: dict | None = None) -> tuple:
        try:
            kw = self._parse_request(body, headers)
        except (ValueError, KeyError, TypeError) as exc:
            return 400, {"error": {"message": str(exc),
                                   "type": "invalid_request_error"}}
        try:
            msgs = kw.pop("messages")
            resp = self.provider.complete(msgs, **kw)
        except QueueFullError as exc:
            # backpressure, not failure: the waiting queue is at
            # FEI_TPU_MAX_QUEUE — tell the client when to come back
            return 429, {"error": {"message": str(exc),
                                   "type": "overloaded_error"}}, \
                self._retry_after(exc)
        except (EngineDegradedError, EngineDrainingError) as exc:
            return 503, {"error": {"message": str(exc),
                                   "type": "overloaded_error"}}, \
                self._retry_after(exc)
        except DeadlineExceededError as exc:
            return 504, {"error": {"message": str(exc),
                                   "type": "timeout_error"}}
        except Exception as exc:  # noqa: BLE001 — surface as JSON, not a
            # dropped socket (EngineError/ProviderError/anything)
            log.warning("completion failed: %r", exc)
            return 500, {"error": {"message": f"{type(exc).__name__}: {exc}",
                                   "type": "server_error"}}
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        return 200, _to_openai_response(
            resp, body.get("model") or self.model_name, rid
        )

    def _overrides_kw(self, body: dict, headers: dict | None = None) -> dict:
        """Per-request sampling knobs — only for providers that declare
        support (JaxLocalProvider); remote/mock providers ignore sampling
        anyway."""
        over = _gen_overrides(body, headers)
        if over and getattr(self.provider, "supports_gen_overrides", False):
            return {"gen_overrides": over}
        return {}

    # -- streaming ----------------------------------------------------------

    def stream_chat(self, body: dict, kw: dict):
        """Yield SSE frames (bytes). ``kw`` comes from _parse_request —
        validation already happened, so the 200 + SSE headers the caller
        committed were safe. Provider/engine errors mid-stream become an
        error frame followed by [DONE] instead of a dropped connection."""
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model") or self.model_name
        created = int(time.time())

        def frame(delta: dict, finish=None, fei: dict | None = None) -> bytes:
            chunk = {
                "id": rid,
                "object": "chat.completion.chunk",
                "created": created,
                "model": model,
                "choices": [
                    {"index": 0, "delta": delta, "finish_reason": finish}
                ],
            }
            if fei is not None:
                chunk["fei"] = fei
            return b"data: " + json.dumps(chunk).encode() + b"\n\n"

        yield frame({"role": "assistant"})
        resp = None
        # Failover side-channel: the engine fills ``export`` in place with
        # every delivered token id and its PRNG resume key; each content
        # frame carries the ids delivered since the previous frame plus
        # the PRNG state after the last of them as an ``fei`` extension,
        # so the fleet router can resurrect this stream on a survivor
        # byte-identically if this process dies mid-stream. OpenAI
        # clients ignore the extra key.
        export: dict | None = None
        if getattr(self.provider, "supports_resume", False):
            export = {}
            kw = dict(kw, export=export)
        sent_toks = 0
        try:
            from fei_tpu.engine.faults import FAULTS

            msgs = kw.pop("messages")
            gen = self.provider.stream(msgs, **kw)
            while True:
                try:
                    delta = next(gen)
                    if delta:
                        ext = None
                        if export is not None and export.get("ids"):
                            n = len(export["ids"])
                            if n > sent_toks:
                                keys = export.get("keys") or []
                                ext = {
                                    "toks": [
                                        int(t) for t in
                                        export["ids"][sent_toks:n]
                                    ],
                                    "key": (
                                        keys[n - 1]
                                        if n - 1 < len(keys) else None
                                    ),
                                }
                                sent_toks = n
                        yield frame({"content": delta}, fei=ext)
                        # the hard-kill seam the chaos_crash stage arms:
                        # dies AFTER the frame left the handler, so the
                        # client-observed suffix is the worst case the
                        # journal + resurrection must cover
                        FAULTS.check("replica.crash", rid=rid)
                except StopIteration as fin:
                    resp = fin.value
                    break
        except Exception as exc:  # noqa: BLE001
            log.warning("stream failed: %r", exc)
            # SSE headers are already committed, so saturation/deadline
            # errors can't change the status line — but the frame keeps
            # the typed category so clients can still back off
            etype = "server_error"
            if isinstance(
                exc,
                (QueueFullError, EngineDegradedError, EngineDrainingError),
            ):
                etype = "overloaded_error"
            elif isinstance(exc, DeadlineExceededError):
                etype = "timeout_error"
            yield (b"data: " + json.dumps({"error": {
                "message": f"{type(exc).__name__}: {exc}",
                "type": etype,
            }}).encode() + b"\n\n")
            yield b"data: [DONE]\n\n"
            return
        finish = "stop"
        if resp is not None and resp.tool_calls:
            finish = "tool_calls"
            yield frame({
                "tool_calls": [
                    {
                        "index": i,
                        "id": c.id,
                        "type": "function",
                        "function": {"name": c.name,
                                     "arguments": json.dumps(c.arguments)},
                    }
                    for i, c in enumerate(resp.tool_calls)
                ]
            })
        yield frame({}, finish=finish)
        yield b"data: [DONE]\n\n"


def make_handler(api: ServeAPI):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route through our logger
            log.debug("http: " + fmt, *args)

        def _json(self, status: int, payload: dict | str,
                  headers: dict | None = None) -> None:
            if isinstance(payload, str):  # Prometheus text exposition
                data = payload.encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                data = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _body(self) -> dict | None:
            """None means malformed JSON or a non-object body (-> 400),
            {} means no body."""
            n = int(self.headers.get("Content-Length") or 0)
            if not n:
                return {}
            try:
                data = json.loads(self.rfile.read(n))
            except json.JSONDecodeError:
                return None
            return data if isinstance(data, dict) else None

        def do_GET(self):  # noqa: N802
            res = api.handle("GET", self.path, {}, dict(self.headers))
            self._json(res[0], res[1], res[2] if len(res) > 2 else None)

        def do_POST(self):  # noqa: N802
            body = self._body()
            if body is None:
                self._json(400, {"error": {
                    "message": "request body is not a JSON object",
                    "type": "invalid_request_error"}})
                return
            if (
                self.path == "/v1/chat/completions"
                and body.get("stream")
                and api.authorized(dict(self.headers))
            ):
                # validate BEFORE committing 200 + SSE headers, so a bad
                # request gets a clean JSON 400 like the non-stream path
                try:
                    kw = api._parse_request(body, dict(self.headers))
                except (ValueError, KeyError, TypeError) as exc:
                    self._json(400, {"error": {"message": str(exc),
                                               "type": "invalid_request_error"}})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                try:
                    for chunk in api.stream_chat(body, kw):
                        self.wfile.write(chunk)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    log.info("client disconnected mid-stream")
                return
            res = api.handle("POST", self.path, body, dict(self.headers))
            self._json(res[0], res[1], res[2] if len(res) > 2 else None)

    return Handler


class ServingServer:
    """Owns the ThreadingHTTPServer; start()/stop() for tests and CLI."""

    def __init__(self, api: ServeAPI, host: str = "127.0.0.1", port: int = 0):
        self.api = api
        self.httpd = ThreadingHTTPServer((host, port), make_handler(api))
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        log.info("serving OpenAI-compatible API on :%d", self.port)

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="OpenAI-compatible serving endpoint over the TPU engine"
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument("--model", default=None,
                   help="model config name (default: [jax_local] model)")
    p.add_argument("--api-key", default=os.environ.get("FEI_TPU_SERVER_API_KEY"))
    args = p.parse_args(argv)

    from fei_tpu.agent.providers import JaxLocalProvider

    provider = JaxLocalProvider(model=args.model)
    api = ServeAPI(
        provider,
        model_name=provider.engine.cfg.name,
        api_key=args.api_key,
    )
    server = ServingServer(api, host=args.host, port=args.port)
    server.start()
    log.info("model %s ready on http://%s:%d/v1 (ctrl-c to stop)",
             provider.engine.cfg.name, args.host, server.port)

    # warm restart: re-admit requests a previous process snapshotted at
    # drain, AND any sessions the crash journal (FEI_TPU_JOURNAL_DIR)
    # recorded as admitted-but-unterminated — the previous process may
    # have died with no cooperation at all (kill -9). Either way they
    # decode to completion server-side (the old connections are gone;
    # clients were told 503 + Retry-After or are being resurrected by
    # the fleet router), which primes the prefix cache for retries and
    # proves none were lost.
    drain_dir = os.environ.get("FEI_TPU_DRAIN_DIR", "")
    eng = getattr(provider, "engine", None)
    has_journal = (
        eng is not None
        and getattr(getattr(eng, "_scheduler", None), "_journal", None)
        is not None
    )
    if eng is not None and (drain_dir or has_journal):
        try:
            restored = eng.warm_restart(drain_dir or None)
        except Exception as exc:  # noqa: BLE001 — boot must survive a
            # corrupt snapshot file; the operator sees the log
            log.warning("warm restart failed: %r", exc)
            restored = []
        if restored:
            log.info("warm restart: re-admitted %d request(s)", len(restored))

            def _finish_restored(s):
                try:
                    for _ in eng.scheduler.drain(s):
                        pass
                except Exception as exc:  # noqa: BLE001
                    log.warning("restored request failed: %r", exc)

            for s in restored:
                threading.Thread(
                    target=_finish_restored, args=(s,), daemon=True
                ).start()

    stopping = threading.Event()
    got_term = threading.Event()

    def _sigterm(signum, frame):  # noqa: ARG001
        got_term.set()
        stopping.set()

    import signal

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass  # not the main thread (embedded use): no SIGTERM hook
    try:
        while not stopping.wait(3600):
            pass
    except KeyboardInterrupt:
        pass
    if got_term.is_set() and eng is not None:
        sched = getattr(eng, "_scheduler", None)
        if sched is not None:
            log.info("SIGTERM: draining before shutdown")
            eng.begin_drain()
            eng.wait_drained(sched.drain_deadline_s + 5.0)
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
