"""Human-readable rendering of METRICS.snapshot() — shared by the CLI's
/stats output and the Textual TUI's /metrics command so both UIs show the
same table."""

from __future__ import annotations


def snapshot_lines(snap: dict) -> list[str]:
    lines: list[str] = []
    spans = snap.get("spans", {})
    if spans:
        lines.append("timings:")
        for name in sorted(spans):
            s = spans[name]
            lines.append(
                f"  {name:<24} n={s['count']:<5} mean={s['mean_s']:.3f}s "
                f"min={s['min_s']:.3f}s max={s['max_s']:.3f}s "
                f"total={s['total_s']:.2f}s"
            )
    hists = snap.get("histograms", {})
    if hists:
        lines.append("latency percentiles:")
        for name in sorted(hists):
            h = hists[name]
            if not h["count"]:
                continue
            lines.append(
                f"  {name:<24} n={h['count']:<5} p50={h['p50']:.4f}s "
                f"p95={h['p95']:.4f}s p99={h['p99']:.4f}s "
                f"max={h['max']:.4f}s"
            )
    counters = snap.get("counters", {})
    if counters:
        lines.append("counters:")
        for name in sorted(counters):
            v = counters[name]
            v = int(v) if float(v) == int(v) else v
            lines.append(f"  {name:<32} {v}")
    gauges = snap.get("gauges", {})
    if gauges:
        lines.append("gauges:")
        for name in sorted(gauges):
            v = gauges[name]
            if isinstance(v, float) and v != int(v):
                lines.append(f"  {name:<32} {v:.4f}")
            else:
                lines.append(f"  {name:<32} {int(v)}")
    if not lines:
        lines.append("(no metrics recorded yet)")
    return lines
