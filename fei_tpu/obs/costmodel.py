"""Analytical roofline accountant: per-dispatch HBM-bytes / FLOPs
estimates from the model config + batch/page geometry.

Single-stream decode is weight-streaming-bound, so achieved tok/s ×
bytes-streamed-per-token against the chip's HBM bandwidth — not MFU — is
the lens that says whether there is headroom (BASELINE.md measures the
same ceiling empirically). This module owns the byte model bench.py
reports against, plus live per-dispatch accounting the scheduler feeds
into the ``roofline.frac`` / ``roofline.tok_s_per_chip`` gauges so
``/metrics`` and every bench line carry the fraction-of-roofline a run
actually achieved.

``FEI_TPU_HBM_GBPS`` overrides the per-chip bandwidth ceiling (default
the v5e spec number) — e.g. when serving on a different TPU generation.
"""

from __future__ import annotations

import os

# v5e HBM bandwidth (chip spec ~819 GB/s) — the default roofline ceiling
V5E_HBM_GBPS = 819.0


def hbm_gbps() -> float:
    """The per-chip HBM bandwidth ceiling (GB/s) the roofline fraction is
    computed against; ``FEI_TPU_HBM_GBPS`` overrides the v5e default."""
    try:
        return float(os.environ.get("FEI_TPU_HBM_GBPS", "") or V5E_HBM_GBPS)
    except ValueError:
        return V5E_HBM_GBPS


def decode_stream_bytes(engine, mean_ctx: int) -> dict:
    """HBM bytes streamed to decode ONE token (the roofline basis,
    round-4 verdict #5): every weight byte except the embedding table
    (a gather reads ~one row; tied embeddings ARE the lm_head and stream
    fully), MoE expert bytes scaled to the top-k actually routed, plus the
    K/V cache read at the mean decode context and the new token's K/V
    write. Activations/norm traffic is O(hidden) per layer — noise next to
    the weight stream — and is reported inside `other` by omission."""
    from fei_tpu.ops.quant import param_bytes

    cfg = engine.cfg
    p = engine.params
    weights = param_bytes(p)
    if not cfg.tie_embeddings and "embed" in p:
        weights -= param_bytes(p["embed"])
    if cfg.is_moe:
        k, E = cfg.num_experts_per_tok, cfg.num_experts
        layers = p.get("layers", {})
        for name in ("w_gate", "w_up", "w_down"):
            if name in layers:
                weights -= param_bytes(layers[name]) * (1 - k / E)
    kv_row = kv_row_bytes(engine)
    kv_read = kv_row * mean_ctx
    kv_write = kv_row
    return {
        "weights": int(weights),
        "kv_read": int(kv_read),
        "kv_write": int(kv_write),
        "total": int(weights + kv_read + kv_write),
    }


def kv_row_bytes(engine) -> int:
    """Bytes of K+V cache per token position (all layers)."""
    import jax.numpy as jnp

    cfg = engine.cfg
    itemsize = jnp.dtype(engine.dtype).itemsize
    return 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim_ * itemsize


def _element_count(tree) -> int:
    import jax
    import numpy as np

    return sum(
        int(np.prod(leaf.shape))
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape")
    )


def decode_flops_per_token(engine) -> int:
    """FLOPs to decode one token ≈ 2 × parameters touched (the matmul
    2·m·n·k identity at batch 1): the embedding gather reads one row so
    an untied table is excluded, and MoE expert weights scale to the
    routed top-k — the same active-weight model as the byte estimate."""
    cfg = engine.cfg
    p = engine.params
    n = _element_count(p)
    if not cfg.tie_embeddings and "embed" in p:
        n -= _element_count(p["embed"])
    if cfg.is_moe:
        k, E = cfg.num_experts_per_tok, cfg.num_experts
        layers = p.get("layers", {})
        for name in ("w_gate", "w_up", "w_down"):
            if name in layers:
                n -= _element_count(layers[name]) * (1 - k / E)
    return 2 * int(n)


def dispatch_bytes(engine, n_steps: int, total_ctx: int, slots: int) -> int:
    """HBM bytes one batched decode dispatch streams: per scanned step the
    full weight stream plus a K/V read over every active slot's context
    and one K/V row write per slot. ``total_ctx`` is the summed context
    length across active slots at dispatch time (the scan's mid-point
    growth is noise at this resolution)."""
    sb = decode_stream_bytes(engine, 0)
    kv_row = kv_row_bytes(engine)
    per_step = sb["weights"] + kv_row * (total_ctx + slots)
    return int(max(1, n_steps) * per_step)


def ragged_dispatch_bytes(
    engine, n_steps: int, total_ctx: int, slots: int,
    chunk_tokens: int, chunk_ctx: int,
) -> int:
    """HBM bytes of one MERGED ragged dispatch: a decode scan that also
    carries a prefill chunk (``chunk_tokens`` positions starting at
    absolute context ``chunk_ctx``) in its first step. The decode side is
    exactly ``dispatch_bytes``; the chunk adds NO extra weight stream —
    that is the point of the merge, the first step's weight read serves
    both sides — only its own K/V traffic: one row write per chunk token
    plus the page reads its causal attention walks (history up to the
    chunk's end, ≈ ``chunk_ctx + chunk_tokens`` rows; the intra-chunk
    triangle is second-order at this resolution)."""
    kv_row = kv_row_bytes(engine)
    chunk = kv_row * (chunk_ctx + 2 * chunk_tokens)
    return dispatch_bytes(engine, n_steps, total_ctx, slots) + int(chunk)


def roofline_fraction(bytes_streamed: int, dt_s: float,
                      n_chips: int = 1) -> float:
    """Fraction of the aggregate HBM roofline achieved: estimated bytes
    over wall time vs ``n_chips`` × the per-chip ceiling."""
    if dt_s <= 0:
        return 0.0
    gbps = bytes_streamed / dt_s / 1e9
    return gbps / (hbm_gbps() * max(1, n_chips))


def chips_for_tag(tag: str | None) -> int:
    """Device count implied by a serving-mesh tag (``ms1`` → 1,
    ``tp2dp2`` → 4). Unparseable tags count as one chip — a wrong
    denominator must never sink a bench line."""
    if not tag or tag in ("ms1", "off"):
        return 1
    try:
        from fei_tpu.parallel.mesh import parse_mesh_shape

        sizes = parse_mesh_shape(tag)
        n = 1
        for s in dict(sizes).values():
            n *= int(s)
        return max(1, n)
    except Exception:  # noqa: BLE001
        return 1


def account_kv_transfer(direction: str, nbytes: int, dt_s: float) -> None:
    """Bytes-moved accounting for the tiered KV store (kv/tier.py) and
    migration: cumulative byte counters plus an achieved-GB/s gauge per
    direction. ``direction`` is ``spilled`` (HBM→host on preemption) or
    ``fetched`` (host→HBM on streamed resume). The gauge tells the
    operator whether tier traffic is anywhere near the device-transfer
    ceiling — spill/fetch time is pure resume-latency overhead."""
    from fei_tpu.obs.metrics import METRICS

    if direction not in ("spilled", "fetched"):
        return
    METRICS.incr(f"kv.bytes_{direction}", int(nbytes))
    if dt_s > 0:
        METRICS.gauge(
            f"kv.{direction}_gbps", round(nbytes / dt_s / 1e9, 6)
        )


def account_dispatch(engine, n_steps: int, total_ctx: int, slots: int,
                     dt_s: float) -> None:
    """Live roofline accounting for one decode dispatch: update the
    ``roofline.frac`` and ``roofline.tok_s_per_chip`` gauges from the
    analytical byte estimate and the measured wall time."""
    from fei_tpu.obs.metrics import METRICS
    from fei_tpu.parallel.mesh import AXES, axis_size

    if dt_s <= 0:
        return
    mesh = getattr(engine, "mesh", None)
    n_chips = 1
    for ax in AXES:
        n_chips *= axis_size(mesh, ax)
    est = dispatch_bytes(engine, n_steps, total_ctx, slots)
    _roofline_gauges(engine, est, n_steps * slots, dt_s, n_chips)


def account_ragged_dispatch(
    engine, n_steps: int, total_ctx: int, slots: int,
    chunk_tokens: int, chunk_ctx: int, dt_s: float,
) -> None:
    """Roofline accounting for one MERGED ragged dispatch (decode scan +
    prefill chunk in one program). The byte estimate credits the chunk's
    K/V traffic but NOT a second weight stream (see
    ``ragged_dispatch_bytes``), and tok_s_per_chip keeps counting decode
    tokens only — prefill positions are not served tokens, so the gauge
    stays comparable across the merged and legacy paths."""
    from fei_tpu.parallel.mesh import AXES, axis_size

    if dt_s <= 0:
        return
    mesh = getattr(engine, "mesh", None)
    n_chips = 1
    for ax in AXES:
        n_chips *= axis_size(mesh, ax)
    est = ragged_dispatch_bytes(
        engine, n_steps, total_ctx, slots, chunk_tokens, chunk_ctx
    )
    _roofline_gauges(engine, est, n_steps * slots, dt_s, n_chips)


def _roofline_gauges(engine, est_bytes: int, tokens: int, dt_s: float,
                     n_chips: int) -> None:
    from fei_tpu.obs.metrics import METRICS

    # 9 decimals: a tiny CPU model's frac is O(1e-7) and must not round
    # to a flat zero; production fractions are O(0.1) and unaffected
    METRICS.gauge(
        "roofline.frac",
        round(roofline_fraction(est_bytes, dt_s, n_chips), 9),
    )
    METRICS.gauge(
        "roofline.tok_s_per_chip",
        round(tokens / dt_s / max(1, n_chips), 3),
    )
