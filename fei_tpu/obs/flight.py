"""Engine flight recorder: a bounded, lock-cheap ring of timestamped
engine events, exportable as Chrome-trace/Perfetto JSON.

Two record shapes share the ring:

- **instant** events — scheduler decisions and lifecycle edges (admission,
  turbo arm/depth, rollback, preempt/snapshot/resume, drain, breaker
  trips, fault injections, observed compiles) recorded at a single
  timestamp;
- **dispatch** events — one per device dispatch, carrying THREE
  timestamps: call begin, dispatch-issue return (the jitted call came
  back; the device is still running), and host sync complete
  (block-until-ready / np.asarray returned). The Chrome-trace export
  splits each into an ``<name>.issue`` and ``<name>.sync`` complete
  ("X") event, so a Perfetto timeline shows host-issue vs
  device+transport time per dispatch.

Every record is tagged with the request trace id(s) it served, the
serving-mesh tag, and (where meaningful) the batch slot. The hot path is
one ``deque.append`` of a plain tuple — CPython's deque append is atomic
under the GIL, so recording takes no lock; only snapshot/export does.

Knobs: ``FEI_TPU_FLIGHT_RING`` bounds the ring (default 4096 records,
oldest evicted first); ``FEI_TPU_FLIGHT_FILE`` additionally appends every
record as one JSONL line (post-hoc flight recording, same contract as
``FEI_TPU_TRACE_FILE``). ``GET /debug/timeline`` on ui/server.py serves
``chrome_trace()``; load the JSON in https://ui.perfetto.dev or
chrome://tracing.

The compile observer lives here too: every jitted-program cache in
engine/ routes its cache-miss through ``CompileObserver.wrap``, which
counts first-build compilations per program signature
(``engine.compiles``), times the first invocation into the
``compile_seconds`` histogram, and flags any signature compiled twice as
a steady-state recompile (``engine.recompiles``) — one silent 20 s
shard_map recompile dwarfs any kernel win, so recompiles-after-warmup
must read as zero.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import Counter, deque

from fei_tpu.obs.metrics import METRICS

# record tuples: ("i", name, ts, tags) | ("X", name, t0, t_issue, t1, tags)
_INSTANT = "i"
_DISPATCH = "X"


def _ring_size() -> int:
    try:
        return max(16, int(os.environ.get("FEI_TPU_FLIGHT_RING", "4096")))
    except ValueError:
        return 4096


class FlightRecorder:
    """Bounded ring of engine events with Chrome-trace export."""

    def __init__(self, maxlen: int | None = None):
        self._ring: deque[tuple] = deque(
            maxlen=_ring_size() if maxlen is None else max(16, int(maxlen))
        )
        self._lock = threading.Lock()  # guards export/reset, not recording

    # -- recording (lock-free: one atomic deque.append) ------------------

    def event(self, name: str, *, rid: str | None = None,
              mesh: str | None = None, slot: int | None = None,
              **args) -> None:
        """Record one instant event (a scheduler decision / lifecycle
        edge) at the current timestamp."""
        tags = self._tags(rid, None, mesh, slot, args)
        rec = (_INSTANT, name, time.perf_counter(), tags)
        self._ring.append(rec)
        self._spill(rec)

    def dispatch(self, name: str, t0: float, t_issue: float, t1: float, *,
                 rid: str | None = None, rids=None,
                 mesh: str | None = None, slot: int | None = None,
                 **args) -> None:
        """Record one device dispatch: ``t0`` call begin, ``t_issue`` the
        jitted call returned (dispatch issued, device running), ``t1``
        host sync complete. All three are time.perf_counter() values."""
        tags = self._tags(rid, rids, mesh, slot, args)
        rec = (_DISPATCH, name, t0, t_issue, t1, tags)
        self._ring.append(rec)
        self._spill(rec)

    @staticmethod
    def _tags(rid, rids, mesh, slot, args) -> dict:
        tags = dict(args)
        if rid is not None:
            tags["rid"] = rid
        if rids is not None:
            tags["rids"] = list(rids)
        if mesh is not None:
            tags["mesh"] = mesh
        if slot is not None:
            tags["slot"] = slot
        return tags

    def _spill(self, rec: tuple) -> None:
        path = os.environ.get("FEI_TPU_FLIGHT_FILE")
        if not path:
            return
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(self._as_dict(rec)) + "\n")
        except OSError:
            pass  # flight recording must never take down the serving loop

    # -- export -----------------------------------------------------------

    @staticmethod
    def _as_dict(rec: tuple) -> dict:
        if rec[0] == _INSTANT:
            _, name, ts, tags = rec
            return {"kind": "instant", "name": name,
                    "ts": round(ts, 6), "tags": tags}
        _, name, t0, t_issue, t1, tags = rec
        return {"kind": "dispatch", "name": name, "ts": round(t0, 6),
                "issue_s": round(t_issue - t0, 6),
                "sync_s": round(t1 - t_issue, 6), "tags": tags}

    def records(self) -> list[dict]:
        """Snapshot of the ring as plain dicts, oldest first."""
        with self._lock:
            ring = list(self._ring)
        return [self._as_dict(r) for r in ring]

    def counts(self) -> Counter:
        """Record count per event name — the recorder side of the
        dispatch-accounting cross-check against METRICS counters."""
        with self._lock:
            ring = list(self._ring)
        return Counter(r[1] for r in ring)

    def for_rid(self, rid: str) -> list[dict]:
        """The ring slice mentioning one request id (instants tagged with
        it, dispatches that served it)."""
        out = []
        for rec in self.records():
            tags = rec["tags"]
            if tags.get("rid") == rid or rid in (tags.get("rids") or ()):
                out.append(rec)
        return out

    def chrome_trace(self) -> dict:
        """The ring as Chrome-trace JSON (``{"traceEvents": [...]}``,
        timestamps in µs). Each dispatch expands into two complete ("X")
        events — ``<name>.issue`` and ``<name>.sync`` — so the issue/sync
        split is visible as adjacent slices on the timeline; instants
        export as ph="i". Args carry the rid/mesh/slot tags verbatim."""
        with self._lock:
            ring = list(self._ring)
        events = []
        for rec in ring:
            if rec[0] == _INSTANT:
                _, name, ts, tags = rec
                events.append({
                    "name": name, "ph": "i", "s": "g",
                    "ts": round(ts * 1e6, 3), "pid": 1, "tid": 1,
                    "args": tags,
                })
            else:
                _, name, t0, t_issue, t1, tags = rec
                events.append({
                    "name": f"{name}.issue", "ph": "X",
                    "ts": round(t0 * 1e6, 3),
                    "dur": round(max(0.0, t_issue - t0) * 1e6, 3),
                    "pid": 1, "tid": 1, "args": tags,
                })
                events.append({
                    "name": f"{name}.sync", "ph": "X",
                    "ts": round(t_issue * 1e6, 3),
                    "dur": round(max(0.0, t1 - t_issue) * 1e6, 3),
                    "pid": 1, "tid": 1, "args": tags,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


class CompileObserver:
    """Counts and times jit compilations per program signature.

    Every jitted-program cache routes its cache-miss through ``wrap``:
    the first miss of a ``(family, key)`` signature counts as a compile
    (``engine.compiles``) and its first invocation — where XLA actually
    compiles — is timed into the ``compile_seconds`` histogram; a LATER
    miss of the same signature (the cache was dropped or the key leaked)
    counts as a steady-state recompile (``engine.recompiles``) and
    records a flight event, because a silent recompile mid-serving is a
    perf bug, not a warmup cost. One observer per engine, so tests see
    only their own engine's signatures.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._seen: set = set()

    def wrap(self, family: str, key, fn):
        """Register a cache miss for ``(family, key)`` and return ``fn``
        wrapped so its first invocation is timed as the compile."""
        sig = (family, key)
        with self._lock:
            if sig in self._seen:
                METRICS.incr("engine.recompiles")
                FLIGHT.event("recompile", family=family, key=str(key))
            else:
                self._seen.add(sig)
                METRICS.incr("engine.compiles")
        state = {"first": True}

        def timed(*a, **kw):
            if state["first"]:
                state["first"] = False
                t0 = time.perf_counter()
                out = fn(*a, **kw)
                dt = time.perf_counter() - t0
                METRICS.timing("compile", dt)
                FLIGHT.event("compile", family=family, key=str(key),
                             seconds=round(dt, 6))
                return out
            return fn(*a, **kw)

        return timed


FLIGHT = FlightRecorder()
