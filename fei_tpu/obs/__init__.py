"""Observability subsystem: metrics, histograms, request traces, the
engine flight recorder, the roofline cost model, and Prometheus
exposition. fei_tpu/utils/metrics.py re-exports the METRICS singleton
from here so pre-existing call sites are unchanged."""

from fei_tpu.obs.flight import FLIGHT, CompileObserver, FlightRecorder
from fei_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    METRICS,
    Histogram,
    Metrics,
)
from fei_tpu.obs.registry import METRIC_REGISTRY, declared, help_for
from fei_tpu.obs.render import snapshot_lines
from fei_tpu.obs.trace import TRACES, RequestTrace, TraceBuffer

__all__ = [
    "DEFAULT_BUCKETS",
    "FLIGHT",
    "METRICS",
    "METRIC_REGISTRY",
    "CompileObserver",
    "FlightRecorder",
    "Histogram",
    "Metrics",
    "RequestTrace",
    "TRACES",
    "TraceBuffer",
    "declared",
    "help_for",
    "snapshot_lines",
]
