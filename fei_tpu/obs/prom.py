"""Prometheus text exposition (format 0.0.4) for the METRICS snapshot.

Pure rendering — no state, no locks; Metrics.prometheus_text() collects a
consistent snapshot under its lock and hands the plain dicts here. Names
are sanitized to the Prometheus charset and prefixed ``fei_``; counters
get the conventional ``_total`` suffix; histograms emit cumulative
``le``-labelled buckets plus ``_sum``/``_count``.
"""

from __future__ import annotations

import re

from fei_tpu.obs.registry import help_for

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    n = _INVALID.sub("_", name)
    if not re.match(r"[a-zA-Z_:]", n):
        n = "_" + n
    return "fei_" + n


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.10g}"


def _header(lines: list[str], prom_name: str, raw_name: str,
            kind: str) -> None:
    info = help_for(raw_name)
    help_text = info[1] if info else raw_name
    lines.append(f"# HELP {prom_name} {_escape_help(help_text)}")
    lines.append(f"# TYPE {prom_name} {kind}")


def render_prometheus(counters: dict, gauges: dict, hists: dict) -> str:
    """hists maps name -> (bounds, counts, inf_count, sum, count), the
    Histogram.state() tuple."""
    lines: list[str] = []
    for name in sorted(counters):
        prom = _sanitize(name) + "_total"
        _header(lines, prom, name, "counter")
        lines.append(f"{prom} {_fmt(counters[name])}")
    for name in sorted(gauges):
        prom = _sanitize(name)
        _header(lines, prom, name, "gauge")
        lines.append(f"{prom} {_fmt(gauges[name])}")
    for name in sorted(hists):
        bounds, counts, inf_count, total_sum, count = hists[name]
        prom = _sanitize(name)
        _header(lines, prom, name, "histogram")
        cum = 0
        for b, c in zip(bounds, counts):
            cum += c
            le = _escape_label(f"{b:.10g}")
            lines.append(f'{prom}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{prom}_bucket{{le="+Inf"}} {cum + inf_count}')
        lines.append(f"{prom}_sum {total_sum:.9g}")
        lines.append(f"{prom}_count {count}")
    return "\n".join(lines) + "\n"
