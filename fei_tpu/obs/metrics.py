"""Structured metrics: counters, gauges, span timers, and histograms.

Grown from fei_tpu/utils/metrics.py (which now re-exports this module so
every existing ``METRICS.*`` call site keeps working). The reference has no
tracing/profiling at all (SURVEY.md §5); this is the core of the
observability layer: cheap counters and gauges, wall-clock span timing with
per-phase aggregation, and fixed-bucket log-spaced latency histograms with
p50/p95/p99 summaries — the percentile surface production engines treat as
a first-class output (RTP-LLM, PAPERS.md). Every span also feeds a
``<name>_seconds`` histogram, so TTFT, per-decode-step latency, prefill
time, and tool-call duration get percentile summaries for free.

Exposition lives in fei_tpu/obs/prom.py (Prometheus text format, served by
``GET /metrics`` on ui/server.py); the metric-name registry every call site
must be declared in is fei_tpu/obs/registry.py (enforced by
scripts/metrics_lint.py in tier-1).
"""

from __future__ import annotations

import bisect
import contextlib
import json
import threading
import time
from collections import defaultdict
from dataclasses import dataclass

# jax.profiler resolution is cached process-wide: None = not yet probed,
# False = unavailable, otherwise the TraceAnnotation class itself. The old
# implementation re-imported jax inside every span(jax_trace=True) call.
_TRACE_ANNOTATION: object = None


def _jax_annotation(name: str):
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        try:
            from jax.profiler import TraceAnnotation

            _TRACE_ANNOTATION = TraceAnnotation
        except Exception:  # noqa: BLE001 — jax may be absent or broken
            _TRACE_ANNOTATION = False
    if _TRACE_ANNOTATION:
        return _TRACE_ANNOTATION(name)
    return contextlib.nullcontext()


@dataclass
class _Stat:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0

    def record(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.min_s = min(self.min_s, dt)
        self.max_s = max(self.max_s, dt)

    def as_dict(self) -> dict:
        mean = self.total_s / self.count if self.count else 0.0
        return {
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "mean_s": round(mean, 6),
            "min_s": round(self.min_s, 6) if self.count else 0.0,
            "max_s": round(self.max_s, 6),
        }


# 24 log-spaced (factor-2) upper bounds from 100 µs to ~839 s: one fixed
# ladder for every latency histogram, so bucket layouts never vary per
# metric and Prometheus can aggregate across restarts.
DEFAULT_BUCKETS: tuple[float, ...] = tuple(1e-4 * 2.0**i for i in range(24))


class Histogram:
    """Fixed-bucket histogram with Prometheus-style quantile estimation.

    ``bounds`` are inclusive upper edges (``le``); observations above the
    last bound land in the implicit +Inf bucket. Quantiles interpolate
    linearly inside the owning bucket (the histogram_quantile rule), which
    makes the math exact and testable on synthetic data.
    """

    __slots__ = ("bounds", "counts", "inf_count", "count", "sum", "min", "max")

    def __init__(self, buckets: tuple[float, ...] | list[float] | None = None):
        bounds = tuple(
            float(b)
            for b in (DEFAULT_BUCKETS if buckets is None else buckets)
        )
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError("histogram buckets must be strictly increasing")
        self.bounds = bounds
        self.counts = [0] * len(bounds)
        self.inf_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        idx = bisect.bisect_left(self.bounds, v)  # first bound >= v (le)
        if idx < len(self.bounds):
            self.counts[idx] += 1
        else:
            self.inf_count += 1

    def quantile(self, q: float) -> float:
        """The q-quantile (q in [0, 1]) under the uniform-within-bucket
        assumption; observations in the +Inf bucket report the last finite
        bound (Prometheus convention)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            prev = cum
            cum += c
            if c and cum >= rank:
                lo = self.bounds[i - 1] if i else 0.0
                hi = self.bounds[i]
                return lo + (hi - lo) * max(0.0, rank - prev) / c
        return self.bounds[-1]

    def summary(self) -> dict:
        mean = self.sum / self.count if self.count else 0.0
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "mean": round(mean, 6),
            "p50": round(self.quantile(0.50), 6),
            "p95": round(self.quantile(0.95), 6),
            "p99": round(self.quantile(0.99), 6),
            "min": round(self.min, 6) if self.count else 0.0,
            "max": round(self.max, 6),
        }

    def state(self) -> tuple:
        """(bounds, per-bucket counts, +Inf count, sum, count) — the raw
        series the Prometheus renderer needs (cumulative le buckets)."""
        return (self.bounds, list(self.counts), self.inf_count,
                self.sum, self.count)


class Metrics:
    """Thread-safe counters, gauges, span timers, and histograms."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._spans: dict[str, _Stat] = defaultdict(_Stat)
        self._hists: dict[str, Histogram] = {}

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Record one observation into the named histogram."""
        with self._lock:
            self._hist_locked(name).observe(value)

    def _hist_locked(self, name: str) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = Histogram()
        return h

    @contextlib.contextmanager
    def span(self, name: str, jax_trace: bool = False):
        """Time a block; optionally also emit a jax.profiler trace
        annotation (import resolved once per process, not per call). The
        duration feeds both the span aggregate and a ``<name>_seconds``
        histogram."""
        ctx = _jax_annotation(name) if jax_trace else contextlib.nullcontext()
        start = time.perf_counter()
        try:
            with ctx:
                yield
        finally:
            dt = time.perf_counter() - start
            with self._lock:
                self._spans[name].record(dt)
                self._hist_locked(name + "_seconds").observe(dt)

    def timing(self, name: str, dt: float) -> None:
        with self._lock:
            self._spans[name].record(dt)
            self._hist_locked(name + "_seconds").observe(dt)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "spans": {k: v.as_dict() for k, v in self._spans.items()},
                "histograms": {
                    k: v.summary() for k, v in self._hists.items()
                },
            }

    def prometheus_text(self) -> str:
        """The full exposition in Prometheus text format (0.0.4)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: v.state() for k, v in self._hists.items()}
        from fei_tpu.obs.prom import render_prometheus

        return render_prometheus(counters, gauges, hists)

    def dumps(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._spans.clear()
            self._hists.clear()


METRICS = Metrics()
