"""Declared metric names — the single source of truth for dashboards.

Every ``METRICS.incr/gauge/observe/span/timing`` call site in fei_tpu/
must use a name declared here (wildcards allowed for families like
``tool.*``); scripts/metrics_lint.py enforces this in tier-1 so renames
can't silently break dashboards. docs/OBSERVABILITY.md renders from the
same table.
"""

from __future__ import annotations

from fnmatch import fnmatch

# name (or fnmatch pattern) -> (kind, help text)
METRIC_REGISTRY: dict[str, tuple[str, str]] = {
    # --- counters -------------------------------------------------------
    "agent.tool_calls": ("counter", "Tool calls issued by the assistant loop."),
    "agent.prompt_tokens": ("counter", "Prompt tokens consumed by LLM calls."),
    "agent.completion_tokens": ("counter",
                                "Completion tokens produced by LLM calls."),
    "tool.calls": ("counter", "Tool executions via the registry."),
    "tool.errors": ("counter", "Tool executions that raised."),
    "scheduler.requests_submitted": ("counter",
                                     "Sequences submitted to the scheduler."),
    "scheduler.requests_completed": ("counter",
                                     "Sequences finished normally."),
    "scheduler.requests_cancelled": ("counter", "Sequences cancelled."),
    "scheduler.requests_failed": ("counter",
                                  "Sequences failed with an error."),
    "scheduler.requests_failed_isolated": (
        "counter", "Request-scoped failures contained to one sequence "
                   "(slot evicted via the healthy-pool path; other "
                   "streams unaffected)."),
    "scheduler.requests_shed": (
        "counter", "Requests rejected by backpressure: waiting queue at "
                   "FEI_TPU_MAX_QUEUE, degraded-state rejections, or "
                   "deadline already expired while queued."),
    "scheduler.requests_deadline_exceeded": (
        "counter", "Sequences that hit their deadline (shed at admission "
                   "or cancelled mid-decode)."),
    "scheduler.admission_blocked": ("counter",
                                    "Admissions deferred by page-pool "
                                    "pressure."),
    "scheduler.preemptions": (
        "counter", "Sequences preempted under KV-pool pressure (snapshot "
                   "+ release + requeue; they resume byte-identically)."),
    "scheduler.preempted_tokens_recomputed": (
        "counter", "Token positions re-prefilled when preempted sequences "
                   "resumed (prefix-cache hits excluded)."),
    "scheduler.resume_replayed_tokens": (
        "counter", "Generated-suffix tokens replayed through the decode-"
                   "shaped forward at resume (bitwise KV rebuild)."),
    "scheduler.prefill_tokens": (
        "counter", "Prompt tokens actually prefilled at admission "
                   "(prefix-cache and content-addressed tier hits "
                   "excluded); divide by total prompt tokens for the "
                   "flops-saved ratio."),
    "scheduler.lazy_grown_pages": (
        "counter", "KV pages allocated mid-decode for lazily-reserved "
                   "sequences."),
    "scheduler.requests_snapshotted": (
        "counter", "Requests snapshotted to disk at drain for warm "
                   "restart."),
    "scheduler.requests_restored": (
        "counter", "Snapshotted requests re-admitted by a warm restart."),
    "scheduler.decode_steps": ("counter",
                               "Device decode steps dispatched."),
    "scheduler.decode_slot_steps": ("counter",
                                    "Per-slot decode steps (steps x active "
                                    "slots)."),
    "scheduler.paged_prefill_disabled": ("counter",
                                         "Paged-native prefill fallbacks."),
    "scheduler.ragged_disabled": (
        "counter", "Merged ragged dispatches disarmed after an on-chip "
                   "failure (legacy two-program path takes over)."),
    "scheduler.spec_steps": ("counter", "Speculative decode steps."),
    "scheduler.spec_accepted": ("counter",
                                "Speculative tokens accepted."),
    "scheduler.spec_disabled": ("counter",
                                "Speculation disabled for a sequence."),
    "scheduler.host_mask_uploads": ("counter",
                                    "Host-side grammar mask uploads."),
    "scheduler.multi_steps": ("counter", "Multi-step decode dispatches."),
    "scheduler.multi_tokens": ("counter",
                               "Tokens produced by multi-step decode."),
    "scheduler.turbo_under_admission": (
        "counter", "Multi-step dispatches run while an admission was "
                   "queued or prefilling in chunks."),
    "scheduler.turbo_rollbacks": (
        "counter", "Free-phase slots rolled back to a mid-scan grammar "
                   "trigger (pool length + rng key restored)."),
    "scheduler.turbo_rollback_tokens": (
        "counter", "Scanned-ahead tokens discarded by free-phase trigger "
                   "rollbacks."),
    "scheduler.swa_pages_released": ("counter",
                                     "KV pages released by sliding-window "
                                     "attention."),
    "scheduler.grammar_trigger_suffix_rejected": (
        "counter", "Grammar trigger suffixes rejected by the matcher."),
    "scheduler.grammar_walked_off": (
        "counter", "Grammar walks that left the trigger automaton."),
    "engine.sp_prefills": ("counter", "Sequence-parallel prefill launches."),
    "engine.decode_dispatches": ("counter",
                                 "Free-phase decode dispatches (fused "
                                 "chunks or per-token steps) — per-token "
                                 "regressions show as a jump vs tokens "
                                 "emitted."),
    "engine.ragged_dispatches": (
        "counter", "Merged ragged dispatches: decode scans that also "
                   "carried a prefill chunk in one program (one weight "
                   "stream for both)."),
    "engine.kernel_loop_depth": (
        "gauge", "Scanned depth of the last decode dispatch in layer "
                 "programs (steps x layers collapsed into one "
                 "dispatch)."),
    "engine.grammar_trigger_suffix_rejected": (
        "counter", "Grammar trigger suffixes rejected (engine path)."),
    "engine.grammar_budget_too_small": (
        "counter", "Fused grammar chunks skipped: token budget too small."),
    "engine.grammar_fused_steps": ("counter",
                                   "Fused grammar-constrained steps."),
    "engine.grammar_walked_off": (
        "counter", "Grammar walks off the automaton (engine path)."),
    "prefix.hits": ("counter", "Prefix-cache hits on admission."),
    "prefix.misses": ("counter", "Prefix-cache misses on admission."),
    "prefix.evictions": ("counter", "Prefix-cache entries evicted."),
    "server.requests": ("counter", "HTTP requests handled by the API core."),
    "provider.retries": ("counter",
                         "Remote provider HTTP attempts retried "
                         "(connection errors and 429/5xx)."),
    "server.profile_captures": ("counter",
                                "On-demand jax.profiler captures taken."),
    "server.drains": ("counter", "Graceful drains initiated via POST "
                                 "/drain."),
    "scheduler.priority_preemptions": (
        "counter", "Running sequences preempted by a strictly higher-"
                   "priority arrival when every slot was busy (the victim "
                   "re-queues and resumes byte-identically)."),
    "scheduler.tenant_budget_deferred": (
        "counter", "Admissions deferred because the candidate tenant's "
                   "reserved-token inflight would exceed its "
                   "FEI_TPU_TENANT_BUDGETS token budget."),
    "tenant.*.tokens_served": ("counter",
                               "Tokens delivered to one tenant's "
                               "requests (per-tenant family)."),
    "tenant.*.sheds": ("counter",
                       "Requests from one tenant rejected by "
                       "backpressure or evicted from the full queue by a "
                       "higher-priority arrival."),
    "tenant.*.preemptions": ("counter",
                             "Preemptions (pool-pressure or priority) "
                             "charged to one tenant's sequences."),
    "router.requests": ("counter", "Requests routed by the fleet router."),
    "router.retries": ("counter",
                       "Forward attempts retried on another replica "
                       "(connection failures and 429/503 backpressure)."),
    "router.ejections": ("counter",
                         "Replicas ejected by the per-replica circuit "
                         "breaker (consecutive-failure threshold)."),
    "router.readmissions": ("counter",
                            "Ejected replicas readmitted after a "
                            "successful half-open health probe."),
    "router.affinity_hits": ("counter",
                             "Requests routed to their session/prefix "
                             "affinity replica."),
    "router.affinity_misses": ("counter",
                               "Affinity lookups that fell back (replica "
                               "draining, ejected, or unknown key)."),
    "router.sheds": ("counter",
                     "Requests the router shed with 503 after every "
                     "replica was unusable or retries were exhausted."),
    "router.invalid_requests": ("counter",
                                "Malformed client requests answered 400 "
                                "at the router without charging any "
                                "replica's breaker."),
    "router.deadline_expired": ("counter",
                                "Requests that ran out of client deadline "
                                "inside the router retry loop (504)."),
    "router.rolling_restarts": ("counter",
                                "Zero-downtime rolling restarts completed "
                                "across the replica set."),
    "router.role_routed": ("counter",
                           "Requests steered by the replica role split "
                           "(prefill-heavy vs decode/mixed preference "
                           "narrowed the candidate set)."),
    "router.migrations": ("counter",
                          "KV sessions migrated between replicas over the "
                          "/kv/export -> /kv/import control plane "
                          "(affinity-miss repair and prefill->decode "
                          "handoff)."),
    "router.migration_failures": ("counter",
                                  "KV migrations that failed or were "
                                  "refused (target full, corrupt blob, "
                                  "transport error); the session simply "
                                  "re-prefills."),
    "kv.spills": ("counter",
                  "Preempted sequences whose KV pages were spilled to the "
                  "host tier (the spill-before-preempt rung)."),
    "kv.spill_failures": ("counter",
                          "Spill attempts that failed (tier I/O, injected "
                          "fault); the sequence still resumes via token "
                          "replay."),
    "kv.pages_spilled": ("counter",
                         "KV pages copied HBM -> host tier at preemption."),
    "kv.bytes_spilled": ("counter",
                         "Bytes copied HBM -> host tier at preemption."),
    "kv.fetches": ("counter",
                   "Resumes served by streaming spilled pages back instead "
                   "of re-prefilling."),
    "kv.pages_restored": ("counter",
                          "KV pages streamed host tier -> HBM at resume."),
    "kv.bytes_fetched": ("counter",
                         "Bytes streamed host tier -> HBM at resume."),
    "kv.fetch_misses": ("counter",
                        "Tier lookups that found no entry (evicted or "
                        "never spilled); resume falls back to replay."),
    "kv.fetch_corrupt": ("counter",
                         "Tier entries rejected by checksum/format "
                         "validation; the entry is discarded and resume "
                         "falls back to replay."),
    "kv.fetch_fallbacks": ("counter",
                           "Resumes that fell back to token replay after "
                           "the tier could not serve them (miss, corrupt, "
                           "stale, or I/O error)."),
    "kv.demotions": ("counter",
                     "Tier entries demoted host RAM -> disk by the RAM "
                     "budget (FEI_TPU_KV_RAM_BYTES)."),
    "kv.evictions": ("counter",
                     "Tier entries dropped entirely by budget pressure "
                     "(no disk tier, or disk budget exceeded)."),
    "kv.migrations_out": ("counter",
                          "Sessions exported as migration blobs by this "
                          "replica."),
    "kv.migrations_in": ("counter",
                         "Migration blobs imported into this replica's "
                         "pool and prefix cache."),
    "kv.pages_migrated": ("counter",
                          "KV pages landed by migration imports."),
    "kv.bytes_migrated": ("counter",
                          "Bytes serialized into migration blobs."),
    "kv.cas_stores": ("counter",
                      "Content-addressed prefix blobs stored in the tier "
                      "(first copy of that content)."),
    "kv.cas_dedup_hits": ("counter",
                          "Content-addressed publishes deduplicated "
                          "against an existing tier copy (N sessions, "
                          "one copy)."),
    "kv.prefix_hits_tier": ("counter",
                            "Admissions whose prefix pages were fetched "
                            "from the local tier by content hash instead "
                            "of re-prefilled."),
    "kv.prefix_tokens_saved": ("counter",
                               "Prompt tokens NOT re-prefilled thanks to "
                               "content-addressed tier hits."),
    "kv.prefix_hits_remote": ("counter",
                              "Prefix blobs the router fetched from a "
                              "peer replica and placed ahead of a cold "
                              "forward."),
    "router.prefix_fetch_failures": ("counter",
                                     "Best-effort peer prefix fetches "
                                     "that failed (probe error, no "
                                     "source served the blob, push "
                                     "refused); the session simply "
                                     "prefills."),
    "router.prewarm_pushes": ("counter",
                              "Hot prefix blobs pushed into a replica by "
                              "speculative pre-warm (rolling restart / "
                              "scale-up)."),
    "router.prewarm_failures": ("counter",
                                "Pre-warm pushes that failed (replica "
                                "unreachable, refused, or corrupt blob); "
                                "the replica serves cold instead."),
    "journal.appends": ("counter",
                        "Records appended to the crash-consistency "
                        "session journal (admissions, delivered tokens, "
                        "terminals)."),
    "journal.bytes": ("counter",
                      "Bytes written to the session journal (framing "
                      "included)."),
    "journal.fsyncs": ("counter",
                       "fsync() calls issued by the journal writer "
                       "(FEI_TPU_JOURNAL_SYNC=batch coalesces; =always "
                       "is one per record)."),
    "journal.recovered_sessions": ("counter",
                                   "Unfinished sessions re-admitted from "
                                   "the journal at warm restart "
                                   "(byte-identical replay)."),
    "journal.torn_records": ("counter",
                             "Half-appended journal records discarded at "
                             "recovery (the crash landed mid-write; "
                             "committed tokens are never among them)."),
    "engine.crash_recoveries": ("counter",
                                "Warm restarts that found and replayed "
                                "at least one journaled session."),
    "engine.recovery_skipped.*": ("counter",
                                  "Journaled sessions a warm restart "
                                  "could NOT re-admit, by reason "
                                  "(page_size: the one geometry gate; "
                                  "deadline_expired: the client's "
                                  "budget ran out mid-crash)."),
    "engine.cross_mesh_recoveries": ("counter",
                                     "Journaled sessions re-admitted "
                                     "onto a DIFFERENT mesh than the "
                                     "one that crashed (tp2 journal "
                                     "replayed on single-chip, etc.) — "
                                     "byte-identical via teacher-"
                                     "forced replay."),
    "kv.resharded_imports": ("counter",
                             "KV blobs (migration, CAS admit, CDN) "
                             "imported across a tp layout skew — the "
                             "host interchange format carries the full "
                             "kv-head extent, so the scatter resheds "
                             "instead of refusing."),
    "router.geometry_skips": ("counter",
                              "Fleet KV/session moves skipped because "
                              "the replicas' INVARIANT fingerprints "
                              "can never match (heterogeneous fleet: "
                              "different model/dtype/page_size) — "
                              "pre-flight off /health or a 409 from "
                              "the /kv plane; never retried."),
    "router.resurrections": ("counter",
                             "Mid-stream sessions moved to a survivor "
                             "after their replica died with tokens "
                             "already delivered."),
    "router.resurrection_replayed_tokens": (
        "counter",
        "Delivered tokens teacher-forced into a survivor during "
        "resurrection (the client never sees them twice)."),
    "engine.compiles": ("counter",
                        "Jit program compilations observed (first build "
                        "per program signature — warmup cost)."),
    "engine.recompiles": ("counter",
                          "Compilations of an ALREADY-SEEN program "
                          "signature: steady-state recompiles; each one "
                          "is a dropped cache or a shape leak, not "
                          "warmup."),
    # --- gauges ---------------------------------------------------------
    "last_ttft_s": ("gauge", "TTFT of the most recent generation (s)."),
    "last_decode_tok_s": ("gauge",
                          "Decode throughput of the most recent "
                          "generation (tok/s)."),
    "scheduler.queue_depth": ("gauge", "Sequences waiting for admission."),
    "engine.degraded": ("gauge",
                        "1 while the crash-loop breaker holds the engine "
                        "degraded (submits rejected), else 0."),
    "engine.draining": ("gauge",
                        "1 once a graceful drain began (sticky for the "
                        "process lifetime), else 0."),
    "scheduler.running_slots": ("gauge", "Sequences actively decoding."),
    "engine.mesh_shape": ("gauge",
                          "Devices in the serving mesh (1 = single chip); "
                          "per-axis sizes in engine.mesh.*."),
    "engine.mesh.*": ("gauge",
                      "Serving-mesh axis size (dp/tp/ep/sp/pp family; 1 = "
                      "axis unused)."),
    "scheduler.replica.*.slots": ("gauge",
                                  "Active decode slots in one dp replica "
                                  "group's batch slice."),
    "scheduler.replica.*.queue_depth": (
        "gauge", "Waiting requests attributed to one dp replica group "
                 "(balanced share of the shared admission queue)."),
    "scheduler.batch_slots_active": ("gauge",
                                     "Active slots in the last decode "
                                     "dispatch (batch utilization)."),
    "pool.pages_total": ("gauge", "Allocatable KV pages (null page "
                                  "excluded)."),
    "pool.pages_free": ("gauge", "Free KV pages."),
    "pool.pages_in_use": ("gauge", "KV pages currently referenced."),
    "prefix.entries": ("gauge", "Entries resident in the prefix cache."),
    "roofline.frac": ("gauge",
                      "Fraction of the aggregate HBM roofline achieved by "
                      "the most recent decode dispatch (analytical bytes "
                      "estimate / wall time vs FEI_TPU_HBM_GBPS × chips)."),
    "roofline.tok_s_per_chip": ("gauge",
                                "Delivered tokens/s per chip over the most "
                                "recent decode dispatch."),
    "tenant.*.queued": ("gauge",
                        "Sequences from one tenant waiting for admission "
                        "(emitted only when tenant budgets are "
                        "configured)."),
    "tenant.*.running": ("gauge",
                         "Sequences from one tenant actively decoding "
                         "(emitted only when tenant budgets are "
                         "configured)."),
    "router.replicas_usable": ("gauge",
                               "Replicas the fleet router considers "
                               "routable (healthy, not draining, not "
                               "ejected)."),
    "kv.tier_bytes_ram": ("gauge",
                          "Bytes resident in the host-RAM KV tier."),
    "kv.tier_bytes_disk": ("gauge",
                           "Bytes resident in the on-disk KV tier."),
    "kv.tier_entries": ("gauge",
                        "Entries resident across both KV tiers."),
    "kv.dedup_ratio": ("gauge",
                       "Fraction of content-addressed publishes that "
                       "deduplicated against an existing copy "
                       "(hits / (hits + stores))."),
    "kv.spilled_gbps": ("gauge",
                        "Achieved HBM -> host throughput of the most "
                        "recent spill (GB/s)."),
    "kv.fetched_gbps": ("gauge",
                        "Achieved host -> HBM throughput of the most "
                        "recent streamed resume (GB/s)."),
    # --- spans (each also feeds a <name>_seconds histogram) -------------
    "prefill": ("span", "Full prefill dispatch."),
    "prefill_chunk": ("span", "One chunked-prefill chunk."),
    "prefill_sp": ("span", "Sequence-parallel prefill dispatch."),
    "decode_step": ("span", "One device decode step."),
    "dispatch_issue": ("span",
                       "Host time to ISSUE one decode dispatch (call "
                       "until the jitted function returned; the device "
                       "keeps running)."),
    "dispatch_sync": ("span",
                      "Host block-until-ready time for one decode "
                      "dispatch (device compute + transport)."),
    "compile": ("span",
                "One observed jit compilation (first invocation of a "
                "program signature)."),
    "decode_chunk": ("span", "One fused free-phase decode chunk (the "
                             "blocking host sync; dispatch is pipelined)."),
    "spec_step": ("span", "One speculative decode step."),
    "grammar_fused_chunk": ("span", "One fused grammar-constrained chunk."),
    "kv_spill": ("span", "One HBM -> host tier spill (gather + enqueue)."),
    "kv_fetch": ("span", "One host tier -> HBM streamed resume (fetch + "
                         "scatter)."),
    "agent.completion": ("span", "One LLM call from the assistant loop."),
    "provider.jax_local": ("span", "One local-engine provider call."),
    "tool.*": ("span", "One tool execution (per-tool family)."),
    "collective.*": ("span",
                     "Sharded decode-dispatch wall time attributed to one "
                     "active mesh axis (per-axis family; an upper bound "
                     "on that axis's collective time — the step includes "
                     "compute)."),
    # --- histograms (observed directly, not via span) -------------------
    "ttft_seconds": ("histogram",
                     "Time from submit to first emitted token."),
    "queue_wait_seconds": ("histogram",
                           "Time from submit to scheduler admission."),
}


def declared(name: str) -> bool:
    """True if a call-site metric name is covered by the registry.

    ``name`` may itself contain ``*`` (the lint normalizes f-string
    ``{...}`` segments to ``*``), so match in both directions.
    """
    if name in METRIC_REGISTRY:
        return True
    return any(
        fnmatch(name, pat) or fnmatch(pat, name) for pat in METRIC_REGISTRY
    )


def help_for(name: str) -> tuple[str, str] | None:
    """(kind, help) for a concrete metric name; ``*_seconds`` histograms
    derived from spans resolve through their base span name."""
    if name in METRIC_REGISTRY:
        return METRIC_REGISTRY[name]
    for pat, info in METRIC_REGISTRY.items():
        if "*" in pat and fnmatch(name, pat):
            return info
    if name.endswith("_seconds"):
        base = help_for(name[: -len("_seconds")])
        if base is not None:
            return ("histogram", base[1] + " (latency histogram)")
    return None
