"""Per-request lifecycle traces for the paged scheduler.

Every submitted sequence gets a request id and an ordered list of phase
events — queued → admitted → prefill → first_token → completed/cancelled/
failed/deadline_exceeded/snapshotted — kept in a bounded ring buffer
(``FEI_TPU_TRACE_RING``, default 256) and served by ``GET /v1/traces`` on
ui/server.py. Preempt-and-resume scheduling adds non-terminal
``preempted`` / ``resumed`` events mid-trace: a sequence evicted under
KV-pool pressure re-admits and continues byte-identically; ``snapshotted``
is the terminal state of a request persisted to disk by a graceful drain
for warm restart. Setting
``FEI_TPU_TRACE_FILE`` additionally appends each finished trace as one
JSONL line, the flight-recorder shape production schedulers use to debug
tail latency after the fact.

Timestamps are time.time() clamped to be non-decreasing within a trace,
so consumers can rely on monotonically ordered phases even across clock
adjustments.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field

TERMINAL_PHASES = (
    "completed", "cancelled", "failed", "deadline_exceeded", "snapshotted",
    # queued request displaced by a higher-priority arrival when the
    # bounded queue was full (scheduler._check_queue_caps) — counts into
    # scheduler.requests_shed like every other backpressure rejection
    "shed",
)


@dataclass
class RequestTrace:
    rid: str
    prompt_tokens: int = 0
    completion_tokens: int = 0
    status: str = "active"
    # serving-mesh tag ("ms1", "tp2", "tp2dp2", …) — post-hoc tail-latency
    # debugging needs to know which mesh mode served the request
    mesh: str = "ms1"
    events: list = field(default_factory=list)  # [(phase, ts), ...]

    def event(self, phase: str) -> None:
        now = time.time()
        if self.events and now < self.events[-1][1]:
            now = self.events[-1][1]
        self.events.append((phase, now))

    def as_dict(self) -> dict:
        spans = [{"phase": p, "ts": round(ts, 6)} for p, ts in self.events]
        dur = 0.0
        if len(self.events) >= 2:
            dur = self.events[-1][1] - self.events[0][1]
        return {
            "id": self.rid,
            "status": self.status,
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "mesh": self.mesh,
            "duration_s": round(dur, 6),
            "spans": spans,
        }


class TraceBuffer:
    """Bounded ring of recent request traces (oldest evicted first)."""

    def __init__(self, maxlen: int | None = None):
        if maxlen is None:
            try:
                maxlen = int(os.environ.get("FEI_TPU_TRACE_RING", "256"))
            except ValueError:
                maxlen = 256
        self._lock = threading.Lock()
        self._ring: deque[RequestTrace] = deque(maxlen=max(1, maxlen))

    def start(self, prompt_tokens: int = 0, mesh: str = "ms1") -> RequestTrace:
        tr = RequestTrace(
            rid=f"req-{uuid.uuid4().hex[:12]}", prompt_tokens=prompt_tokens,
            mesh=mesh,
        )
        tr.event("queued")
        with self._lock:
            self._ring.append(tr)
        return tr

    def finish(self, trace: RequestTrace, status: str,
               completion_tokens: int | None = None) -> None:
        """Mark a trace terminal. Idempotent: the first terminal status
        wins, so racing cancel/finish paths can't double-record."""
        if status not in TERMINAL_PHASES:
            raise ValueError(f"not a terminal status: {status!r}")
        with self._lock:
            if trace.status != "active":
                return
            trace.status = status
            if completion_tokens is not None:
                trace.completion_tokens = completion_tokens
            trace.event(status)
        path = os.environ.get("FEI_TPU_TRACE_FILE")
        if path:
            try:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(trace.as_dict()) + "\n")
            except OSError:
                pass  # tracing must never take down the serving loop

    def get(self, rid: str) -> RequestTrace | None:
        """The trace with request id ``rid``, or None if it was never
        recorded or has been evicted from the ring."""
        with self._lock:
            for tr in reversed(self._ring):
                if tr.rid == rid:
                    return tr
        return None

    def recent(self, limit: int = 50) -> list[dict]:
        """Most recent traces first (active ones included)."""
        with self._lock:
            traces = list(self._ring)
        return [t.as_dict() for t in reversed(traces[-max(0, limit):])]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


TRACES = TraceBuffer()
