"""Entry point: dispatch to the CLI or the Textual TUI.

Parity with reference fei/__main__.py:11-28 (``--textual`` flag selects the
TUI; everything else goes to the CLI argparse).
"""

from __future__ import annotations

import sys


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--textual" in argv:
        argv.remove("--textual")
        from fei_tpu.ui.textual_chat import main as textual_main

        return textual_main(argv)
    from fei_tpu.ui.cli import main as cli_main

    return cli_main(argv)


if __name__ == "__main__":
    sys.exit(main())
