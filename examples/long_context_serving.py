"""Long-context serving: sequence-sharded prefill + speculative decode.

The agent task loop grows conversations without bound (reference
fei/core/task_executor.py:231-252). This demo serves a ~3k-token prompt on
an 8-device mesh: admission prefill runs ring-attention SEQUENCE-SHARDED
(each device holds T/8 tokens — parallel/long_prefill.py routed by the
engine), decode continues from the paged pool, and greedy echo output
takes multi-token speculative steps verified by the multi-query block
kernel.

    python examples/long_context_serving.py   (hermetic 8-device CPU mesh)
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.parallel.mesh import make_mesh
from fei_tpu.utils.metrics import METRICS


def main() -> None:
    mesh = make_mesh({"sp": 8})
    engine = InferenceEngine.from_config(
        "tiny", paged=True, batch_size=2, max_seq_len=4096,
        mesh=mesh, long_prefill_min=1024,
    )
    prompt = [(13 * i + 7) % 180 + 20 for i in range(3000)]
    gen = GenerationConfig(max_new_tokens=16, ignore_eos=True)

    toks = list(engine.scheduler.stream(prompt, gen))
    snap = METRICS.snapshot()
    sp = snap["counters"].get("engine.sp_prefills", 0)
    sp_s = snap["spans"].get("prefill_sp", {}).get("mean_s", 0.0)
    spec = snap["counters"].get("scheduler.spec_steps", 0)
    print(f"served 3000-token prompt -> {len(toks)} tokens decoded")
    print(f"sequence-sharded prefills: {sp:.0f} (one {sp_s:.2f}s dispatch, "
          f"each device held 3000/8 tokens via ring attention)")
    note = (
        "" if spec else " (random-weight output never echoed context this "
        "run; real agent outputs echo paths/identifiers and multi-step)"
    )
    print(f"speculative multi-token steps: {spec:.0f}{note}")


if __name__ == "__main__":
    main()
