"""The full in-tree serving loop: engine -> OpenAI endpoint -> OpenAI client.

Starts `fei serve`'s ServingServer over a tiny paged engine, then talks to
it two ways:
  1. a raw OpenAI-protocol request (urllib), streaming and non-streaming;
  2. our own RemoteProvider pointed at the endpoint — the transport shape
     the reference used for external APIs (fei/core/assistant.py:524-530),
     now closed onto the in-tree engine: agent, protocol, and decoder all
     local, zero external API calls.

Run: JAX_PLATFORMS=cpu python examples/serve_openai_endpoint.py
"""

import json
import urllib.request

import jax

jax.config.update("jax_platforms", "cpu")

from fei_tpu.agent.providers import JaxLocalProvider, RemoteProvider  # noqa: E402
from fei_tpu.engine.engine import InferenceEngine  # noqa: E402
from fei_tpu.ui.server import ServeAPI, ServingServer  # noqa: E402


def main() -> None:
    engine = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    api = ServeAPI(JaxLocalProvider(engine=engine), model_name="tiny")
    server = ServingServer(api)  # ephemeral port
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    print(f"serving on {base}/v1")

    # 1a. plain completion
    req = urllib.request.Request(
        f"{base}/v1/chat/completions",
        data=json.dumps({
            "messages": [{"role": "user", "content": "hello engine"}],
            "max_tokens": 12, "temperature": 0.8, "min_p": 0.1, "seed": 7,
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        body = json.loads(r.read())
    print("completion:", repr(body["choices"][0]["message"]["content"]),
          body["usage"])

    # 1b. SSE stream
    req = urllib.request.Request(
        f"{base}/v1/chat/completions",
        data=json.dumps({
            "messages": [{"role": "user", "content": "stream please"}],
            "max_tokens": 8, "stream": True,
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    n_chunks = 0
    with urllib.request.urlopen(req, timeout=300) as r:
        for line in r:
            if line.strip().startswith(b"data: ") and b"[DONE]" not in line:
                n_chunks += 1
    print(f"streamed {n_chunks} SSE chunks")

    # 2. our own remote provider against our own endpoint
    rp = RemoteProvider(provider="openai", model="tiny", api_base=f"{base}/v1")
    resp = rp.complete([{"role": "user", "content": "loop"}], max_tokens=8)
    print("self-loop reply:", repr(resp.content))

    server.stop()


if __name__ == "__main__":
    main()
