"""Two Memorychain nodes reaching consensus over HTTP: node B joins via
node A as seed, a proposal on A is quorum-voted and replicated to B
(reference docs/HOW_FEI_NETWORK_WORKS.md flow).

    python examples/memorychain_network.py
"""

import tempfile
import time

from fei_tpu.memory.memorychain.node import MemorychainNode


def main() -> None:
    with tempfile.TemporaryDirectory() as home:
        a = MemorychainNode(node_id="node-a", port=0, base_dir=f"{home}/a")
        a.start_background()
        b = MemorychainNode(
            node_id="node-b", port=0, base_dir=f"{home}/b", seed=a.address
        )
        b.start_background()
        time.sleep(0.2)
        print("a peers:", a.chain.peers)
        print("b peers:", b.chain.peers)

        block = a.chain.propose_memory(
            {"headers": {"Subject": "shared memory"}, "content": "via quorum"}
        )
        print("proposal committed as block:",
              block.index if block else "(rejected)")
        time.sleep(0.3)

        print("a height:", len(a.chain.blocks), "b height:", len(b.chain.blocks))
        a.shutdown()
        b.shutdown()


if __name__ == "__main__":
    main()
