"""Agent + Memdir: register the memory tool suite so the assistant can
save/search/recall memories, against a local store — no server needed for
the direct-store path (reference examples/fei_memdir_integration.py).

    python examples/memdir_integration.py
"""

import tempfile

from fei_tpu.memory.memdir.samples import create_samples
from fei_tpu.memory.memdir.search import parse_search_args, search_memories
from fei_tpu.memory.memdir.store import MemdirStore


def main() -> None:
    with tempfile.TemporaryDirectory() as base:
        store = MemdirStore(base)
        n = create_samples(store)
        print(f"seeded {n} memories")

        # the memdir query language: #tag, field:value, /regex/, sort:, limit:
        for query in ("#tpu", "Subject:project sort:date", "urgent"):
            hits = search_memories(store, parse_search_args(query))
            print(f"{query!r}: {len(hits)} hit(s)")
            for m in hits[:2]:
                print("   ", m.headers.get("Subject"))

        mem = store.save(
            "Ring attention rotates KV blocks over ICI.",
            tags=["tpu", "notes"],
        )
        print("saved:", mem.id)
        print("folders:", store.list_folders())


if __name__ == "__main__":
    main()
