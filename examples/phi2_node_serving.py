"""Phi-2 node serving: the reference's mocked scenario, run for real.

The reference's node-onboarding walkthrough shows a hypothetical node
benchmarking "Phi-2 inference: 67 tokens/s" on an RTX 3080
(/root/reference/docs/HOW_FEI_NETWORK_WORKS.md:60-75) — an illustrative
mock-up; the reference has no model code at all. Here the Phi architecture
(shared-norm parallel attn+MLP block, LayerNorm with bias, partial rotary,
fc1/fc2 biased MLP) is a first-class family in the scan-stacked decoder:
this example serves it through the paged scheduler exactly like the node
scenario describes, and on a real chip `FEI_TPU_BENCH_MODEL=phi-2
python bench.py` measures the real number (2.7B bf16 = 5.6 GB: one v5e).

Run hermetically on CPU (tiny-phi preset, random weights):
  JAX_PLATFORMS=cpu python examples/phi2_node_serving.py
With real weights (HF safetensors layout):
  FEI_TPU_PHI_MODEL=phi-2 FEI_TPU_PHI_CHECKPOINT=/path/to/phi-2 \
      python examples/phi2_node_serving.py
"""

import concurrent.futures as cf
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from fei_tpu.engine import GenerationConfig, InferenceEngine


def main() -> None:
    model = os.environ.get("FEI_TPU_PHI_MODEL", "tiny-phi")
    ckpt = os.environ.get("FEI_TPU_PHI_CHECKPOINT") or None
    eng = InferenceEngine.from_config(
        model, tokenizer=ckpt or "byte", checkpoint_dir=ckpt,
        max_seq_len=256, paged=True, batch_size=2, page_size=16,
    )
    if ckpt is None:
        print("(random weights — set FEI_TPU_PHI_CHECKPOINT for real ones)")
    cfg = eng.cfg
    print(
        f"{cfg.name}: {cfg.num_layers} layers, parallel_block="
        f"{cfg.parallel_block}, rotary {cfg.rotary_dim}/{cfg.head_dim_} dims"
    )
    gen = GenerationConfig(max_new_tokens=24, temperature=0.0, ignore_eos=True)
    prompts = [
        "def maildir_flags(name):",
        "Explain why Maildir renames are atomic:",
    ]

    def serve(text: str) -> list[int]:
        return list(eng.scheduler.stream(eng.tokenizer.encode(text), gen))

    try:
        with cf.ThreadPoolExecutor(2) as ex:
            outs = list(ex.map(serve, prompts))
        for text, toks in zip(prompts, outs):
            print(f"{text!r} -> {len(toks)} tokens: {toks[:8]}...")
        # the node scenario's check: serving is deterministic per request
        assert outs[0] == serve(prompts[0])
        print("deterministic under concurrency — the node scenario, real")
    finally:
        eng.close()


if __name__ == "__main__":
    main()
