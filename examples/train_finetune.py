"""Fine-tune loop with checkpoint/resume: the jitted train step (remat'd
forward, adamw) plus orbax composite checkpoints.

    python examples/train_finetune.py
"""

import jax
import jax.numpy as jnp

from fei_tpu.engine import restore_checkpoint, save_checkpoint
from fei_tpu.engine.train import TrainConfig, make_optimizer, make_train_step
from fei_tpu.models.configs import get_model_config
from fei_tpu.models.llama import init_params


def main() -> None:
    cfg = get_model_config("tiny", num_layers=2)
    tc = TrainConfig(learning_rate=3e-4, remat=True)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    opt = make_optimizer(tc)
    opt_state = opt.init(params)
    _, train_step = make_train_step(cfg, tc)

    data = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size)

    ckpt_dir = "/tmp/fei_tpu_finetune_ckpt"
    for step in range(6):
        params, opt_state, loss = train_step(params, opt_state, data)
        print(f"step {step}: loss={float(loss):.4f}")
        if step == 2:
            save_checkpoint(ckpt_dir, step, params, opt_state=opt_state)
            print("  checkpointed at step 2")

    restored = restore_checkpoint(
        ckpt_dir, target={"params": params, "opt_state": opt_state}
    )
    print("restored step-2 checkpoint;",
          "resume with train_step(restored['params'], restored['opt_state'], ...)")


if __name__ == "__main__":
    main()
