"""Repository map: ranked, token-budgeted symbol overview of a codebase
(reference examples/repo_map_example.py).

    python examples/repo_map_example.py [path]
"""

import sys

from fei_tpu.tools.repomap import (
    generate_repo_dependencies,
    generate_repo_map,
    generate_repo_summary,
)


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "."
    print("=== repo map (1024-token budget) ===")
    print(generate_repo_map(path, token_budget=1024))

    summary = generate_repo_summary(path)
    print("\n=== summary ===")
    for module, files in list(summary.items())[:5]:
        print(f"{module}: {len(files)} file(s)")

    deps = generate_repo_dependencies(path)
    print(f"\n=== dependencies: {len(deps)} file(s) with references ===")


if __name__ == "__main__":
    main()
