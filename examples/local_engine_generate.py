"""Drive the TPU inference engine directly: prefill + streaming decode
(the compute path behind the jax_local provider).

Uses the `tiny` random-weight config so it runs anywhere:

    python examples/local_engine_generate.py
"""

import jax.numpy as jnp

from fei_tpu.engine import GenerationConfig, InferenceEngine


def main() -> None:
    engine = InferenceEngine.from_config(
        "tiny", dtype=jnp.float32, tokenizer="byte", max_seq_len=256,
    )
    gen = GenerationConfig(max_new_tokens=48, temperature=0.8, seed=0,
                           ignore_eos=True)
    prompt = engine.tokenizer.encode("Once upon a time")

    print("streaming:", end=" ", flush=True)
    ids = []
    for tok in engine.generate_stream(prompt, gen):
        ids.append(tok)
        print(tok, end=" ", flush=True)
    print("\ntext:", repr(engine.tokenizer.decode(ids)))

    # fused chunked decode: one device dispatch per 64 tokens — what the
    # benchmark uses for throughput
    result = engine.generate_fused(prompt, gen)
    print(f"fused: {len(result.token_ids)} tokens, "
          f"ttft={result.ttft_s * 1e3:.1f} ms, "
          f"{result.decode_tokens_per_s:.1f} tok/s")


if __name__ == "__main__":
    main()
