"""SWA x sequence-parallel prefill (round 4): a long sliding-window prompt
ring-prefills over the sp mesh axis, token-identical to the dense-SWA
engine.

The agent task loop grows context without bound (reference behavior:
fei/core/task_executor.py:231-252) and Mistral-family configs bound
attention with a sliding window — before round 4 these two features didn't
compose (SWA prompts silently fell back to monolithic dense prefill). Now
the window mask runs inside the sharded ring/ulysses attends, and the ring
rotation stops after ceil((window-1)/chunk)+1 hops: at Mistral scale
(window 4096, 32k prompt, sp=8) each device attends 2 of 8 chunks instead
of masking 6 of them to zero.

Run hermetically on the 8-device virtual CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/swa_sp_long_prefill.py
"""

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from fei_tpu.engine import GenerationConfig, InferenceEngine
from fei_tpu.parallel.mesh import make_mesh
from fei_tpu.utils.metrics import METRICS


def main() -> None:
    n = min(8, len(jax.devices()))
    prompt = [(13 * i + 7) % 200 + 10 for i in range(1024)]
    gen = GenerationConfig(max_new_tokens=8, temperature=0.0, ignore_eos=True)

    dense = InferenceEngine.from_config("tiny-swa", max_seq_len=2048)
    want = dense.generate(prompt, gen).token_ids
    print(f"dense-SWA reference (window={dense.cfg.sliding_window}): {want}")

    mesh = make_mesh({"sp": n}, devices=jax.devices()[:n])
    sp = InferenceEngine.from_config(
        "tiny-swa", max_seq_len=2048, mesh=mesh, long_prefill_min=512
    )
    before = METRICS.snapshot()["counters"].get("engine.sp_prefills", 0)
    got = sp.generate(prompt, gen).token_ids
    delta = METRICS.snapshot()["counters"].get("engine.sp_prefills", 0) - before
    assert delta >= 1, "prompt did not route through sp prefill"
    assert got == want, (got, want)
    print(f"sp-SWA ({len(prompt)} tokens ring-prefilled over sp={n}): {got}")
    print("token-identical: the window mask runs inside the sharded attends")


if __name__ == "__main__":
    main()
