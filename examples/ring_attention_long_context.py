"""Sequence parallelism for long-context prefill: ring attention (KV blocks
rotating via ppermute) and Ulysses (head/sequence all_to_all), verified
against each other.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/ring_attention_long_context.py
"""

import jax
import jax.numpy as jnp
import numpy as np

from fei_tpu.parallel import make_mesh, ring_attention, ulysses_attention


def main() -> None:
    n = len(jax.devices())
    mesh = make_mesh({"sp": n})
    B, T, H, K, D = 1, 128 * n, 8, 8, 64  # sequence sharded n ways
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), dtype=jnp.float32) * 0.3
    k = jax.random.normal(ks[1], (B, T, K, D), dtype=jnp.float32) * 0.3
    v = jax.random.normal(ks[2], (B, T, K, D), dtype=jnp.float32) * 0.3

    ring = ring_attention(q, k, v, mesh)
    uly = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(uly), atol=2e-3)
    print(f"T={T} over sp={n}: ring and ulysses agree "
          f"(per-device chunk {T // n} tokens)")


if __name__ == "__main__":
    main()
