"""Tensor-parallel inference over a device mesh. On a pod the same code
shards over real chips; here it runs on a virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/sharded_inference.py
"""

import jax
import jax.numpy as jnp

from fei_tpu.engine import GenerationConfig, InferenceEngine
from fei_tpu.models.configs import get_model_config
from fei_tpu.parallel import best_mesh_shape, make_mesh


def main() -> None:
    n = len(jax.devices())
    cfg = get_model_config("tiny")
    # tp is capped at the model's kv-head count (the cache shards over it)
    shape = best_mesh_shape(n, num_kv_heads=cfg.num_kv_heads)
    mesh = make_mesh(shape)
    print(f"devices={n} mesh={dict(mesh.shape)}")

    engine = InferenceEngine.from_config(
        "tiny", dtype=jnp.float32, tokenizer="byte", max_seq_len=256,
        mesh=mesh,  # params get TP shardings; caches shard over dp/tp
    )
    wq = engine.params["layers"]["wq"]
    print("wq sharding:", wq.sharding)

    gen = GenerationConfig(max_new_tokens=24, temperature=0.0, ignore_eos=True)
    result = engine.generate(engine.tokenizer.encode("sharded"), gen)
    print(f"decoded {len(result.token_ids)} tokens on the mesh")


if __name__ == "__main__":
    main()
