"""Paged KV cache decode: HBM allocated page-by-page instead of max_seq_len
up front, with the Pallas ragged paged-attention kernel reading through the
block table.

    python examples/paged_decode.py
"""

import jax.numpy as jnp

from fei_tpu.engine import GenerationConfig, InferenceEngine


def main() -> None:
    engine = InferenceEngine.from_config(
        "tiny", dtype=jnp.float32, tokenizer="byte",
        max_seq_len=512,
        paged=True, page_size=32,
        num_pages=9,  # HBM budget: 9 x 32 = 288 tokens of KV, shared pool
    )
    gen = GenerationConfig(max_new_tokens=40, temperature=0.0, ignore_eos=True)
    prompt = engine.tokenizer.encode("The paged cache grows as needed. ")

    result = engine.generate(prompt, gen)
    alloc = engine._allocator
    print(f"decoded {len(result.token_ids)} tokens")
    print(f"pool: {alloc.num_pages} pages of {alloc.page_size} tokens; "
          f"{alloc.free_pages} free after release")


if __name__ == "__main__":
    main()
