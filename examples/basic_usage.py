"""Basic agent usage: an Assistant with the code-tool suite
(reference examples/basic_usage.py).

Runs with the mock provider so no weights or network are needed:

    python examples/basic_usage.py

Swap provider="jax_local" (and optionally model="llama3-8b",
checkpoint via FEI_TPU_CHECKPOINT_DIR) to decode on the local TPU.
"""

import asyncio

from fei_tpu.agent import Assistant
from fei_tpu.tools import ToolRegistry, create_code_tools


async def main() -> None:
    registry = ToolRegistry()
    create_code_tools(registry)  # glob/grep/view/edit/ls/shell/...

    assistant = Assistant(provider="mock", tool_registry=registry)
    reply = await assistant.chat("What tools do you have available?")
    print("assistant:", reply)

    # the conversation is stateful; follow-ups share context
    reply = await assistant.chat("Thanks!")
    print("assistant:", reply)


if __name__ == "__main__":
    asyncio.run(main())
