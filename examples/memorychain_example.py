"""Memorychain: PoW ledger + FeiCoin wallet, driven in-process
(reference examples/fei_memorychain_example.py).

    python examples/memorychain_example.py
"""

import tempfile

from fei_tpu.memory.memorychain.chain import MemoryChain


def main() -> None:
    with tempfile.TemporaryDirectory() as home:
        chain = MemoryChain(node_id="demo-node", base_dir=home)
        print("genesis hash:", chain.head.hash[:16], "…")

        block = chain.add_block(
            {"headers": {"Subject": "first memory"},
             "content": "proof-of-work mined"},
        )
        print(f"mined block #{block.index} nonce={block.nonce} "
              f"hash={block.hash[:16]}…")

        # no peers configured: propose commits locally
        block = chain.propose_memory(
            {"headers": {"Subject": "proposed memory"}, "content": "quorum of 1"}
        )
        print(f"proposed -> block #{block.index}")

        print("chain valid:", chain.validate_chain())
        print("wallet balance:", chain.wallet.balance("demo-node"))


if __name__ == "__main__":
    main()
