"""Memdir over HTTP: start the REST server, drive it with the connector
(reference examples/memdir_http_client.py). The connector auto-starts the
server as a child process and stops it at exit.

    python examples/memdir_http_client.py
"""

import os
import tempfile

from fei_tpu.tools.memdir_connector import MemdirConnector


def main() -> None:
    base = tempfile.mkdtemp(prefix="memdir_demo_")
    os.environ["MEMDIR_BASE"] = base

    conn = MemdirConnector(
        server_url="http://127.0.0.1:5987", auto_start=True, base_dir=base
    )
    if not conn.check_connection() and not conn.start_server():
        print("server did not start; try: python -m fei_tpu.memory.memdir.server")
        return
    print("server healthy:", conn.server_status())

    created = conn.create_memory(
        "HTTP round-trip memory", tags="demo,http",
        headers={"Subject": "created over REST"},
    )
    print("created:", created.get("id"))

    hits = conn.search("#demo")
    print("search #demo:", hits.get("count", len(hits.get("results", []))))

    conn.stop_server()
    print("server stopped")


if __name__ == "__main__":
    main()
