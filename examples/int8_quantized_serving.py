"""Weight-only int8 serving: halve weight HBM, double the decode ceiling.

Three entry points, smallest to largest:
  1. random-init int8 engine (benches; quantize-at-init, no bf16 peak)
  2. int8 + continuous batching (paged scheduler)
  3. checkpoint streamed straight into sharded HBM, quantizing on the read
     (the 70B-on-a-pod path — here demonstrated on the CPU test mesh)

Run hermetically on CPU:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/int8_quantized_serving.py
"""

import os
import threading

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the container sitecustomize pins the TPU platform; honor the env pin
    # explicitly and WITHOUT touching the backend (no default_backend() —
    # that would initialize it)
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp

from fei_tpu.engine import GenerationConfig, InferenceEngine
from fei_tpu.ops.quant import QTensor, param_bytes


def single_stream():
    engine = InferenceEngine.from_config(
        "tiny", tokenizer="byte", quantize="int8", max_seq_len=128,
    )
    assert isinstance(engine.params["layers"]["wq"], QTensor)
    print(f"int8 engine: {param_bytes(engine.params)/1e6:.2f} MB of params")
    ids = engine.tokenizer.encode("fei", add_bos=True)
    res = engine.generate(ids, GenerationConfig(max_new_tokens=12, temperature=0.0))
    print("decoded:", res.token_ids)


def continuous_batching():
    engine = InferenceEngine.from_config(
        "tiny", tokenizer="byte", quantize="int8",
        max_seq_len=128, paged=True, batch_size=3, page_size=16,
    )
    gen = GenerationConfig(max_new_tokens=8, temperature=0.0, ignore_eos=True)
    prompt = engine.tokenizer.encode("hello", add_bos=True)

    def consume(i):
        toks = list(engine.scheduler.stream(prompt, gen))
        print(f"stream {i}: {len(toks)} tokens")

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def streamed_sharded_checkpoint():
    import json
    import tempfile

    import numpy as np
    from safetensors.numpy import save_file

    from fei_tpu.models.configs import get_model_config
    from fei_tpu.parallel.mesh import make_mesh

    cfg = get_model_config("tiny")
    h, d = cfg.hidden_size, cfg.head_dim_
    H, K, I, L, V = (cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size,
                     cfg.num_layers, cfg.vocab_size)
    rng = np.random.default_rng(0)
    r = lambda s: (rng.standard_normal(s) * 0.05).astype(np.float32)  # noqa: E731
    t = {"model.embed_tokens.weight": r((V, h)),
         "model.norm.weight": np.ones(h, np.float32),
         "lm_head.weight": r((V, h))}
    for i in range(L):
        p = f"model.layers.{i}."
        t[p + "input_layernorm.weight"] = np.ones(h, np.float32)
        t[p + "post_attention_layernorm.weight"] = np.ones(h, np.float32)
        t[p + "self_attn.q_proj.weight"] = r((H * d, h))
        t[p + "self_attn.k_proj.weight"] = r((K * d, h))
        t[p + "self_attn.v_proj.weight"] = r((K * d, h))
        t[p + "self_attn.o_proj.weight"] = r((h, H * d))
        t[p + "mlp.gate_proj.weight"] = r((I, h))
        t[p + "mlp.up_proj.weight"] = r((I, h))
        t[p + "mlp.down_proj.weight"] = r((h, I))
    with tempfile.TemporaryDirectory() as ckpt:
        save_file(t, f"{ckpt}/model.safetensors")
        with open(f"{ckpt}/config.json", "w") as fh:
            json.dump({"vocab_size": V}, fh)
        n = len(jax.devices())
        mesh = make_mesh({"tp": 2, "dp": n // 2}) if n >= 2 else None
        engine = InferenceEngine.from_config(
            "tiny", tokenizer="byte", checkpoint_dir=ckpt,
            mesh=mesh, quantize="int8", max_seq_len=64, dtype=jnp.float32,
        )
        print("streamed+sharded int8 load ok;",
              "wq sharding:", engine.params["layers"]["wq"].q.sharding)
        ids = engine.tokenizer.encode("2+2?", add_bos=True)
        res = engine.generate(ids, GenerationConfig(max_new_tokens=6))
        print("decoded:", res.token_ids)


if __name__ == "__main__":
    single_stream()
    continuous_batching()
    streamed_sharded_checkpoint()
