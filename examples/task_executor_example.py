"""Continuous task execution: iterate the agent until it emits
[TASK_COMPLETE] (reference fei --task mode, task_executor.py).

    python examples/task_executor_example.py
"""

import asyncio

from fei_tpu.agent import Assistant, TaskExecutor
from fei_tpu.tools import ToolRegistry, create_code_tools


async def main() -> None:
    registry = ToolRegistry()
    create_code_tools(registry)
    assistant = Assistant(provider="mock", tool_registry=registry)

    executor = TaskExecutor(assistant, max_iterations=3)
    ctx = await executor.execute_task("List the python files in this repo")
    print(f"completed={ctx.completed} iterations={ctx.iterations} "
          f"duration={ctx.duration_s:.1f}s")
    print("final response:", ctx.final_response[:200])


if __name__ == "__main__":
    asyncio.run(main())
