"""Pipeline-parallel training forward: layers staged over a pp mesh axis
with GPipe microbatching, verified against the single-device forward.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_parallel_train.py
"""

import jax
import jax.numpy as jnp
import numpy as np

from fei_tpu.models.configs import get_model_config
from fei_tpu.models.llama import forward_train, init_params
from fei_tpu.parallel import make_mesh, pipeline_forward_train


def main() -> None:
    n = min(4, len(jax.devices()))
    mesh = make_mesh({"pp": n}, devices=jax.devices()[:n])
    cfg = get_model_config("tiny", num_layers=2 * n)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)

    staged = pipeline_forward_train(params, cfg, tokens, mesh, num_micro=2)
    dense = forward_train(params, cfg, tokens, remat=False)
    np.testing.assert_allclose(np.asarray(staged), np.asarray(dense), atol=1e-3)
    print(f"pp={n}, {cfg.num_layers} layers, 2 microbatches: "
          "pipeline output matches the dense forward")


if __name__ == "__main__":
    main()
