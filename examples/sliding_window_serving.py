"""Sliding-window (Mistral-style) serving: bounded attention, bounded KV.

cfg.sliding_window masks attention to the last W positions in every path
(XLA oracle, flash fwd/bwd, paged kernels). Serving adds two memory wins
on top: the paged kernels never DMA pages wholly below the window (index
maps clamp past them), and the scheduler RELEASES those pages back to the
pool mid-stream (rolling buffer) — a long SWA conversation holds
~window+margin tokens of KV, not its whole history.

Run hermetically on CPU:
  JAX_PLATFORMS=cpu python examples/sliding_window_serving.py
"""

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from fei_tpu.engine import GenerationConfig, InferenceEngine
from fei_tpu.utils.metrics import METRICS


def dense_window():
    eng = InferenceEngine.from_config("tiny-swa", tokenizer="byte", max_seq_len=64)
    print(f"window: last {eng.cfg.sliding_window} positions only")
    gen = GenerationConfig(max_new_tokens=12, temperature=0.0, ignore_eos=True)
    res = eng.generate(eng.tokenizer.encode("sliding window"), gen)
    print("dense decode:", res.token_ids)
    return res.token_ids


def rolling_buffer(want):
    eng = InferenceEngine.from_config(
        "tiny-swa", tokenizer="byte", max_seq_len=160, paged=True,
        batch_size=1, page_size=8,
    )
    gen = GenerationConfig(max_new_tokens=12, temperature=0.0, ignore_eos=True)
    got = list(eng.scheduler.stream(eng.tokenizer.encode("sliding window"), gen))
    assert got == want, "paged SWA must match dense token-for-token"
    print("paged matches dense:", got == want)

    # a longer stream crosses the release threshold: pages go back
    long_gen = GenerationConfig(
        max_new_tokens=100, temperature=0.0, ignore_eos=True
    )
    list(eng.scheduler.stream(eng.tokenizer.encode("long probe"), long_gen))
    released = METRICS.snapshot()["counters"].get(
        "scheduler.swa_pages_released", 0
    )
    print(f"rolling buffer: {released:.0f} below-window pages released "
          "back to the pool mid-stream")
    eng.close()


if __name__ == "__main__":
    want = dense_window()
    rolling_buffer(want)
