"""Weight-only int4 serving: halve the weight stream AGAIN after int8.

Decode is weight-streaming-bound, so the int8→int4 halving raises the
single-chip ceiling ~1.6x (scales + the int8-kept leaves take the rest).
The matmul is a fused Pallas kernel (ops/pallas/int4_matmul.py) that
streams the nibble-packed bytes once; on CPU it runs in interpret mode, on
tp meshes it runs under shard_map per N-shard.

Three entry points, smallest to largest:
  1. random-init int4 engine (quantize-at-init, per-layer fp32 transient)
  2. int4 + continuous batching (paged scheduler)
  3. int4 on a tp mesh: column-parallel linears keep the packed kernel,
     row-parallel wo/w_down stay int8 (nibble pairs span the contraction
     axis tp shards)

Run hermetically on CPU:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/int4_quantized_serving.py
"""

import os
import threading

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp

from fei_tpu.engine import GenerationConfig, InferenceEngine
from fei_tpu.ops.quant import QTensor, QTensor4, param_bytes

# h=512 keeps the example fast; the linears are int4-eligible (h % 256 == 0)
SHAPE = dict(
    num_layers=2, hidden_size=512, intermediate_size=1024,
    num_heads=8, num_kv_heads=4, max_seq_len=128, tokenizer="byte",
)


def single_stream():
    engine = InferenceEngine.from_config("tiny", quantize="int4", **SHAPE)
    assert isinstance(engine.params["layers"]["wq"], QTensor4)
    assert isinstance(engine.params["lm_head"], QTensor)  # int8 by default
    print(f"int4 engine: {param_bytes(engine.params)/1e6:.2f} MB of params")
    ids = engine.tokenizer.encode("fei", add_bos=True)
    res = engine.generate(ids, GenerationConfig(max_new_tokens=12, temperature=0.0))
    print("decoded:", res.token_ids)


def continuous_batching():
    engine = InferenceEngine.from_config(
        "tiny", quantize="int4", paged=True, batch_size=2, page_size=16,
        **SHAPE,
    )
    gen = GenerationConfig(max_new_tokens=8, temperature=0.0, ignore_eos=True)
    outs = {}

    def serve(i):
        ids = engine.tokenizer.encode(f"request {i}")
        outs[i] = list(engine.scheduler.stream(ids, gen))

    threads = [threading.Thread(target=serve, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    engine.close()
    print("paged int4 streams:", {i: len(v) for i, v in outs.items()})


def tp_mesh():
    if len(jax.devices()) < 2:
        print("tp example skipped (needs >= 2 devices)")
        return
    from fei_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    engine = InferenceEngine.from_config(
        "tiny", quantize="int4", mesh=mesh, **SHAPE
    )
    assert isinstance(engine.params["layers"]["wq"], QTensor4)  # column: int4
    assert isinstance(engine.params["layers"]["wo"], QTensor)  # row: int8
    ids = engine.tokenizer.encode("sharded int4")
    res = engine.generate(ids, GenerationConfig(max_new_tokens=8, temperature=0.0))
    print("tp=2 int4 decoded:", res.token_ids)


if __name__ == "__main__":
    single_stream()
    continuous_batching()
    tp_mesh()
