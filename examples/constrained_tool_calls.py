"""Grammar-enforced tool calls: the decoder cannot emit an invalid call.

The reference validates tool-call JSON after the fact
(fei/tools/registry.py:92-153) and silently drops what fails to parse.
Here the union grammar over every registered tool's input schema drives
generation the moment the model emits the <tool_call> trigger — on the
dense path the DFA steps inside the fused on-device scan; on the paged
path it rides the batched scheduler step with per-slot states.

Runs hermetically on CPU with random tiny weights — which is exactly the
point: even a model emitting pure noise produces a schema-valid call.

    JAX_PLATFORMS=cpu python examples/constrained_tool_calls.py
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import json

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.grammar import char_walk, compile_agent_tool_grammar
from fei_tpu.utils.metrics import METRICS

TOOLS = [
    {
        "name": "GrepTool",
        "description": "search file contents",
        "input_schema": {
            "type": "object",
            "properties": {
                "pattern": {"type": "string"},
                "path": {"type": "string"},
            },
            "required": ["pattern"],
        },
    },
    {
        "name": "Shell",
        "description": "run a command",
        "input_schema": {
            "type": "object",
            "properties": {"command": {"type": "string"}},
            "required": ["command"],
        },
    },
]


def main() -> None:
    engine = InferenceEngine.from_config("tiny")
    grammar = compile_agent_tool_grammar(TOOLS, engine.tokenizer)
    print(
        f"union grammar over {len(TOOLS)} tools: "
        f"{grammar.table.shape[0]} DFA states, "
        f"{grammar.table_bytes / 1e6:.2f} MB token tables, "
        f"lifted in {grammar.lift_seconds:.2f}s"
    )

    # use the model's own first token as the trigger so the constrained
    # phase engages deterministically under random weights (a real
    # checkpoint emits the taught <tool_call> tag instead)
    gen = GenerationConfig(max_new_tokens=96, ignore_eos=True)
    prompt = list(range(11, 23))
    first = next(iter(engine.generate_stream(prompt, gen)))
    trigger = engine.tokenizer.decode([first])

    toks = list(
        engine.generate_stream_toolcalls(
            prompt, gen, grammar=grammar, trigger=trigger
        )
    )
    text = engine.tokenizer.decode(toks)
    payload = text[len(trigger):-len("</tool_call>")]
    call = json.loads(payload)  # grammar guarantee: always parses
    assert char_walk(grammar, payload) == grammar.accept

    fused = METRICS.snapshot()["counters"].get("engine.grammar_fused_steps", 0)
    print(f"model emitted (random weights!): {payload}")
    print(f"tool: {call['name']}  arguments: {call['arguments']}")
    print(f"fused on-device DFA steps: {fused:.0f} — zero per-token host syncs")


if __name__ == "__main__":
    main()
