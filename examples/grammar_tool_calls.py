"""Grammar-constrained decoding: the decoder *cannot emit* an invalid tool
call — a JSON schema is compiled to a DFA whose token masks gate sampling.

    python examples/grammar_tool_calls.py
"""

import json

import jax.numpy as jnp

from fei_tpu.engine import (
    GenerationConfig,
    InferenceEngine,
    compile_tool_call_grammar,
)


def main() -> None:
    engine = InferenceEngine.from_config(
        "tiny", dtype=jnp.float32, tokenizer="byte", max_seq_len=256,
    )
    schema = {
        "type": "object",
        "properties": {
            "pattern": {"type": "string"},
            "recursive": {"type": "boolean"},
            "max_results": {"type": "integer"},
        },
    }
    grammar = compile_tool_call_grammar(schema, engine.tokenizer)

    gen = GenerationConfig(max_new_tokens=80, temperature=1.0, seed=42)
    result = engine.generate(
        engine.tokenizer.encode("Call the glob tool:"),
        gen,
        logit_mask_fn=grammar.logit_mask_fn(max_tokens=80),
    )
    print("raw output:", result.text)
    args = json.loads(result.text)  # always parses — that's the guarantee
    print("parsed:", args)


if __name__ == "__main__":
    main()
