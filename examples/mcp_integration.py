"""MCP client: configure servers via env/config, list services, call one,
and expose them as agent tools (reference examples/mcp_brave_search.py).

No real MCP server is required for this demo — it shows configuration and
the registry passthrough wiring, then calls only if a server is reachable.

    FEI_TPU_MCP_SERVER_ECHO='{"type": "http", "url": "http://localhost:9um"}' \
        python examples/mcp_integration.py
"""

from fei_tpu.agent.mcp import MCPManager, register_mcp_tools
from fei_tpu.tools import ToolRegistry


def main() -> None:
    manager = MCPManager()
    services = manager.list_services()
    print("configured services:", services or "(none)")

    registry = ToolRegistry()
    register_mcp_tools(registry, manager)
    mcp_tools = [n for n in registry.list_tools() if n.startswith(("mcp_", "brave"))]
    print("registered tools:", mcp_tools)

    for svc in services:
        try:
            info = manager.client.call_service(svc, "ping", {})
            print(f"{svc}.ping ->", info)
        except Exception as exc:  # noqa: BLE001 — demo: servers may be down
            print(f"{svc} unreachable: {exc}")

    manager.close()


if __name__ == "__main__":
    main()
