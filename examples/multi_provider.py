"""Switching providers: the agent loop is backend-agnostic — the in-tree
jax_local TPU decoder, the mock echo provider, and remote HTTP providers all
implement the same Provider contract (reference examples/multi_provider.py).

    python examples/multi_provider.py
"""

import asyncio

from fei_tpu.agent import Assistant
from fei_tpu.agent.providers import MockProvider, ProviderManager


async def main() -> None:
    # 1. by name (resolved through ProviderManager + config/env)
    assistant = Assistant(provider="mock")
    print("mock:", await assistant.chat("hello"))

    # 2. by instance — anything implementing Provider.complete/stream
    class ShoutProvider(MockProvider):
        name = "shout"

        def complete(self, messages, system=None, tools=None, max_tokens=4000):
            resp = super().complete(messages, system, tools, max_tokens)
            resp.content = (resp.content or "").upper()
            return resp

    assistant = Assistant(provider=ShoutProvider())
    print("shout:", await assistant.chat("hello"))

    # 3. jax_local: the TPU decoder (random tiny weights without a
    #    checkpoint; set FEI_TPU_MODEL/checkpoint config for real ones)
    mgr = ProviderManager("jax_local", "tiny")
    print("jax_local provider ready:", mgr.get_provider().name)


if __name__ == "__main__":
    asyncio.run(main())
