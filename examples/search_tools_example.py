"""The code-search tool suite: glob, grep, batch glob, find-in-files, and
smart search, called through the tool registry exactly as the agent calls
them (reference examples/ask_with_search.py + SEARCH_TOOLS.md).

    python examples/search_tools_example.py
"""

import json

from fei_tpu.tools import ToolRegistry, create_code_tools


def call(registry: ToolRegistry, name: str, **args) -> dict:
    out = registry.execute_tool(name, args)
    print(f"--- {name}({json.dumps(args)})")
    text = json.dumps(out, indent=2, default=str)
    print(text[:400] + ("…" if len(text) > 400 else ""))
    return out


def main() -> None:
    registry = ToolRegistry()
    create_code_tools(registry)

    call(registry, "GlobTool", pattern="fei_tpu/ops/*.py")
    call(registry, "GrepTool", pattern="flash_attention", include="*.py",
         path="fei_tpu/ops")
    call(registry, "BatchGlob", patterns=["*.md", "tests/test_p*.py"])
    call(registry, "FindInFiles", pattern="ppermute", files=["fei_tpu/parallel/ring.py"])
    call(registry, "SmartSearch", query="def paged_attention python")


if __name__ == "__main__":
    main()
