"""Benchmark harness: single-stream decode throughput + TTFT on the local
TPU chip, per BASELINE.json ("tokens/sec/chip + p50 TTFT for fei --message").

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is value / 20.0 — the BASELINE.json north-star floor of
20 tok/s/chip (the reference publishes no numbers of its own; BASELINE.md).
Progress/debug goes to stderr. Model/dtype/token counts are env-tunable:
  FEI_TPU_BENCH_MODEL   (default llama3-1b)
  FEI_TPU_BENCH_TOKENS  (default 256)
  FEI_TPU_BENCH_PROMPT  (default ~128 tokens)
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _build_and_warm(model, n_tokens):
    import jax.numpy as jnp

    from fei_tpu.engine import GenerationConfig, InferenceEngine

    t0 = time.time()
    engine = InferenceEngine.from_config(
        model, dtype=jnp.bfloat16, max_seq_len=2048, tokenizer="byte"
    )
    log(f"bench: params initialized in {time.time()-t0:.1f}s "
        f"(~{engine.cfg.num_params()/1e9:.2f}B params)")

    prompt_text = os.environ.get(
        "FEI_TPU_BENCH_PROMPT",
        "Write a Python function that parses a Maildir-style filename into "
        "its timestamp, unique id, hostname and flag components, returning "
        "a dict; include error handling for malformed names. " * 2,
    )
    prompt = engine.tokenizer.encode(prompt_text, add_bos=True)[:128]
    # ignore_eos: random-weight decode must run the full budget for timing
    gen = GenerationConfig(max_new_tokens=n_tokens, temperature=0.0, ignore_eos=True)

    # warm-up: compiles prefill bucket + fused decode chunk
    t0 = time.time()
    warm = engine.generate_fused(prompt, gen, chunk=64)
    log(f"bench: warm-up (compile) {time.time()-t0:.1f}s, "
        f"{len(warm.token_ids)} tokens")
    return engine, prompt, gen


def _touch_backend_or_reexec():
    """First device touch, with retry via re-exec.

    A transiently unavailable axon/TPU backend raises at init and the failure
    is cached for the process lifetime, so an in-process retry is useless —
    re-exec ourselves with backoff instead (round-1 BENCH died here, rc=1).
    """
    import jax

    attempt = int(os.environ.get("FEI_TPU_BENCH_ATTEMPT", "0"))
    try:
        backend = jax.default_backend()
        devices = jax.devices()
    except Exception as exc:  # noqa: BLE001
        if attempt >= 4:
            log(f"bench: backend unavailable after {attempt + 1} attempts: {exc!r}")
            raise
        delay = 30 * (2 ** attempt)
        log(f"bench: backend init failed ({exc!r}); retry {attempt + 1}/4 "
            f"in {delay}s")
        time.sleep(delay)
        os.environ["FEI_TPU_BENCH_ATTEMPT"] = str(attempt + 1)
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, [sys.executable] + sys.argv)
    return backend, devices


def main() -> int:
    model = os.environ.get("FEI_TPU_BENCH_MODEL", "llama3-1b")
    n_tokens = int(os.environ.get("FEI_TPU_BENCH_TOKENS", "256"))
    backend, devices = _touch_backend_or_reexec()
    log(f"bench: model={model} backend={backend} devices={devices}")

    try:
        engine, prompt, gen = _build_and_warm(model, n_tokens)
    except Exception as exc:  # noqa: BLE001
        # the flash/pallas path must never sink the bench: fall back to the
        # XLA oracle attention and try once more
        log(f"bench: warm-up failed ({exc!r}); retrying with FEI_TPU_FLASH=0")
        os.environ["FEI_TPU_FLASH"] = "0"
        engine, prompt, gen = _build_and_warm(model, n_tokens)

    # timed runs
    ttfts, tps = [], []
    for i in range(3):
        res = engine.generate_fused(prompt, gen, chunk=64)
        ttfts.append(res.ttft_s)
        tps.append(res.decode_tokens_per_s)
        log(f"bench: run {i}: ttft={res.ttft_s*1000:.1f}ms "
            f"decode={res.decode_tokens_per_s:.1f} tok/s "
            f"({len(res.token_ids)} tokens)")

    ttft_p50 = sorted(ttfts)[len(ttfts) // 2]
    tok_s = sorted(tps)[len(tps) // 2]
    result = {
        "metric": f"{model}_decode_tok_s_per_chip",
        "value": round(tok_s, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s / 20.0, 3),
    }
    log(f"bench: p50 ttft={ttft_p50*1000:.1f}ms")
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
