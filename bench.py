"""Benchmark harness: decode throughput + TTFT on the local TPU chip, per
BASELINE.json ("tokens/sec/chip + p50 TTFT for fei --message").

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
plus suite-dependent extras: "ttft_ms"; for decode, the roofline fields
"gb_per_tok" / "achieved_gbps" / "pct_v5e_hbm" / "roofline_tok_s". When the
TPU is unavailable and a persisted gate measurement exists, a decode-suite
line reports THAT record as the headline with "stale": true and
"source": "onchip_state <ts>", demoting the CPU run to "cpu_liveness";
other suites (and a state-less checkout) keep an explicit
*_CPU_FALLBACK_TPU_UNAVAILABLE metric instead.

vs_baseline is value / 20.0 — the BASELINE.json north-star floor of
20 tok/s/chip (the reference publishes no numbers of its own; BASELINE.md).
Progress/debug goes to stderr.

Suites (FEI_TPU_BENCH_SUITE):
  decode (default) — single-stream fused decode (BASELINE config #2 shape)
  paged            — N concurrent scheduler streams over one paged pool,
                     aggregate decode tok/s (BASELINE config #3: the agent
                     task-loop serving shape)
  moe              — routed-MoE decode on the bench-scale Mixtral-shaped
                     config (BASELINE config #4 on one chip)
  prefill          — TTFT for an FEI_TPU_BENCH_PREFILL_LEN-token prompt
                     (default 4096) through the paged scheduler's chunked
                     admission (the serving path); emits prefill tok/s
  agent            — end-to-end `fei --message` through the whole stack
  remote           — BASELINE config #1: client-path floor via
                     RemoteProvider against a loopback OpenAI-compatible
                     stub (no device involved)
  federation       — BASELINE config #5 shape: 4-node shared-embedding
                     all-gather bandwidth + propose->consensus p50 on the
                     hermetic 4-device CPU mesh
  sharded          — the mesh-mode ladder: the paged workload at ms1, tp2,
                     tp2dp2 (FEI_TPU_BENCH_MESH_LADDER) with per-rung
                     aggregate tok/s, slot counts (dp multiplies them) and
                     a greedy token-parity probe vs the ms1 rung; on a CPU
                     backend it re-execs onto the 8-device host mesh
  kvtier           — tiered KV store under 10x slot oversubscription
                     (FEI_TPU_BENCH_OVERSUB): park/resume latency
                     percentiles, goodput with spill/streamed-resume on,
                     the recomputed-tokens-flat-while-pages-restored-climbs
                     acceptance numbers, and the affinity-miss TTFT cost
                     before vs after a cross-replica KV migration. Every
                     line stamps kv.tier_bytes_{ram,disk}
  fleet            — bursty multi-tenant overload through the fleet router
                     (2 in-process replicas): per-tenant p99 TTFT, goodput
                     and shed counts at ~2x capacity, with a zero-downtime
                     rolling restart mid-burst. The QoS claims live in the
                     extras: gold (priority 2) p99 vs its unloaded
                     baseline, and the share of sheds absorbed by bronze
                     (priority 0)
  crash            — mid-burst replica death at ~2x overload: a replica
                     is severed while streams are in flight and the
                     router resurrects every affected session on the
                     survivor. Headline is resurrection MTTR (client-
                     visible stream gap); extras carry tokens replayed,
                     dropped accepted streams (the zero-loss claim wants
                     0) and the journal-sync decode A/B
                     (disabled/batch/always tok/s)
  reshard          — mesh-elastic recovery cost: catch-up latency for a
                     torn journaled session recovered across a mesh
                     shrink (tp2 -> single chip) vs on the same mesh vs
                     cold re-prefill with no journal; extras carry
                     replayed/restored token counts and per-leg
                     byte-identity flags

Knobs:
  FEI_TPU_BENCH_MODEL    (decode default llama3-8b — the BASELINE config #2
                          gate scale; paged/agent default llama3-1b; moe
                          uses moe-2b)
  FEI_TPU_BENCH_TOKENS   (default 256)
  FEI_TPU_BENCH_PROMPT   (default ~128 tokens)
  FEI_TPU_BENCH_QUANT    ("int8" -> weight-only int8. Defaults to int8 for
                          the llama3-8b decode suite so 8B + KV fits the
                          16 GB chip; set empty to opt out)
  FEI_TPU_BENCH_STREAMS  (paged suite concurrency, default 4)
  FEI_TPU_BENCH_CHUNK    (decode-suite fused-scan chunk, default 64: tokens
                          decoded per device dispatch. Each chunk boundary
                          is a host sync; over the tunneled backend that is
                          a WAN round-trip, so the ladder 64/128/256 is the
                          roofline gap attribution. Non-default chunks get
                          a -c<N> metric suffix so an A/B run can never
                          displace the gate headline)
  FEI_TPU_BENCH_MAX_WAIT_S (total backend-retry wall-clock budget, 900)
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _make_engine(model: str, **kwargs):
    import jax.numpy as jnp

    from fei_tpu.engine import InferenceEngine

    quant = os.environ.get("FEI_TPU_BENCH_QUANT") or None
    if kwargs.get("paged"):
        # int8 KV only exists for paged pools; other suites ignore the knob
        kwargs.setdefault(
            "kv_quant", os.environ.get("FEI_TPU_BENCH_KV_QUANT") or None
        )
    t0 = time.time()
    engine = InferenceEngine.from_config(
        model, dtype=jnp.bfloat16, tokenizer="byte", quantize=quant, **kwargs
    )
    from fei_tpu.ops.quant import param_bytes

    log(f"bench: params initialized in {time.time()-t0:.1f}s "
        f"(~{engine.cfg.num_params()/1e9:.2f}B params, "
        f"{param_bytes(engine.params)/1e9:.2f} GB on device"
        f"{', ' + quant if quant else ''})")
    return engine


def _tag(model: str) -> str:
    """Metric-name prefix: model plus the quant mode when one is active —
    ONE spelling so variant runs can never collide in onchip_state.json."""
    quant = os.environ.get("FEI_TPU_BENCH_QUANT")
    return f"{model}-{quant}" if quant else model


def _prompt(engine):
    text = os.environ.get(
        "FEI_TPU_BENCH_PROMPT",
        "Write a Python function that parses a Maildir-style filename into "
        "its timestamp, unique id, hostname and flag components, returning "
        "a dict; include error handling for malformed names. " * 2,
    )
    return engine.tokenizer.encode(text, add_bos=True)[:128]


STATE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "onchip_state.json"
)
# BASELINE config #2's exact metric — the ONLY one that owns the headline
# slot (an int4/moe decode stage must not displace the gate number)
GATE_METRIC = "llama3-8b-int8_decode_tok_s_per_chip"


def _load_state() -> dict:
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001 — missing/corrupt state must not sink
        return {}


def _record_onchip(line: dict) -> None:
    """Persist a REAL on-chip measurement so later outages can still report
    it (VERDICT r3 #1: the chip comes and goes; the driver snapshot must not
    depend on the backend being up at that instant). Only called for
    measurements taken on an actual TPU backend. ``line`` already carries
    the suite's extras (_emit merges them before recording)."""
    entry = dict(line)
    entry["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        import jax

        entry["device"] = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001
        pass
    state = _load_state()
    state.setdefault("suites", {})[line["metric"]] = entry
    # the headline slot holds ONLY the BASELINE config #2 gate metric — a
    # first-recorded int4/paged/A-B stage must never occupy it, or an outage
    # would carry a non-gate number as the headline (round-4 advisory)
    if line["metric"] == GATE_METRIC:
        state["last_onchip"] = entry
        # best-AND-latest: the same config measured 71.8 then 30.7 tok/s in
        # consecutive lease windows (backend variance, not a regression) —
        # keep the best gate measurement alongside the latest so an outage
        # report can show both
        best = state.get("best_onchip")
        if not best or float(entry.get("value", 0.0)) >= float(
            best.get("value", 0.0)
        ):
            state["best_onchip"] = entry
    tmp = STATE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1, sort_keys=True)
    os.replace(tmp, STATE_PATH)  # atomic: a mid-write kill can't truncate


def _gate_record(state: dict) -> dict | None:
    """The persisted BASELINE config #2 gate measurement, if one exists.
    Reads the dedicated suites slot first; a legacy state file whose
    last_onchip happens to BE the gate metric also counts."""
    gate = state.get("suites", {}).get(GATE_METRIC)
    if gate is None:
        last = state.get("last_onchip")
        if last and last.get("metric") == GATE_METRIC:
            gate = last
    return gate


def _emit(metric: str, value: float, unit: str = "tok/s/chip",
          extra: dict | None = None) -> int:
    line = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / 20.0, 3),
    }
    # every record carries the serving mesh it ran under — suites run in
    # different FEI_TPU_MESH modes must never collide silently
    try:
        from fei_tpu.parallel.mesh import env_mesh_tag

        line["mesh"] = env_mesh_tag()
    except Exception:  # noqa: BLE001 — the headline number must survive
        pass
    if extra:
        line.update(extra)
    if os.environ.get("FEI_TPU_BENCH_CPU_FALLBACK"):
        # TPU-roofline extras are meaningless for a CPU liveness run —
        # never print a pct_v5e_hbm for a run that touched no TPU
        for k in ("gb_per_tok", "achieved_gbps", "pct_v5e_hbm",
                  "roofline_tok_s"):
            line.pop(k, None)
        # a DECODE-suite fallback reports the last REAL gate measurement as
        # the headline (clearly marked stale), never the meaningless
        # tiny-CPU number — a driver reading parsed.value gets a TPU number
        # in both the live and the outage case (round-4 verdict #4). The
        # CPU run is demoted to liveness metadata: it proves the stack
        # still executes. Other suites keep their own (labeled) metric so
        # a mid-pipeline outage cannot masquerade a decode number as a
        # prefill/paged/agent result.
        state = _load_state()
        gate = _gate_record(state)
        if gate and metric.endswith("_decode_tok_s_per_chip"):
            line = dict(gate)
            line["source"] = f"onchip_state {gate.get('ts', 'unknown')}"
            line["stale"] = True
            line["cpu_liveness"] = {
                "metric": f"{metric}_CPU_FALLBACK",
                "value": round(value, 2),
                "unit": unit,
            }
            # headline = LATEST gate measurement; attach the BEST one so
            # window-to-window backend variance (71.8 -> 30.7 same-config)
            # reads as variance, not as a framework regression
            best = state.get("best_onchip")
            if best:
                line["best_onchip"] = {
                    "value": best.get("value"), "ts": best.get("ts"),
                }
        else:
            # non-decode suite, or no gate record anywhere: label the CPU
            # number honestly; still carry the gate record as metadata so
            # the artifact keeps the on-chip evidence through the outage
            line["metric"] = f"{metric}_CPU_FALLBACK_TPU_UNAVAILABLE"
            if gate:
                line["last_onchip"] = gate
    elif os.environ.get("FEI_TPU_BENCH_ONCHIP"):
        _record_onchip(line)
    # every line carries the roofline fraction and per-chip throughput —
    # suites that computed their own roofline keep it; the rest fall back
    # to the live gauge the engine's dispatch accounting maintains
    try:
        from fei_tpu.obs.costmodel import chips_for_tag
        from fei_tpu.utils.metrics import METRICS

        if "roofline_frac" not in line:
            if "pct_v5e_hbm" in line:
                line["roofline_frac"] = round(line["pct_v5e_hbm"] / 100.0, 9)
            else:
                gauges = METRICS.snapshot().get("gauges", {})
                # 9 decimals: a tiny CPU smoke's frac is O(1e-7) and must
                # survive into the line (TPU fractions are O(0.1))
                line["roofline_frac"] = round(
                    float(gauges.get("roofline.frac", 0.0)), 9
                )
        if "tok_s_per_chip" not in line:
            chips = chips_for_tag(line.get("mesh"))
            v = float(line.get("value", 0.0))
            if unit == "tok/s/chip":
                line["tok_s_per_chip"] = round(v, 2)
            elif "tok/s" in unit:
                line["tok_s_per_chip"] = round(v / chips, 2)
            else:
                line["tok_s_per_chip"] = 0.0
    except Exception:  # noqa: BLE001 — the headline number must survive
        pass
    diag = os.environ.get("FEI_TPU_ATTACH_DIAG")
    if diag:
        line["attach_diag"] = diag
    # attach the live METRICS snapshot (histogram percentiles included) so
    # BENCH_*.json captures scheduler/engine counters alongside tok/s —
    # AFTER the gate/record logic so onchip_state.json stays lean
    try:
        from fei_tpu.utils.metrics import METRICS

        line["metrics"] = METRICS.snapshot()
    except Exception:  # noqa: BLE001 — the headline number must survive
        pass
    print(json.dumps(line), flush=True)
    return 0


def _probe_backend(timeout_s: float):
    """Touch the backend in a SUBPROCESS so a hung attach cannot consume the
    caller's whole timeout (round-2 BENCH died at rc=124: the backend was
    down and the in-process retry loop ate the driver's budget). Returns
    ("ok", backend_name) / ("error", msg) / ("timeout", msg).

    A probe that outlives ``timeout_s`` is ABANDONED, never killed: killing
    a client mid-claim wedges the chip lease (observed during the round-2
    outage — every subsequent attach then hangs for many minutes). The
    orphaned child writes to a scratch file, finishes its attach on its own
    schedule, and exits cleanly, releasing any claim it acquired."""
    import subprocess
    import tempfile

    outfile = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".probe", delete=False
    )
    code = (
        "import jax, json, sys; ds = jax.devices(); "
        "print('PROBE ' + json.dumps([jax.default_backend(), len(ds)])); "
        "sys.stdout.flush()"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=outfile, stderr=subprocess.STDOUT, text=True,
        start_new_session=True,  # survives our exit if abandoned
    )
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(1.0)
    if proc.poll() is None:
        # leave it attaching; it will release its claim when it finishes.
        # unlink-by-path is safe while the orphan still holds its fd
        outfile.close()
        os.unlink(outfile.name)
        return "timeout", (
            f"attach exceeded {timeout_s:.0f}s (probe pid {proc.pid} "
            "left to finish on its own — killing mid-claim wedges the lease)"
        )
    outfile.seek(0)
    text = outfile.read()
    outfile.close()
    os.unlink(outfile.name)
    if proc.returncode == 0:
        for line in text.splitlines():
            if line.startswith("PROBE "):
                backend, n = json.loads(line[6:])
                return "ok", f"{backend} ({n} devices)"
        return "error", "probe printed no marker"
    tail = text.strip().splitlines()[-3:]
    return "error", " | ".join(tail)[-300:]


def _touch_backend_or_reexec():
    """First device touch, bounded by a TOTAL wall-clock budget.

    The backend is probed in a subprocess (hang-safe); only after a
    successful probe does this process attach. A transiently unavailable
    axon/TPU backend raises at init and the failure is cached for the
    process lifetime, so if the in-process attach still fails we re-exec
    with backoff. Once FEI_TPU_BENCH_MAX_WAIT_S (default 900 s) of total
    waiting is spent, emit an EXPLICITLY-LABELED CPU-fallback line on a tiny
    model rather than dying with no JSON at all — the metric name says it is
    NOT a TPU measurement.
    """
    import jax

    budget = float(os.environ.get("FEI_TPU_BENCH_MAX_WAIT_S", "900"))
    t0 = float(os.environ.setdefault("FEI_TPU_BENCH_T0", repr(time.time())))

    def fallback(reason: str):
        log(f"bench: TPU unavailable ({reason}); "
            "falling back to an explicitly-labeled CPU run")
        # labeled diagnosis for the emitted JSON: an attach that HUNG is a
        # wedged lease, not a missing backend — downstream triage differs
        os.environ.setdefault("FEI_TPU_ATTACH_DIAG", f"attach-failed:{reason}")
        jax.config.update("jax_platforms", "cpu")
        os.environ["FEI_TPU_BENCH_MODEL"] = "tiny"
        os.environ["FEI_TPU_BENCH_CPU_FALLBACK"] = "1"
        return "cpu (TPU-UNAVAILABLE FALLBACK)", jax.devices()

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # explicit CPU smoke run — no probe dance, no fallback relabeling
        return jax.default_backend(), jax.devices()

    attempt = int(os.environ.get("FEI_TPU_BENCH_ATTEMPT", "0"))
    while True:
        remaining = budget - (time.time() - t0)
        if remaining <= 0:
            return fallback(f"retry budget ({budget:.0f}s) exhausted")
        status, detail = _probe_backend(min(max(remaining, 30.0), 600.0))
        if status == "ok":
            log(f"bench: backend probe ok: {detail}")
            os.environ["FEI_TPU_ATTACH_DIAG"] = f"attach-ok:{detail}"
            break
        if status == "timeout":
            # the backend is hung (the probe is still blocked in attach and
            # was ABANDONED, not killed) — attaching in-process would hang
            # the same way; give up cleanly while the budget allows
            os.environ["FEI_TPU_ATTACH_DIAG"] = f"attach-hung:{detail}"
            return fallback(f"backend attach hung: {detail}")
        attempt += 1
        os.environ["FEI_TPU_BENCH_ATTEMPT"] = str(attempt)
        remaining = budget - (time.time() - t0)
        if remaining <= 0:
            return fallback(f"retry budget ({budget:.0f}s) exhausted")
        delay = min(30.0 * (2 ** (attempt - 1)), 120.0, remaining)
        log(f"bench: backend probe failed ({detail}); retry {attempt} "
            f"in {delay:.0f}s ({remaining:.0f}s of budget left)")
        time.sleep(delay)
    try:
        return jax.default_backend(), jax.devices()
    except Exception as exc:  # noqa: BLE001
        # probe succeeded but our (cached-for-life) init failed — re-exec to
        # clear the cache, with backoff and a cap so a flapping backend
        # isn't hammered with attach cycles for the whole budget
        execs = int(os.environ.get("FEI_TPU_BENCH_EXEC_ATTEMPT", "0"))
        delay = 30.0 * (2 ** execs)
        if execs >= 3 or time.time() - t0 + delay >= budget:
            return fallback(f"in-process attach failed: {exc!r}")
        os.environ["FEI_TPU_BENCH_EXEC_ATTEMPT"] = str(execs + 1)
        log(f"bench: in-process attach failed after ok probe ({exc!r}); "
            f"re-exec {execs + 1}/3 in {delay:.0f}s")
        time.sleep(delay)
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, [sys.executable] + sys.argv)


# The byte model and the v5e ceiling now live in fei_tpu.obs.costmodel
# (the engine's live per-dispatch roofline accounting uses the same
# estimates); these aliases keep bench-side callers and tests working.
from fei_tpu.obs.costmodel import (  # noqa: E402
    V5E_HBM_GBPS,
    decode_stream_bytes as _decode_stream_bytes,
)


def bench_decode(model: str, n_tokens: int) -> int:
    from fei_tpu.engine import GenerationConfig

    chunk = max(1, int(os.environ.get("FEI_TPU_BENCH_CHUNK", "64")))

    def build():
        engine = _make_engine(model, max_seq_len=2048)
        prompt = _prompt(engine)
        # ignore_eos: random-weight decode must run the full budget for timing
        gen = GenerationConfig(
            max_new_tokens=n_tokens, temperature=0.0, ignore_eos=True
        )
        t0 = time.time()
        warm = engine.generate_fused(prompt, gen, chunk=chunk)
        log(f"bench: warm-up (compile) {time.time()-t0:.1f}s, "
            f"{len(warm.token_ids)} tokens")
        return engine, prompt, gen

    # the flash/pallas path must never sink the bench: fall back to the XLA
    # oracle and try once more. The rebuild happens OUTSIDE the except block
    # so the failed engine's HBM (pinned via the exception's traceback
    # frames) is freed before a second copy of the weights allocates.
    retry = False
    try:
        engine, prompt, gen = build()
    except Exception as exc:  # noqa: BLE001
        log(f"bench: warm-up failed ({exc!r}); retrying with FEI_TPU_FLASH=0")
        os.environ["FEI_TPU_FLASH"] = "0"
        retry = True
    if retry:
        engine, prompt, gen = build()

    ttfts, tps = [], []
    for i in range(3):
        res = engine.generate_fused(prompt, gen, chunk=chunk)
        ttfts.append(res.ttft_s)
        tps.append(res.decode_tokens_per_s)
        log(f"bench: run {i}: ttft={res.ttft_s*1000:.1f}ms "
            f"decode={res.decode_tokens_per_s:.1f} tok/s "
            f"({len(res.token_ids)} tokens)")

    ttft_p50 = sorted(ttfts)[len(ttfts) // 2]
    tok_s = sorted(tps)[len(tps) // 2]
    log(f"bench: p50 ttft={ttft_p50*1000:.1f}ms")
    prof = os.environ.get("FEI_TPU_BENCH_PROFILE")
    if prof:
        # one traced generation for the roofline gap attribution (where do
        # the GB/s between achieved and the streaming bound go) — viewable
        # with tensorboard or xprof against the written directory
        import jax

        with jax.profiler.trace(prof):
            engine.generate_fused(prompt, gen, chunk=chunk)
        log(f"bench: profiler trace written to {prof}")
    # Roofline: decode is weight-streaming-bound, so the honest utilization
    # lens is tok/s × bytes-streamed-per-token against the HBM ceiling.
    # (MFU stays as a secondary stderr line: a few percent is EXPECTED for
    # single-stream decode — it contextualizes, it does not judge.)
    mean_ctx = len(prompt) + n_tokens // 2
    sb = _decode_stream_bytes(engine, mean_ctx)
    eff_bw = tok_s * sb["total"]
    pct = 100.0 * eff_bw / (V5E_HBM_GBPS * 1e9)
    ceiling = V5E_HBM_GBPS * 1e9 / sb["total"]
    log(f"bench: roofline {sb['total']/1e9:.2f} GB/token "
        f"(weights {sb['weights']/1e9:.2f} + kv_read {sb['kv_read']/1e9:.3f} "
        f"+ kv_write {sb['kv_write']/1e6:.1f}e-3) -> {eff_bw/1e9:.0f} GB/s "
        f"achieved = {pct:.0f}% of v5e {V5E_HBM_GBPS:.0f} GB/s; "
        f"streaming-bound ceiling {ceiling:.1f} tok/s")
    flops_per_tok = 2.0 * engine.cfg.num_active_params()
    mfu = tok_s * flops_per_tok / 197e12
    log(f"bench: est. MFU {mfu*100:.2f}% "
        f"({flops_per_tok/1e9:.1f} GFLOPs/token @ 197 TFLOP/s bf16 peak)")
    tag = _tag(model)
    if chunk != 64:  # A/B arms must never displace the gate headline
        tag += f"-c{chunk}"
    return _emit(f"{tag}_decode_tok_s_per_chip", tok_s,
                 extra={
                     "ttft_ms": round(ttft_p50 * 1000, 1),
                     "gb_per_tok": round(sb["total"] / 1e9, 3),
                     "achieved_gbps": round(eff_bw / 1e9, 1),
                     # 7 sig-decimals: a tiny CPU smoke sits at ~1e-4 %
                     # and must not report a flat zero fraction
                     "pct_v5e_hbm": round(pct, 7),
                     "roofline_tok_s": round(ceiling, 1),
                 })


def bench_prefill(model: str, n_tokens: int) -> int:
    """Prefill latency at agent-loop prompt lengths: time-to-first-token
    for an N-token prompt through the SERVING path — the paged scheduler's
    chunked admission (prompts enter the pool chunk by chunk, interleaved
    with live decode; scheduler.py) — not the dense monolithic prefill.
    Decode throughput never sees this cost; TTFT is its own budget (the
    BASELINE north-star pins p50 TTFT < 500 ms).

    FEI_TPU_BENCH_PREFILL_LEN (default 4096; capped at 512 on the CPU
    fallback) sets the prompt length; ``n_tokens`` is unused — this suite
    times the prompt side, not decode. Emits prefill tokens/sec
    (prompt_len / ttft)."""
    from fei_tpu.engine import GenerationConfig

    plen = int(os.environ.get("FEI_TPU_BENCH_PREFILL_LEN", "4096"))
    if os.environ.get("FEI_TPU_BENCH_CPU_FALLBACK"):
        plen = min(plen, 512)
    engine = _make_engine(
        model, max_seq_len=plen + 64, paged=True, batch_size=1,
    )
    # a prompt of byte-tokenizer ids; content is irrelevant to timing
    prompt = (list(range(1, 256)) * (plen // 255 + 1))[:plen]
    gen = GenerationConfig(max_new_tokens=1, temperature=0.0, ignore_eos=True)

    def one_ttft() -> float:
        t0 = time.time()
        stream = engine.scheduler.stream(prompt, gen)
        next(iter(stream))
        return time.time() - t0

    t0 = time.time()
    one_ttft()
    log(f"bench: prefill warm-up (compile) {time.time()-t0:.1f}s")

    ttfts = []
    for i in range(3):
        t = one_ttft()
        ttfts.append(t)
        log(f"bench: prefill run {i}: {plen} tokens, ttft={t*1000:.1f}ms "
            f"-> {plen/t:.0f} tok/s chunked admission")
    p50 = sorted(ttfts)[len(ttfts) // 2]
    log(f"bench: p50 prefill ttft={p50*1000:.1f}ms for {plen} tokens")
    engine.close()
    return _emit(f"{_tag(model)}_prefill{plen}_tok_s_per_chip", plen / p50,
                 extra={"ttft_ms": round(p50 * 1000, 1)})


def bench_paged(model: str, n_tokens: int) -> int:
    """Continuous batching: N concurrent streams over one paged pool —
    the serving shape of the agent task loop (conversations grow without
    bound, reference fei/core/task_executor.py:231-252)."""
    import threading

    from fei_tpu.engine import GenerationConfig

    streams = int(os.environ.get("FEI_TPU_BENCH_STREAMS", "4"))

    def build_and_warm():
        engine = _make_engine(
            model, max_seq_len=2048, paged=True, batch_size=streams,
            page_size=64,
        )
        prompt = _prompt(engine)
        gen = GenerationConfig(
            max_new_tokens=n_tokens, temperature=0.0, ignore_eos=True
        )

        errors: list = []

        def consume(counts, idx):
            try:
                n = 0
                for _ in engine.scheduler.stream(prompt, gen):
                    n += 1
                counts[idx] = n
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                errors.append(exc)

        # warm-up round compiles admit/step programs
        log(f"bench: paged warm-up ({streams} streams)...")
        t0 = time.time()
        counts = [0] * streams
        threads = [
            threading.Thread(target=consume, args=(counts, i))
            for i in range(streams)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        if not all(counts):
            raise RuntimeError(f"paged warm-up incomplete: tokens={counts}")
        log(f"bench: warm-up {time.time()-t0:.1f}s, tokens={counts}")
        return engine, consume, errors

    # see bench_decode: rebuild outside the handler so the failed engine's
    # HBM is released before the second allocation. The retry disables
    # every optional kernel path (flash, block-attention verify, paged-
    # native prefill) — a Mosaic rejection of any of them must never sink
    # the bench.
    retry = False
    try:
        engine, consume, errors = build_and_warm()
    except Exception as exc:  # noqa: BLE001 — pallas must never sink the bench
        log(f"bench: paged warm-up failed ({exc!r}); retrying with "
            "FEI_TPU_FLASH=0 FEI_TPU_BLOCK_ATTN=0 FEI_TPU_PAGED_PREFILL=0")
        os.environ["FEI_TPU_FLASH"] = "0"
        os.environ["FEI_TPU_BLOCK_ATTN"] = "0"
        os.environ["FEI_TPU_PAGED_PREFILL"] = "0"
        retry = True
    if retry:
        engine, consume, errors = build_and_warm()

    # headline = MEDIAN of >= 3 measured runs: max() rewarded one lucky
    # scheduling window and made run-to-run regressions invisible
    # (VERDICT r5); the median is stable against a single outlier in
    # either direction while per-run rates stay in the emitted extras.
    rates: list[float] = []
    for run in range(3):
        counts = [0] * streams
        errors.clear()
        threads = [
            threading.Thread(target=consume, args=(counts, i))
            for i in range(streams)
        ]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:  # a failed stream must sink the run, not deflate it
            raise errors[0]
        dt = time.time() - t0
        agg = sum(counts) / dt
        log(f"bench: paged run {run}: {sum(counts)} tokens in {dt:.1f}s "
            f"-> {agg:.1f} tok/s aggregate")
        rates.append(agg)
    kv = os.environ.get("FEI_TPU_BENCH_KV_QUANT")
    tag = _tag(model)
    if kv:
        tag += f"-kv{kv}"
    ms = os.environ.get("FEI_TPU_SCHED_MULTISTEP")
    if ms:  # A/B runs must not collide with the default metric
        tag += f"-ms{ms}"
    sp = os.environ.get("FEI_TPU_SPECULATE")
    if sp is not None:  # both arms of the spec A/B must persist
        tag += f"-spec{sp}"
    return _emit(
        f"{tag}_paged_{streams}stream_agg_tok_s_per_chip",
        sorted(rates)[len(rates) // 2],
        extra={"runs_tok_s": [round(r, 2) for r in rates]},
    )


def bench_ragged(model: str, n_tokens: int) -> int:
    """A/B of the ragged merged dispatch: FEI_TPU_ATTENTION=paged (legacy
    solo chunk + solo scan programs) vs =ragged (one merged program per
    overlap iteration), at batch 1 and batch 8, median-of-3 per arm with
    per-run rates attached. The flag is read at scheduler construction,
    so each arm builds its own engine; a small prefill chunk keeps
    admissions chunked (the regime the merge exists for). Each rung also
    greedy-compares one stream across arms — an A/B whose arms decode
    different tokens measures nothing."""
    import threading

    from fei_tpu.engine import GenerationConfig

    prev_attn = os.environ.get("FEI_TPU_ATTENTION")
    results: dict[str, dict] = {}
    gen = GenerationConfig(
        max_new_tokens=n_tokens, temperature=0.0, ignore_eos=True
    )
    try:
        for streams in (1, 8):
            engines: dict[str, tuple] = {}
            ref_tokens = None
            for arm in ("paged", "ragged"):
                os.environ["FEI_TPU_ATTENTION"] = arm
                engine = _make_engine(
                    model, max_seq_len=2048, paged=True,
                    batch_size=streams, page_size=64,
                )
                # the 128-token bench prompt must actually chunk (2 here)
                # or no overlap iterations occur and both arms measure the
                # same program; single-slot engines still never merge
                engine.scheduler.prefill_chunk = 64
                prompt = _prompt(engine)
                # parity probe doubles as the single-stream warm-up
                toks = list(engine.scheduler.stream(prompt, gen))
                if ref_tokens is None:
                    ref_tokens = toks
                elif toks != ref_tokens:
                    raise RuntimeError(
                        f"ragged A/B arms diverged at {streams} stream(s): "
                        f"{toks[:8]} vs {ref_tokens[:8]}"
                    )
                engines[arm] = (engine, prompt)

            def fan(engine, prompt, streams=streams):
                counts = [0] * streams
                errors: list = []

                def consume(i):
                    try:
                        counts[i] = sum(
                            1 for _ in engine.scheduler.stream(prompt, gen)
                        )
                    except BaseException as exc:  # noqa: BLE001 — re-raised
                        errors.append(exc)

                threads = [
                    threading.Thread(target=consume, args=(i,))
                    for i in range(streams)
                ]
                t0 = time.time()
                [t.start() for t in threads]
                [t.join() for t in threads]
                if errors:
                    raise errors[0]
                return sum(counts), time.time() - t0

            # untimed full-fan round per arm first: compiles every
            # merged-program signature (one per armed-slot count) before
            # the clock starts
            for arm in ("paged", "ragged"):
                fan(*engines[arm])
            rates: dict[str, list[float]] = {"paged": [], "ragged": []}
            for run in range(3):
                # interleaved: machine drift lands on both arms equally
                for arm in ("paged", "ragged"):
                    n_toks, dt = fan(*engines[arm])
                    rates[arm].append(n_toks / dt)
            for arm in ("paged", "ragged"):
                engines[arm][0].scheduler.close()
                med = sorted(rates[arm])[len(rates[arm]) // 2]
                log(f"bench: ragged A/B arm={arm} streams={streams}: "
                    f"median {med:.1f} tok/s (runs {rates[arm]})")
                results[f"{arm}_{streams}s"] = {
                    "tok_s": round(med, 2),
                    "runs_tok_s": [round(r, 2) for r in rates[arm]],
                }
            engines.clear()
    finally:
        if prev_attn is None:
            os.environ.pop("FEI_TPU_ATTENTION", None)
        else:
            os.environ["FEI_TPU_ATTENTION"] = prev_attn
    rc = 0
    for key, r in results.items():
        rc = _emit(
            f"{_tag(model)}_ragged_ab_{key}_agg_tok_s_per_chip",
            r["tok_s"], extra={"runs_tok_s": r["runs_tok_s"]},
        )
    return rc


def bench_moe(model: str, n_tokens: int) -> int:
    os.environ.setdefault("FEI_TPU_ROUTED_MOE", "auto")
    return bench_decode(model, n_tokens)


def bench_sharded(model: str, n_tokens: int) -> int:
    """The mesh-mode ladder: the SAME paged-serving workload at ms1, tp2
    (and any further FEI_TPU_BENCH_MESH_LADDER rungs — tp4, tp2dp2, …).
    Each rung reports aggregate tok/s AND its slot count, so dp replica
    groups multiplying the scheduler's decode slots reads directly off
    the ladder; each sharded rung also replays one greedy stream and
    checks it token-identical to the ms1 reference (the serving mode's
    bit-identity contract, docs/ENGINE.md "Mesh modes"). Rungs the host
    cannot place (too few devices, tp not dividing the model's kv heads)
    are SKIPPED LOUDLY — a silent drop would read as a covered rung."""
    import threading

    from fei_tpu.engine import GenerationConfig
    from fei_tpu.parallel.mesh import env_mesh_tag

    rungs = [
        r.strip() for r in os.environ.get(
            "FEI_TPU_BENCH_MESH_LADDER", "ms1,tp2,tp2dp2"
        ).split(",") if r.strip()
    ]
    streams = int(os.environ.get("FEI_TPU_BENCH_STREAMS", "2"))
    gen = GenerationConfig(
        max_new_tokens=n_tokens, temperature=0.0, ignore_eos=True
    )
    prev_mesh = os.environ.get("FEI_TPU_MESH")
    ladder: list[dict] = []
    ref_tokens: list | None = None
    try:
        for rung in rungs:
            os.environ["FEI_TPU_MESH"] = "" if rung == "ms1" else rung
            try:
                engine = _make_engine(
                    model, max_seq_len=1024, paged=True,
                    batch_size=streams, page_size=64,
                )
            except ValueError as exc:
                log(f"bench: sharded rung {rung} SKIPPED: {exc}")
                ladder.append({"mesh": rung, "skipped": str(exc)})
                continue
            prompt = _prompt(engine)
            slots = engine.batch_size  # dp multiplies the configured slots

            # one greedy stream first: the parity probe (and the warm-up
            # that compiles the admit/decode programs)
            toks = list(engine.scheduler.stream(prompt, gen))
            if ref_tokens is None:
                ref_tokens = toks
            parity = toks == ref_tokens

            counts = [0] * slots
            errors: list = []

            def consume(i, engine=engine, prompt=prompt, counts=counts,
                        errors=errors):
                try:
                    counts[i] = sum(
                        1 for _ in engine.scheduler.stream(prompt, gen)
                    )
                except BaseException as exc:  # noqa: BLE001 — re-raised
                    errors.append(exc)

            t0 = time.time()
            threads = [
                threading.Thread(target=consume, args=(i,))
                for i in range(slots)
            ]
            [t.start() for t in threads]
            [t.join() for t in threads]
            if errors:
                raise errors[0]
            dt = time.time() - t0
            agg = sum(counts) / dt
            engine.scheduler.close()
            del engine
            tag = env_mesh_tag()
            log(f"bench: sharded rung {rung} ({tag}): {slots} slots, "
                f"{sum(counts)} tokens in {dt:.1f}s -> {agg:.1f} tok/s "
                f"aggregate, greedy_parity={parity}")
            ladder.append({
                "mesh": tag, "slots": slots,
                "agg_tok_s": round(agg, 2), "greedy_parity": parity,
            })
    finally:
        if prev_mesh is None:
            os.environ.pop("FEI_TPU_MESH", None)
        else:
            os.environ["FEI_TPU_MESH"] = prev_mesh

    measured = [r for r in ladder if "agg_tok_s" in r]
    if not measured:
        raise RuntimeError(f"sharded ladder measured nothing: {ladder}")
    if not all(r.get("greedy_parity") for r in measured):
        raise RuntimeError(f"sharded ladder parity violated: {ladder}")
    headline = measured[-1]  # the widest rung that actually ran
    return _emit(
        f"{_tag(model)}_sharded_{headline['mesh']}_agg_tok_s_per_chip",
        headline["agg_tok_s"],
        extra={"mesh": headline["mesh"], "ladder": ladder,
               "streams_per_replica": streams},
    )


def bench_remote(n_tokens: int) -> int:
    """BASELINE config #1: the remote-client transport baseline — the full
    `fei --message` stack (Assistant → RemoteProvider → HTTP) against a
    loopback OpenAI-compatible stub. No TPU involved by design: the number
    is the CLIENT-PATH floor the in-tree jax_local provider replaces
    (reference transport: fei/core/assistant.py:524-530)."""
    import asyncio

    from fei_tpu.agent import Assistant
    from fei_tpu.agent.providers import RemoteProvider
    from fei_tpu.utils.openai_stub import serve_openai_stub

    content = " ".join(f"tok{i}" for i in range(n_tokens))
    server, base = serve_openai_stub(
        content=content, completion_tokens=n_tokens
    )
    provider = RemoteProvider("openai", model="stub", api_key="local",
                              api_base=base)
    message = "Summarize what a Maildir filename encodes."

    def turn() -> float:
        assistant = Assistant(provider=provider, max_tokens=n_tokens)
        t0 = time.perf_counter()
        asyncio.run(assistant.chat(message))
        return time.perf_counter() - t0

    turn()  # warm-up (event loop, connection setup)
    lats = [turn() for _ in range(20)]
    server.shutdown()
    p50 = sorted(lats)[len(lats) // 2]
    tok_s = n_tokens * len(lats) / sum(lats)
    log(f"bench: remote client loopback: p50 turn {p50*1000:.1f} ms, "
        f"{tok_s:.0f} tok/s through the full client path "
        f"({len(lats)} turns, {n_tokens} tok canned completion)")
    return _emit("remote_client_loopback_e2e_tok_s", tok_s)


def bench_federation(n_tokens: int) -> int:
    """BASELINE config #5 shape on the hermetic mesh: 4 federation nodes —
    (a) shared-embedding bank all-gather over the mesh's node axis (the ICI
    data plane that replaces the reference's HTTP JSON gossip,
    memdir_tools/memorychain.py:1003-1035) and (b) propose→consensus→commit
    latency over the loopback transport (51 % quorum)."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fei_tpu.memory.memorychain.chain import MemoryChain
    from fei_tpu.memory.memorychain.embedding_exchange import (
        EmbeddingFederation,
        exchange_banks,
    )
    from fei_tpu.memory.memorychain.transport import LoopbackTransport
    from fei_tpu.parallel.mesh import make_mesh

    n_nodes = 4
    devs = jax.devices()
    if len(devs) < n_nodes:
        log(f"bench: federation needs {n_nodes} devices, have {len(devs)}")
        return 1
    mesh = make_mesh({"dp": n_nodes}, devices=devs[:n_nodes])
    bank, dim = int(os.environ.get("FEI_TPU_BENCH_FED_BANK", "4096")), 256
    feds = [
        EmbeddingFederation(i, n_nodes, bank_size=bank, dim=dim)
        for i in range(n_nodes)
    ]
    for i, fed in enumerate(feds):
        for j in range(64):
            fed.add(f"mem-{i}-{j}", f"node {i} memory {j} maildir flags tools")
    banks = np.stack([f.local_bank for f in feds])  # [4, bank, 256] fp32

    # the bank lives ON DEVICE in a real node (its compute produces it);
    # land it sharded once so the loop times the collective, not a
    # host->device upload per iteration
    from jax.sharding import NamedSharding, PartitionSpec as P

    dev_banks = jax.device_put(
        jnp.asarray(banks), NamedSharding(mesh, P("dp"))
    )
    # jit once so the loop times the collective, not per-call shard_map
    # re-lowering; block every iteration (queueing unbounded CPU
    # collectives can abort) without transferring the 4x-redundant view —
    # this suite always runs on the forced CPU mesh, where
    # block_until_ready is real (the axon caveat doesn't apply)
    import functools

    gather = jax.jit(functools.partial(exchange_banks, mesh=mesh))
    jax.block_until_ready(gather(dev_banks))  # compile
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(gather(dev_banks))
    dt = time.perf_counter() - t0
    recv = banks.nbytes * (n_nodes - 1) / n_nodes  # bytes received/device
    gbps = iters * recv / dt / 1e9
    log(f"bench: federation all-gather: {banks.nbytes/1e6:.1f} MB bank, "
        f"{gbps:.2f} GB/s effective per device over {iters} iters")

    # the gathered view must actually serve recall
    feds[0].sync(mesh, banks)
    hits = feds[0].search("maildir flags", top_k=3)
    assert hits, "federation search returned nothing"

    tmp = tempfile.mkdtemp(prefix="fei-fed-bench-")
    lb = LoopbackTransport()
    chains = [
        MemoryChain(node_id=f"bench-n{i}", base_dir=tmp, transport=lb)
        for i in range(n_nodes)
    ]
    for i, c in enumerate(chains):
        lb.register(f"n{i}", c)
        c.peers = [f"n{j}" for j in range(n_nodes) if j != i]
    lats = []
    for k in range(20):
        t1 = time.perf_counter()
        blk = chains[0].propose_memory(
            {"content": f"bench memory {k}",
             "headers": {"Subject": f"bench {k}"}}
        )
        lats.append(time.perf_counter() - t1)
        if blk is None:
            raise RuntimeError("federation proposal rejected")
    p50 = sorted(lats)[len(lats) // 2]
    log(f"bench: federation consensus: propose->commit p50 "
        f"{p50*1000:.2f} ms (4 nodes, 51% quorum, loopback transport)")
    return _emit("federation_4node_embed_allgather_GBps", gbps, unit="GB/s")


def bench_fleet(model: str, n_tokens: int) -> int:
    """Bursty multi-tenant overload through the fleet front door.

    Two in-process replicas (tiny paged engines behind ServeAPI cores)
    sit behind fei_tpu.fleet.Router with FEI_TPU_TENANT_BUDGETS
    gold:4/silver:2/bronze:1 and a deliberately small waiting queue, so
    ~2x-capacity concurrent sessions MUST overflow. The shape of the
    degradation is the measurement: bronze (priority 0) absorbs the
    sheds and queue evictions, gold (priority 2) keeps a bounded p99
    TTFT vs its own unloaded baseline. A rolling restart fires
    mid-burst; any stream that had tokens flowing and then died counts
    as a dropped accepted request (the zero-downtime claim wants 0).

    FEI_TPU_BENCH_SESSIONS (default 18; raise on-chip) sets burst width,
    FEI_TPU_BENCH_ROUNDS (default 2) requests per session."""
    import tempfile
    import threading

    from fei_tpu.agent.providers import JaxLocalProvider
    from fei_tpu.fleet import InProcessReplica, Router
    from fei_tpu.fleet.router import _parse_sse
    from fei_tpu.ui.server import ServeAPI

    # QoS knobs land before any engine exists (TenantBook reads env at
    # scheduler construction)
    os.environ.setdefault("FEI_TPU_TENANT_BUDGETS", "gold:4,silver:2,bronze:1")
    os.environ.setdefault("FEI_TPU_MAX_QUEUE", "3")
    sessions = int(os.environ.get("FEI_TPU_BENCH_SESSIONS", "18"))
    rounds = int(os.environ.get("FEI_TPU_BENCH_ROUNDS", "2"))
    budget = min(n_tokens, 24)

    def factory():
        engine = _make_engine(
            model, max_seq_len=512, paged=True, batch_size=2, page_size=16,
        )
        return ServeAPI(JaxLocalProvider(engine=engine), model_name="fleet")

    replicas = [
        InProcessReplica(
            f"r{i}", factory=factory,
            drain_dir=tempfile.mkdtemp(prefix=f"fei-fleet-r{i}-"),
        )
        for i in range(2)
    ]
    router = Router(replicas, health_ttl_s=0.2, breaker_cooldown_s=0.5)

    tenants = [("gold", 2), ("silver", 1), ("bronze", 0)]
    weights = {"gold": 4.0, "silver": 2.0, "bronze": 1.0}

    def one_request(tenant: str, priority: int, session: str):
        body = {
            "messages": [{"role": "user",
                          "content": f"fleet bench {tenant} {session}"}],
            "max_tokens": budget, "temperature": 0,
            "tenant": tenant, "priority": priority, "session": session,
        }
        t0 = time.perf_counter()
        ttft, tokens, err = None, 0, None
        for chunk in router.stream_chat(body, {}):
            info = _parse_sse(chunk)
            if info is None:
                continue
            if info.get("error"):
                err = dict(info["error"])
                break
            delta = (info.get("choices") or [{}])[0].get("delta") or {}
            if delta.get("content"):
                tokens += 1
                if ttft is None:
                    ttft = time.perf_counter() - t0
        return {"tenant": tenant, "ttft": ttft, "tokens": tokens,
                "error": err}

    # -- unloaded baseline: gold alone, sequential --------------------------
    log("bench: fleet unloaded gold baseline...")
    base = [one_request("gold", 2, f"gold-base-{i}") for i in range(4)]
    base_ttfts = sorted(r["ttft"] for r in base if r["ttft"] is not None)
    if not base_ttfts:
        raise RuntimeError(f"fleet baseline produced no tokens: {base}")
    base_p99 = base_ttfts[int(0.99 * (len(base_ttfts) - 1))]
    log(f"bench: fleet unloaded gold p99 ttft={base_p99*1000:.1f}ms")

    # -- 2x-overload burst + rolling restart mid-stream ---------------------
    results: list[dict] = []
    res_lock = threading.Lock()

    def session_worker(idx: int):
        tenant, priority = tenants[idx % len(tenants)]
        for r in range(rounds):
            out = one_request(tenant, priority, f"{tenant}-s{idx}")
            with res_lock:
                results.append(out)

    restart_report: dict = {}

    def do_restart():
        time.sleep(1.0)  # let the burst saturate first
        restart_report.update(router.rolling_restart(
            drain_deadline_s=60.0, wait_s=120.0
        ))

    log(f"bench: fleet overload burst: {sessions} sessions x {rounds} "
        f"rounds across {len(tenants)} tenants, restart mid-burst...")
    t0 = time.time()
    workers = [threading.Thread(target=session_worker, args=(i,))
               for i in range(sessions)]
    restarter = threading.Thread(target=do_restart)
    [w.start() for w in workers]
    restarter.start()
    [w.join() for w in workers]
    restarter.join()
    dt = time.time() - t0

    per: dict[str, dict] = {
        t: {"served": 0, "tokens": 0, "sheds": 0, "ttfts": []}
        for t, _ in tenants
    }
    dropped = 0
    for r in results:
        b = per[r["tenant"]]
        if r["error"] is not None and r["tokens"] == 0:
            b["sheds"] += 1
            continue
        if r["error"] is not None:
            dropped += 1  # accepted (tokens flowed), then died
            continue
        b["served"] += 1
        b["tokens"] += r["tokens"]
        if r["ttft"] is not None:
            b["ttfts"].append(r["ttft"])

    total_tokens = sum(b["tokens"] for b in per.values())
    total_sheds = sum(b["sheds"] for b in per.values())
    extra: dict = {"per_tenant": {}, "unloaded_gold_p99_ttft_ms":
                   round(base_p99 * 1000, 1)}
    for t, _ in tenants:
        b = per[t]
        ts = sorted(b["ttfts"])
        p99 = ts[int(0.99 * (len(ts) - 1))] if ts else None
        extra["per_tenant"][t] = {
            "served": b["served"], "tokens": b["tokens"],
            "sheds": b["sheds"],
            "p99_ttft_ms": round(p99 * 1000, 1) if p99 else None,
            "goodput_per_weight": round(b["tokens"] / weights[t], 2),
        }
        log(f"bench: fleet tenant {t}: served={b['served']} "
            f"tokens={b['tokens']} sheds={b['sheds']} "
            f"p99_ttft={p99*1000:.1f}ms" if p99 else
            f"bench: fleet tenant {t}: served={b['served']} "
            f"tokens={b['tokens']} sheds={b['sheds']} (no ttft)")
    gold_ts = sorted(per["gold"]["ttfts"])
    if gold_ts:
        gold_p99 = gold_ts[int(0.99 * (len(gold_ts) - 1))]
        extra["gold_p99_vs_unloaded"] = round(gold_p99 / base_p99, 3)
    extra["bronze_shed_share"] = (
        round(per["bronze"]["sheds"] / total_sheds, 3) if total_sheds else None
    )
    extra["total_sheds"] = total_sheds
    extra["restart_dropped_accepted"] = dropped
    extra["rolling_restart"] = restart_report
    extra["sessions"] = sessions
    log(f"bench: fleet burst done in {dt:.1f}s: {total_tokens} tokens, "
        f"{total_sheds} sheds (bronze share "
        f"{extra['bronze_shed_share']}), dropped_accepted={dropped}, "
        f"restart={restart_report}")
    return _emit("fleet_2replica_overload_agg_tok_s", total_tokens / dt,
                 unit="tok/s", extra=extra)


def bench_crash(model: str, n_tokens: int) -> int:
    """Mid-burst replica death: resurrection MTTR + the journal tax.

    Phase 1 — ~2x-overload burst of streams through the router over two
    in-process replicas; once every stream has tokens flowing, replica
    r0 is severed (health and every live stream raise, exactly what a
    SIGKILL looks like from the router's side). The router must
    resurrect every affected stream on r1 with the delivered suffix
    teacher-forced. Headline: MTTR — the client-visible inter-frame gap
    the failover cost, taken as the top-R max gaps after the kill (R =
    resurrections; unaffected streams keep their normal decode
    cadence). Extras: tokens replayed, dropped accepted streams (the
    zero-loss claim wants 0).

    Phase 2 — journal sync A/B: single-stream decode tok/s with the
    session journal disabled, FEI_TPU_JOURNAL_SYNC=batch, and =always
    (the fsync-per-record fleet mode), so the durability tax is a
    recorded number, not folklore."""
    import tempfile
    import threading

    from fei_tpu.agent.providers import JaxLocalProvider
    from fei_tpu.fleet import InProcessReplica, Router
    from fei_tpu.fleet.router import _parse_sse
    from fei_tpu.ui.server import ServeAPI
    from fei_tpu.utils.metrics import METRICS

    os.environ.setdefault("FEI_TPU_MAX_QUEUE", "32")
    sessions = int(os.environ.get("FEI_TPU_BENCH_SESSIONS", "8"))
    # streams must outlive the kill by a wide margin or the burst
    # degenerates into pre-commit retries (nothing to resurrect), so the
    # crash suite enforces a floor on the per-stream budget
    budget = min(max(n_tokens, 16), 32)

    class _Mortal:
        """Delegating wrapper that can drop dead mid-stream."""

        def __init__(self, inner):
            self._inner = inner
            self.dead = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def health(self):
            if self.dead:
                raise ConnectionError(f"{self._inner.rid} is dead")
            return self._inner.health()

        def request(self, *a, **k):
            if self.dead:
                raise ConnectionError(f"{self._inner.rid} is dead")
            return self._inner.request(*a, **k)

        def stream(self, body, headers=None):
            inner = self._inner.stream(body, headers)

            def frames():
                for f in inner:
                    if self.dead:
                        raise ConnectionError(
                            f"{self._inner.rid} died mid-stream"
                        )
                    yield f

            return frames()

    def make_api():
        engine = _make_engine(
            model, max_seq_len=512, paged=True, batch_size=2, page_size=16,
        )
        return ServeAPI(JaxLocalProvider(engine=engine), model_name="crash")

    replicas = [_Mortal(InProcessReplica(f"r{i}", api=make_api()))
                for i in range(2)]
    router = Router(replicas, retries=2, backoff_s=0.05, health_ttl_s=0.2)
    c0 = METRICS.snapshot()["counters"]

    delivered = [0]  # content frames across all streams (kill trigger)
    dl_lock = threading.Lock()
    results: list[dict] = []
    res_lock = threading.Lock()
    t_kill = [None]

    def one_stream(idx: int):
        body = {
            "messages": [{"role": "user", "content": f"crash bench {idx}"}],
            "max_tokens": budget, "temperature": 0, "ignore_eos": True,
            "session": f"crash-{idx}",
        }
        frame_times, tokens, err = [], 0, None
        for chunk in router.stream_chat(body, {}):
            info = _parse_sse(chunk)
            if info is None:
                continue
            if info.get("error"):
                err = dict(info["error"])
                break
            delta = (info.get("choices") or [{}])[0].get("delta") or {}
            if delta.get("content"):
                tokens += 1
                frame_times.append(time.perf_counter())
                with dl_lock:
                    delivered[0] += 1
        with res_lock:
            results.append(
                {"tokens": tokens, "err": err, "times": frame_times}
            )

    def killer():
        # sever as soon as streams have genuinely committed tokens —
        # waiting longer lets short streams finish and turns the kill
        # into a boring pre-commit retry
        deadline = time.time() + 120.0
        while time.time() < deadline:
            with dl_lock:
                if delivered[0] >= 2:
                    break
            time.sleep(0.005)
        t_kill[0] = time.perf_counter()
        replicas[0].dead = True
        log("bench: crash: severed r0 mid-burst")

    log(f"bench: crash burst: {sessions} streams x {budget} tokens, "
        "killing r0 mid-flight...")
    t0 = time.time()
    workers = [threading.Thread(target=one_stream, args=(i,))
               for i in range(sessions)]
    kth = threading.Thread(target=killer)
    [w.start() for w in workers]
    kth.start()
    [w.join() for w in workers]
    kth.join()
    dt = time.time() - t0

    c1 = METRICS.snapshot()["counters"]

    def delta(k: str) -> float:
        return c1.get(k, 0) - c0.get(k, 0)

    resurrections = int(delta("router.resurrections"))
    replayed = int(delta("router.resurrection_replayed_tokens"))
    dropped = sum(1 for r in results if r["err"] is not None
                  and r["tokens"] > 0)
    sheds = sum(1 for r in results if r["err"] is not None
                and r["tokens"] == 0)
    total_tokens = sum(r["tokens"] for r in results)

    # per-stream worst inter-frame gap after the kill; the top-R are the
    # resurrected streams' failover stalls
    gaps = []
    tk = t_kill[0]
    for r in results:
        ts = [t for t in r["times"] if tk is None or t >= tk]
        prev = tk
        worst = 0.0
        for t in ts:
            if prev is not None:
                worst = max(worst, t - prev)
            prev = t
        if worst > 0:
            gaps.append(worst)
    gaps.sort(reverse=True)
    mttr = sorted(gaps[:resurrections]) if resurrections else []
    mttr_p50 = mttr[len(mttr) // 2] if mttr else 0.0
    mttr_max = mttr[-1] if mttr else 0.0

    extra = {
        "sessions": sessions,
        "resurrections": resurrections,
        "replayed_tokens": replayed,
        "dropped_accepted": dropped,
        "sheds": sheds,
        "burst_agg_tok_s": round(total_tokens / dt, 2),
        "mttr_max_ms": round(mttr_max * 1000, 1),
    }
    log(f"bench: crash burst done in {dt:.1f}s: "
        f"resurrections={resurrections} replayed={replayed} "
        f"dropped_accepted={dropped} mttr_p50={mttr_p50*1000:.1f}ms "
        f"max={mttr_max*1000:.1f}ms")
    for r in replicas:
        eng = r._inner.engine
        if eng is not None:
            eng.close()

    # -- phase 2: the journal durability tax --------------------------------
    sync_ab: dict[str, float] = {}
    saved = {k: os.environ.get(k)
             for k in ("FEI_TPU_JOURNAL_DIR", "FEI_TPU_JOURNAL_SYNC")}
    try:
        for mode in ("disabled", "batch", "always"):
            if mode == "disabled":
                os.environ.pop("FEI_TPU_JOURNAL_DIR", None)
                os.environ.pop("FEI_TPU_JOURNAL_SYNC", None)
            else:
                os.environ["FEI_TPU_JOURNAL_DIR"] = tempfile.mkdtemp(
                    prefix=f"fei-bench-journal-{mode}-"
                )
                os.environ["FEI_TPU_JOURNAL_SYNC"] = mode
            engine = _make_engine(
                model, max_seq_len=512, paged=True, batch_size=1,
                page_size=16,
            )
            provider = JaxLocalProvider(engine=engine)
            msgs = [{"role": "user", "content": "journal tax probe"}]

            def run(tokens: int) -> float:
                t0 = time.perf_counter()
                n = sum(1 for _ in provider.stream(
                    msgs, max_tokens=tokens,
                    gen_overrides={"temperature": 0.0, "ignore_eos": True},
                ))
                dt = time.perf_counter() - t0
                return max(n, 1) / dt

            run(4)  # compile warm-up
            sync_ab[mode] = round(run(budget), 2)
            log(f"bench: crash journal A/B {mode}: {sync_ab[mode]} tok/s")
            engine.close()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    extra["journal_sync_tok_s"] = sync_ab

    return _emit("crash_resurrection_mttr_p50_ms", mttr_p50 * 1000,
                 unit="ms", extra=extra)


def bench_reshard(model: str, n_tokens: int) -> int:
    """Mesh-elastic recovery cost: what does it take to get a torn
    session streaming again on a DIFFERENT mesh?

    Three legs, each measured as catch-up latency (time until the
    recovered stream has delivered one token PAST the pre-crash point):

    - shrink    — journal written by a tp2 engine, recovered on a
                  single chip (the headline: the chip-died-and-the-
                  replica-re-formed-smaller scene). Needs >= 2 devices;
                  degrades to a same-mesh run with a note otherwise.
    - same_mesh — journal written and recovered on the same single-chip
                  geometry (the cross-mesh tax baseline).
    - cold      — no journal at all: re-prefill the prompt and
                  re-generate up to the same point (what recovery costs
                  when you have nothing).

    Extras carry per-leg first-frame latency, replayed/restored token
    counts, the engine.cross_mesh_recoveries delta, and a per-leg
    byte_identical flag (the zero-loss claim wants all true)."""
    import shutil
    import tempfile

    import jax

    from fei_tpu.engine.engine import GenerationConfig
    from fei_tpu.utils.metrics import METRICS

    budget = max(8, min(n_tokens, 16))
    accept = 5  # tokens the client had before the crash
    can_tp2 = len(jax.devices()) >= 2
    work = tempfile.mkdtemp(prefix="fei-bench-reshard-")

    def make(mesh: str | None, jdir: str | None):
        overrides = {
            "FEI_TPU_JOURNAL_DIR": jdir,
            "FEI_TPU_JOURNAL_SYNC": "batch" if jdir else None,
            "FEI_TPU_MESH": mesh,
        }
        old = {k: os.environ.get(k) for k in overrides}
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            return _make_engine(
                model, max_seq_len=512, paged=True, batch_size=2,
                page_size=16,
            )
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    gen = GenerationConfig(max_new_tokens=budget, temperature=0.0,
                           ignore_eos=True)
    warm = GenerationConfig(max_new_tokens=2, temperature=0.0,
                            ignore_eos=True)
    prompt: list | None = None
    legs: dict[str, dict] = {}

    def torn_journal(name: str, src_mesh: str | None) -> tuple[str, list]:
        """Freeze a journal dir exactly as a kill -9 would leave it:
        ``accept`` tokens delivered, flushed, copied before any
        cooperative shutdown runs."""
        nonlocal prompt
        jdir = os.path.join(work, f"{name}-wal")
        crash = os.path.join(work, f"{name}-dead")
        src = make(src_mesh, jdir)
        if prompt is None:
            prompt = _prompt(src)[:32]
        seq = src.scheduler.submit(prompt, gen)
        pre = [seq.out.get() for _ in range(accept)]
        assert src.scheduler._journal.flush()
        shutil.copytree(jdir, crash)
        src.close()
        return crash, pre

    def recover(name: str, dst_mesh: str | None, crash: str,
                pre: list) -> None:
        dst = make(dst_mesh, crash)
        # same-shape warm-up so the leg times recovery (journal read +
        # teacher-forced replay + decode), not XLA compilation: one
        # plain stream, plus one restore-shaped submit to compile the
        # replay path itself; both terminate cleanly so warm_restart
        # never sees them
        list(dst.scheduler.stream(prompt, warm))
        wseq = dst.scheduler.submit(
            prompt, warm,
            _restore={"generated": list(pre[:2]), "resume_key": None},
        )
        list(dst.scheduler.drain(wseq))
        c0 = METRICS.snapshot()["counters"]
        t0 = time.perf_counter()
        restored = dst.warm_restart()
        toks: list = []
        t_first = t_caught = None
        for s in restored:
            for t in dst.scheduler.drain(s):
                toks.append(t)
                now = time.perf_counter()
                if t_first is None:
                    t_first = now
                if t_caught is None and len(toks) > len(pre):
                    t_caught = now
        c1 = METRICS.snapshot()["counters"]
        dst.close()
        legs[name] = {
            "first_frame_ms": round(((t_first or t0) - t0) * 1000, 1),
            "catchup_ms": round(((t_caught or t_first or t0) - t0) * 1000,
                                1),
            "restored_sessions": int(
                c1.get("journal.recovered_sessions", 0)
                - c0.get("journal.recovered_sessions", 0)),
            "replayed_tokens": len(pre),
            "cross_mesh_recoveries": int(
                c1.get("engine.cross_mesh_recoveries", 0)
                - c0.get("engine.cross_mesh_recoveries", 0)),
            "byte_identical": toks[:len(pre)] == pre,
        }
        log(f"bench: reshard {name}: catchup={legs[name]['catchup_ms']}ms "
            f"byte_identical={legs[name]['byte_identical']}")

    # -- leg 1: tp2 -> single chip (the shrink) -----------------------------
    src_mesh = "tp2" if can_tp2 else None
    if not can_tp2:
        log("bench: reshard: single device visible; shrink leg degrades "
            "to a same-mesh run (note stamped in extras)")
    crash, pre = torn_journal("shrink", src_mesh)
    recover("shrink", None, crash, pre)

    # -- leg 2: same mesh (the cross-mesh tax baseline) ---------------------
    crash, pre = torn_journal("same_mesh", None)
    recover("same_mesh", None, crash, pre)

    # -- leg 3: cold re-prefill (no journal: the cost of having nothing) ----
    cold = make(None, None)
    list(cold.scheduler.stream(prompt, warm))
    cold_gen = GenerationConfig(max_new_tokens=accept + 1, temperature=0.0,
                                ignore_eos=True)
    t0 = time.perf_counter()
    toks = list(cold.scheduler.stream(prompt, cold_gen))
    t_caught = time.perf_counter()
    cold.close()
    legs["cold"] = {
        "catchup_ms": round((t_caught - t0) * 1000, 1),
        "replayed_tokens": 0,
        "restored_sessions": 0,
        "byte_identical": toks[:accept] == pre,
    }
    log(f"bench: reshard cold: catchup={legs['cold']['catchup_ms']}ms")

    shutil.rmtree(work, ignore_errors=True)
    extra = {
        "legs": legs,
        "accepted_tokens_at_crash": accept,
        "tp2_leg": "tp2" if can_tp2 else "degraded_ms1_single_device",
        "all_byte_identical": all(v["byte_identical"]
                                  for v in legs.values()),
    }
    return _emit(f"{_tag(model)}_reshard_shrink_catchup_ms",
                 legs["shrink"]["catchup_ms"], unit="ms", extra=extra)


def bench_kvtier(model: str, n_tokens: int) -> int:
    """Tiered KV store under heavy slot oversubscription + migration.

    Phase 1 — park/resume: FEI_TPU_BENCH_OVERSUB (default 10) sessions
    per slot hammer a deliberately tight paged pool with the host tier
    on (FEI_TPU_KV_TIER, default ram), so the scheduler constantly parks
    and resumes sequences. The acceptance shape is in the extras:
    ``preempted_tokens_recomputed`` stays flat (streamed resume, not
    re-prefill) while ``kv.pages_restored`` climbs with the preemption
    count; every stream must deliver its full token budget (zero lost
    tokens). Park/resume latency comes from the kv_spill/kv_fetch span
    histograms.

    Phase 2 — migration: a warm replica exports its session blob; the
    TTFT of the same prompt on a cold replica (re-prefill) vs on a cold
    replica that imported the blob first is the affinity-miss cost
    before/after migration."""
    import threading

    from fei_tpu.engine.engine import GenerationConfig
    from fei_tpu.utils.metrics import METRICS

    os.environ.setdefault("FEI_TPU_KV_TIER", "ram")
    oversub = max(2, int(os.environ.get("FEI_TPU_BENCH_OVERSUB", "10")))
    budget = min(n_tokens, 24)
    batch = 2

    # tight pool: room for ~1.5 active sequences so concurrent streams
    # must park; page_size 4 keeps page counts meaningful at tiny scale
    engine = _make_engine(
        model, max_seq_len=256, paged=True, batch_size=batch, page_size=4,
        num_pages=14, prefix_cache=True,
    )
    sched = engine.scheduler
    sessions = batch * oversub
    base_prompt = _prompt(engine)[:18]
    prompts = [list(base_prompt[:-1]) + [i + 2] for i in range(sessions)]
    gen = GenerationConfig(max_new_tokens=budget, temperature=0.0,
                           ignore_eos=True)

    c0 = METRICS.snapshot()["counters"]
    log(f"bench: kvtier parking {sessions} sessions on {batch} slots "
        f"({oversub}x oversubscription)...")
    results: list = [None] * sessions
    t0 = time.perf_counter()
    seqs = [sched.submit(p, gen) for p in prompts]

    def drain(i):
        results[i] = list(sched.drain(seqs[i]))

    threads = [threading.Thread(target=drain, args=(i,))
               for i in range(sessions)]
    [t.start() for t in threads]
    [t.join(timeout=600) for t in threads]
    dt = time.perf_counter() - t0
    lost = sum(1 for r in results if not r or len(r) != budget)
    total_tokens = sum(len(r or []) for r in results)
    snap = METRICS.snapshot()
    c1, hist = snap["counters"], snap["histograms"]

    def delta(name: str) -> float:
        return float(c1.get(name, 0)) - float(c0.get(name, 0))

    extra: dict = {
        "oversubscription": oversub,
        "sessions": sessions,
        "lost_streams": lost,
        "preemptions": delta("scheduler.preemptions"),
        "preempted_tokens_recomputed": delta(
            "scheduler.preempted_tokens_recomputed"),
        "kv_spills": delta("kv.spills"),
        "kv_pages_restored": delta("kv.pages_restored"),
        "kv_fetch_fallbacks": delta("kv.fetch_fallbacks"),
        "park_p50_ms": round(
            hist.get("kv_spill_seconds", {}).get("p50", 0.0) * 1000, 2),
        "park_p99_ms": round(
            hist.get("kv_spill_seconds", {}).get("p99", 0.0) * 1000, 2),
        "resume_p50_ms": round(
            hist.get("kv_fetch_seconds", {}).get("p50", 0.0) * 1000, 2),
        "resume_p99_ms": round(
            hist.get("kv_fetch_seconds", {}).get("p99", 0.0) * 1000, 2),
    }
    log(f"bench: kvtier oversubscription done in {dt:.1f}s: "
        f"{total_tokens} tokens, preemptions={extra['preemptions']:.0f}, "
        f"recomputed={extra['preempted_tokens_recomputed']:.0f}, "
        f"pages_restored={extra['kv_pages_restored']:.0f}, lost={lost}")
    engine.close()

    # -- phase 2: affinity-miss TTFT, before vs after migration -------------
    from fei_tpu.agent.providers import JaxLocalProvider
    from fei_tpu.ui.server import ServeAPI

    def make_api():
        # pool wide enough for several full sessions: phase 2 measures
        # admission latency, not pressure — evictions here would hand the
        # export a partial prefix
        eng = _make_engine(
            model, max_seq_len=256, paged=True, batch_size=batch,
            page_size=4, num_pages=192, prefix_cache=True,
        )
        return ServeAPI(JaxLocalProvider(engine=eng), model_name="kvtier")

    # probe and decoys: same length (identical prefill/import shapes, so
    # one compiles the programs the other then times) but differing from
    # the FIRST content byte, so the only prefix a decoy can seed for the
    # probe is the shared chat-template pages
    def _body(fill: str) -> dict:
        return {
            "messages": [{"role": "user", "content":
                          fill * 160 + " :kvtier migration probe"}],
            "max_tokens": 1, "temperature": 0,
        }

    body, decoy, decoy2 = _body("x"), _body("y"), _body("z")

    def ttft_ms(api, req=None) -> float:
        t0 = time.perf_counter()
        status, payload = api.handle(
            "POST", "/v1/chat/completions", dict(req or body), {})[:2]
        if status != 200:
            raise RuntimeError(f"kvtier migration probe failed: {payload}")
        return (time.perf_counter() - t0) * 1000

    def export_blob(api, req) -> str:
        status, exported = api.handle(
            "POST", "/kv/export", {"messages": req["messages"]}, {})[:2]
        if status != 200:
            raise RuntimeError(f"kvtier export failed: {exported}")
        return exported["blob"]

    warm = make_api()
    ttft_ms(warm)               # warms the prefix cache on the source
    blob = export_blob(warm, body)
    ttft_ms(warm, decoy)
    decoy_blob = export_blob(warm, decoy)
    # jit compile caches are PER ENGINE: each timed replica must amortize
    # its own admission programs, via untimed same-shape decoy sessions,
    # before its probe is timed — or one probe eats a one-time compile the
    # other doesn't. The cold replica needs TWO decoys: the first runs a
    # clean-cache full prefill, the second the partial template-prefix-hit
    # geometry the probe will actually take.
    cold = make_api()
    ttft_ms(cold, decoy)
    ttft_ms(cold, decoy2)
    cold_ms = ttft_ms(cold)     # affinity miss, no migration: re-prefill
    migrated = make_api()

    def import_blob(api, b) -> dict:
        status, imported = api.handle(
            "POST", "/kv/import", {"blob": b}, {})[:2]
        if status != 200 or not imported.get("pages"):
            raise RuntimeError(f"kvtier import failed: {imported}")
        return imported

    import_blob(migrated, decoy_blob)
    ttft_ms(migrated, decoy)    # untimed: compiles the prefix-hit path
    imported = import_blob(migrated, blob)
    migrated_ms = ttft_ms(migrated)  # affinity miss repaired by migration
    for api in (warm, cold, migrated):
        api.provider.engine.close()
    extra["affinity_miss_cold_ttft_ms"] = round(cold_ms, 1)
    extra["affinity_miss_migrated_ttft_ms"] = round(migrated_ms, 1)
    extra["migration_pages"] = int(imported["pages"])
    extra["migration_ttft_speedup"] = (
        round(cold_ms / migrated_ms, 2) if migrated_ms > 0 else None
    )
    log(f"bench: kvtier affinity-miss ttft cold={cold_ms:.1f}ms "
        f"migrated={migrated_ms:.1f}ms "
        f"(pages={extra['migration_pages']})")
    gauges = METRICS.snapshot()["gauges"]
    extra["kv_tier_bytes_ram"] = int(gauges.get("kv.tier_bytes_ram", 0))
    extra["kv_tier_bytes_disk"] = int(gauges.get("kv.tier_bytes_disk", 0))
    return _emit(f"{_tag(model)}_kvtier_oversub_agg_tok_s",
                 total_tokens / dt, unit="tok/s", extra=extra)


def bench_kvcdn(model: str, n_tokens: int) -> int:
    """Content-addressed prefix store (KV CDN) flops-saved + pre-warm.

    Phase 1 — dedup under a Zipfian session mix: FEI_TPU_BENCH_SESSIONS
    (default 28) sessions sample a handful of shared "repo" contexts with
    Zipf weights (a few hot repos dominate, a long tail barely repeats) —
    the shape fleet prompt traffic actually has. Headline is the prefill
    flops saved: 1 - scheduler.prefill_tokens / total prompt tokens
    (prefix + content-addressed hits are tokens never re-prefilled), with
    ``kv.dedup_ratio`` — N sessions per hot repo, ONE tier copy — riding
    first-class in the extras.

    Phase 2 — rolling-restart TTFT: a two-replica fleet serves a hot
    prompt, then rolls. Speculative pre-warm pushes the hot blob into
    each fresh engine before sessions return, so the post-restart TTFT
    of the hot prompt (admitted over fetched bytes) is compared against
    the TTFT of a same-length NEVER-seen prompt on the very same
    restarted replica — exactly what the restart would have cost every
    prompt without the CDN. Both probes amortize their jit compiles via
    untimed same-shape decoy sessions first (see bench_kvtier)."""
    import random

    from fei_tpu.agent.providers import JaxLocalProvider
    from fei_tpu.ui.server import ServeAPI
    from fei_tpu.utils.metrics import METRICS

    os.environ.setdefault("FEI_TPU_KV_TIER", "ram")
    sessions = max(8, int(os.environ.get("FEI_TPU_BENCH_SESSIONS", "28")))
    repos = 6

    def make_api(tag: str):
        # pool wide enough that every repo context stays resident in the
        # prefix cache — this suite measures dedup and fetch, not the
        # eviction churn bench_kvtier owns
        eng = _make_engine(
            model, max_seq_len=512, paged=True, batch_size=2,
            page_size=4, num_pages=512, prefix_cache=True,
        )
        return ServeAPI(JaxLocalProvider(engine=eng), model_name=tag)

    def chat(api, body) -> dict:
        status, payload = api.handle(
            "POST", "/v1/chat/completions", dict(body), {})[:2]
        if status != 200:
            raise RuntimeError(f"kvcdn bench request failed: {payload}")
        return payload

    # -- phase 1: Zipfian repo mix on one engine ----------------------------
    ctx = [
        ("Repository %02d context: module layout, paging design, "
         "scheduler admission flow, tier spill policy, router affinity. "
         % r) * 2
        for r in range(repos)
    ]
    rng = random.Random(0)
    weights = [1.0 / (r + 1) for r in range(repos)]  # Zipf s=1
    picks = rng.choices(range(repos), weights=weights, k=sessions)

    api = make_api("kvcdn")
    c0 = METRICS.snapshot()["counters"]
    prompt_tokens = 0
    t0 = time.perf_counter()
    for i, r in enumerate(picks):
        out = chat(api, {
            "messages": [{"role": "user", "content": ctx[r]}],
            "max_tokens": 4, "temperature": 0, "session": f"cdn-{i}",
        })
        prompt_tokens += int(out.get("usage", {}).get("prompt_tokens", 0))
    dt = time.perf_counter() - t0
    snap = METRICS.snapshot()
    c1, gauges = snap["counters"], snap["gauges"]

    def delta(name: str) -> float:
        return float(c1.get(name, 0)) - float(c0.get(name, 0))

    prefilled = delta("scheduler.prefill_tokens")
    flops_saved = (
        100.0 * (1.0 - prefilled / prompt_tokens) if prompt_tokens else 0.0
    )
    extra: dict = {
        "sessions": sessions,
        "repos": repos,
        "prompt_tokens": int(prompt_tokens),
        "prefill_tokens": int(prefilled),
        "kv_cas_stores": delta("kv.cas_stores"),
        "kv_cas_dedup_hits": delta("kv.cas_dedup_hits"),
        "kv_dedup_ratio": round(float(gauges.get("kv.dedup_ratio", 0)), 3),
        "kv_prefix_tokens_saved": delta("kv.prefix_tokens_saved"),
    }
    log(f"bench: kvcdn zipf mix done in {dt:.1f}s: "
        f"{sessions} sessions / {repos} repos, "
        f"prefilled {int(prefilled)}/{prompt_tokens} prompt tokens "
        f"-> {flops_saved:.1f}% prefill flops saved, "
        f"dedup_ratio={extra['kv_dedup_ratio']}")
    api.provider.engine.close()

    # -- phase 2: rolling restart, pre-warmed vs never-seen TTFT ------------
    import tempfile

    from fei_tpu.fleet import InProcessReplica, Router

    # long probes: at tiny scale a short prompt's prefill is too cheap
    # to see against the fetch+scatter cost the CDN pays instead
    def _body(fill: str) -> dict:
        return {
            "messages": [{"role": "user", "content":
                          fill * 400 + " :kvcdn restart probe"}],
            "max_tokens": 1, "temperature": 0,
        }

    hot, decoy, decoy2, cold = (_body(f) for f in "xyzw")

    replicas = [
        InProcessReplica(
            f"r{i}", factory=lambda: make_api("kvcdn-fleet"),
            drain_dir=tempfile.mkdtemp(prefix=f"fei-bench-kvcdn-r{i}-"),
        )
        for i in range(2)
    ]
    router = Router(replicas, retries=2, backoff_s=0.02, health_ttl_s=0.1)

    def ttft_ms(rep, req) -> float:
        t0 = time.perf_counter()
        status, payload, _ = rep.request(
            "POST", "/v1/chat/completions", dict(req), {})
        if status != 200:
            raise RuntimeError(f"kvcdn restart probe failed: {payload}")
        return (time.perf_counter() - t0) * 1000

    # serve the hot prompt on both replicas (publishes its blob into both
    # tiers) and compile the prefix-hit geometry the warm probe takes;
    # decoy2 is served too so pre-warm carries ITS blob as well — the
    # post-restart decoy2 session then runs the fetch-and-scatter path
    # untimed, amortizing its one-time compile before the hot probe
    for rep in replicas:
        ttft_ms(rep, hot)
        ttft_ms(rep, hot)
        ttft_ms(rep, decoy2)
    warm_ms = ttft_ms(replicas[1], hot)

    c0 = METRICS.snapshot()["counters"]
    report = router.rolling_restart(drain_deadline_s=60.0, wait_s=120.0)
    if not all(v.get("healthy") for v in report.values()):
        raise RuntimeError(f"kvcdn rolling restart failed: {report}")
    c1 = METRICS.snapshot()["counters"]
    prewarm_pushes = (c1.get("router.prewarm_pushes", 0)
                      - c0.get("router.prewarm_pushes", 0))

    # fresh engines: amortize compiles untimed — full prefill (decoy),
    # then a pre-warmed CAS admission (decoy2: fetch, scatter, and the
    # chunked prefix-hit geometry the hot probe will take)
    probe_rep = replicas[1]
    ttft_ms(probe_rep, decoy)
    ttft_ms(probe_rep, decoy2)
    c0 = METRICS.snapshot()["counters"]
    prewarmed_ms = ttft_ms(probe_rep, hot)   # admits over pre-warmed bytes
    c1 = METRICS.snapshot()["counters"]
    cas_admitted = (c1.get("kv.prefix_hits_tier", 0)
                    - c0.get("kv.prefix_hits_tier", 0)) >= 1
    hot_local_ms = ttft_ms(probe_rep, hot)   # second hit: local prefix
    cold_ms = ttft_ms(probe_rep, cold)       # never-seen: full prefill
    for rep in replicas:
        eng = rep.engine
        if eng is not None:
            eng.close()
    extra.update({
        "restart_prewarm_pushes": int(prewarm_pushes),
        "restart_hot_cas_admitted": bool(cas_admitted),
        "warm_ttft_ms": round(warm_ms, 1),
        "restart_prewarmed_ttft_ms": round(prewarmed_ms, 1),
        "restart_hot_local_ttft_ms": round(hot_local_ms, 1),
        "restart_cold_ttft_ms": round(cold_ms, 1),
        "restart_ttft_speedup": (
            round(cold_ms / prewarmed_ms, 2) if prewarmed_ms > 0 else None
        ),
    })
    log(f"bench: kvcdn restart ttft prewarmed={prewarmed_ms:.1f}ms "
        f"cold={cold_ms:.1f}ms warm-baseline={warm_ms:.1f}ms "
        f"(prewarm_pushes={int(prewarm_pushes)}, "
        f"cas_admitted={cas_admitted})")
    return _emit(f"{_tag(model)}_kvcdn_prefill_flops_saved_pct",
                 flops_saved, unit="%", extra=extra)


def bench_agent(model: str, n_tokens: int) -> int:
    """End-to-end `fei --message` shape (BASELINE config #3): chat template
    -> jax_local provider -> engine stream -> incremental detokenize ->
    agent bookkeeping. Reports effective tok/s through the WHOLE stack, so
    the delta vs the decode suite is the framework overhead."""
    import asyncio

    from fei_tpu.agent import Assistant
    from fei_tpu.agent.providers import JaxLocalProvider
    from fei_tpu.tools import ToolRegistry, create_code_tools

    # the tool schema prompt alone is ~3k byte-tokens; give the agent shape
    # the full context the serving config uses
    message = "Summarize what a Maildir filename encodes and why renames are atomic."

    def build():
        # the tool schema prompt alone is ~3k byte-tokens; give the agent
        # shape the full context the serving config uses
        engine = _make_engine(model, max_seq_len=8192)
        registry = ToolRegistry()
        create_code_tools(registry)
        provider = JaxLocalProvider(
            engine=engine, gen_overrides={"ignore_eos": True}
        )

        def turn():
            assistant = Assistant(
                provider=provider, tool_registry=registry, max_tokens=n_tokens
            )
            provider.last_ttft_s = None  # record THIS turn's first round
            t0 = time.time()
            asyncio.run(assistant.chat(message))
            dt = time.time() - t0
            # summed across tool rounds by Assistant.chat, so multi-round
            # turns don't under-report
            toks = assistant.last_usage.get("completion_tokens", 0)
            return toks, dt, provider.last_ttft_s

        log("bench: agent warm-up (compile)...")
        turn()
        return turn

    # see bench_decode: the pallas path must never sink the bench
    retry = False
    try:
        turn = build()
    except Exception as exc:  # noqa: BLE001
        log(f"bench: agent warm-up failed ({exc!r}); retrying FEI_TPU_FLASH=0")
        os.environ["FEI_TPU_FLASH"] = "0"
        retry = True
    if retry:
        turn = build()
    # median of the 3 measured runs, same rationale as bench_paged: max()
    # hid run-to-run regressions behind one lucky window (VERDICT r5)
    rates, ttfts = [], []
    for run in range(3):
        toks, dt, ttft = turn()
        rate = toks / dt if dt > 0 else 0.0
        if ttft is not None:
            ttfts.append(ttft)
        log(f"bench: agent run {run}: {toks} tokens in {dt:.1f}s -> "
            f"{rate:.1f} tok/s"
            + (f", ttft={ttft*1000:.1f}ms" if ttft is not None else ""))
        rates.append(rate)
    # the agent hot path decodes through the fused chunked free phase
    # (FEI_TPU_DECODE_CHUNK; engine/fused_decode.py) — report the effective
    # chunk so a dispatch-per-token regression is attributable from the
    # artifact alone (engine.decode_dispatches rides in the METRICS
    # snapshot _emit attaches)
    from fei_tpu.engine.fused_decode import resolve_chunk

    extra = {
        "decode_chunk": resolve_chunk(),
        "runs_tok_s": [round(r, 2) for r in rates],
    }
    if ttfts:
        p50 = sorted(ttfts)[len(ttfts) // 2]
        log(f"bench: agent p50 ttft={p50*1000:.1f}ms (first visible token "
            "through template+provider+engine)")
        extra["ttft_ms"] = round(p50 * 1000, 1)
    return _emit(
        f"{_tag(model)}_agent_e2e_tok_s_per_chip",
        sorted(rates)[len(rates) // 2],
        extra=extra,
    )


def main() -> int:
    suite = os.environ.get("FEI_TPU_BENCH_SUITE", "decode")
    if suite == "federation" and os.environ.get("FEI_TPU_FED_READY") != "1":
        # the federation suite needs a multi-device mesh: re-exec onto the
        # 4-device virtual CPU mesh BEFORE jax initializes any backend
        os.environ["FEI_TPU_FED_READY"] = "1"
        os.environ["JAX_PLATFORMS"] = "cpu"
        import re as _re

        flags = os.environ.get("XLA_FLAGS", "")
        flag = "--xla_force_host_platform_device_count=4"
        if "xla_force_host_platform_device_count" in flags:
            # a pre-existing smaller count would leave the suite unable to
            # build its 4-node mesh — override, don't trust
            flags = _re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
        os.execv(sys.executable, [sys.executable] + sys.argv)
    if (
        suite in ("sharded", "reshard")
        and os.environ.get("FEI_TPU_SHARDED_READY") != "1"
        and os.environ.get("JAX_PLATFORMS", "") == "cpu"
    ):
        # the CPU rehearsal of the mesh ladder needs an 8-device host
        # mesh BEFORE jax initializes (same re-exec dance as federation);
        # the reshard suite only needs 2 for its tp2 source leg; on a
        # real TPU backend both just use the visible chips
        os.environ["FEI_TPU_SHARDED_READY"] = "1"
        import re as _re

        flags = os.environ.get("XLA_FLAGS", "")
        count = 8 if suite == "sharded" else 2
        flag = f"--xla_force_host_platform_device_count={count}"
        if "xla_force_host_platform_device_count" in flags:
            flags = _re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
        os.execv(sys.executable, [sys.executable] + sys.argv)
    if suite == "moe":
        default_model = "moe-2b"
    elif suite == "kvtier":
        # park/resume churn is about pool pressure, not model weight
        default_model = "tiny"
    elif suite == "kvcdn":
        # content-addressed dedup/pre-warm is about prefix bytes moving,
        # not model weight
        default_model = "tiny"
    elif suite == "fleet":
        # two engines in one process: tiny keeps the burst about QoS
        # shape, not model weight; override with FEI_TPU_BENCH_MODEL
        default_model = "tiny"
    elif suite == "reshard":
        # five engine boots across two meshes: the cost being measured
        # is recovery machinery, not model weight
        default_model = "tiny"
    elif suite == "decode":
        # BASELINE config #2 gate scale: Llama-3-8B on ONE chip. int8
        # weight-only (~8 GB) is what makes 8B + KV fit the 16 GB v5e;
        # export FEI_TPU_BENCH_QUANT= (empty) to opt out explicitly.
        default_model = "llama3-8b"
    else:
        default_model = "llama3-1b"
    model = os.environ.get("FEI_TPU_BENCH_MODEL", default_model)
    if (
        suite in ("decode", "prefill")
        and model == "llama3-8b"
        and "FEI_TPU_BENCH_QUANT" not in os.environ
    ):
        os.environ["FEI_TPU_BENCH_QUANT"] = "int8"
    n_tokens = int(os.environ.get("FEI_TPU_BENCH_TOKENS", "256"))
    if suite == "remote":
        # client-path baseline: no device backend involved at all
        return bench_remote(min(n_tokens, 256))
    from fei_tpu.utils.platform import honor_jax_platforms

    # the container's sitecustomize pins the axon TPU platform and ignores
    # the env var; honor it explicitly so CPU smoke runs work
    honor_jax_platforms()
    if suite == "federation":
        return bench_federation(n_tokens)
    backend, devices = _touch_backend_or_reexec()
    if os.environ.get("FEI_TPU_BENCH_CPU_FALLBACK"):
        model = os.environ["FEI_TPU_BENCH_MODEL"]  # shrunk to 'tiny'
        n_tokens = min(n_tokens, 32)
    elif backend == "tpu":
        # a real chip measurement: persist it so later outages still report
        # it (see _record_onchip)
        os.environ["FEI_TPU_BENCH_ONCHIP"] = "1"
    log(f"bench: suite={suite} model={model} backend={backend} devices={devices}")

    if suite == "prefill":
        return bench_prefill(model, n_tokens)
    if suite == "paged":
        return bench_paged(model, n_tokens)
    if suite == "ragged":
        return bench_ragged(model, n_tokens)
    if suite == "sharded":
        return bench_sharded(model, n_tokens)
    if suite == "moe":
        return bench_moe(model, n_tokens)
    if suite == "fleet":
        return bench_fleet(model, n_tokens)
    if suite == "crash":
        return bench_crash(model, n_tokens)
    if suite == "reshard":
        return bench_reshard(model, n_tokens)
    if suite == "kvtier":
        return bench_kvtier(model, n_tokens)
    if suite == "kvcdn":
        return bench_kvcdn(model, n_tokens)
    if suite == "agent":
        return bench_agent(model, n_tokens)
    return bench_decode(model, n_tokens)


if __name__ == "__main__":
    sys.exit(main())
