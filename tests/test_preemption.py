"""KV-page exhaustion survival: preempt-and-resume, drain, warm restart.

The claims under test (docs/ENGINE.md "Memory pressure & preemption"):
- page exhaustion is a scheduling event, never a request failure: when
  the pool cannot cover an admission or mid-decode growth, the scheduler
  preempts the least-progressed victim (never the requester, never a
  freshly-admitted shielded slot), releases its pages, and requeues it;
- a resumed stream is BYTE-IDENTICAL to an unpreempted run — greedy and
  seeded — with no duplicated or dropped tokens (re-prefill of
  prompt + generated[:-1], the slot's PRNG key captured at preemption
  and re-installed at re-admission);
- lazily-admitted sequences grow their reservation mid-decode through
  the same pressure-aware path, self-preempting (deferred, not failed)
  when no victim exists;
- the allocator's refcounts survive the churn: registry-pinned prefix
  pages outlive a victim's release, and a failed try_alloc has no
  partial effects;
- graceful drain sheds new submits with a typed Retry-After error,
  snapshots whatever the deadline strands, and a warm restart re-admits
  every snapshot and replays byte-identically — zero accepted requests
  lost.

A 4-token page over a ~13-page pool makes two worst-case reservations
collide, so preemption triggers organically — no sleeps, no fault
arming needed (the pool.alloc fault point is exercised separately).
"""

from __future__ import annotations

import json
import os
import threading

import pytest

from fei_tpu.engine.checkpoint import (
    CheckpointError,
    clear_request_snapshots,
    load_request_snapshots,
    save_request_snapshots,
)
from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.faults import FAULTS
from fei_tpu.engine.paged_cache import PageAllocator
from fei_tpu.utils.errors import EngineDrainingError, EngineError
from fei_tpu.utils.metrics import METRICS

PROMPTS = [list(range(11 + i, 29 + i)) for i in range(4)]
PROMPT = PROMPTS[0]


def _counter(name: str) -> float:
    return METRICS.snapshot()["counters"].get(name, 0)


def _gauge(name: str) -> float:
    return METRICS.snapshot()["gauges"].get(name, 0)


def _gen(**kw) -> GenerationConfig:
    kw.setdefault("max_new_tokens", 24)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("ignore_eos", True)
    return GenerationConfig(**kw)


def _make(**kwargs) -> InferenceEngine:
    return InferenceEngine.from_config(
        "tiny", paged=True, batch_size=kwargs.pop("batch_size", 2), **kwargs
    )


def _tight(**kwargs) -> InferenceEngine:
    """A pool two worst-case reservations cannot share: page_size=4 puts
    one 18-token-prompt 24-token-budget request at ceil(42/4) = 11 pages;
    num_pages=14 leaves 13 allocatable (page 0 is the null page)."""
    kwargs.setdefault("page_size", 4)
    kwargs.setdefault("num_pages", 14)
    kwargs.setdefault("prefix_cache", True)
    return _make(**kwargs)


def _run_concurrent(sched, prompts, gen):
    """Drain one stream per prompt concurrently; returns (tokens, seq)
    per prompt so tests can inspect the request traces afterwards."""
    gens = gen if isinstance(gen, list) else [gen] * len(prompts)
    seqs = [sched.submit(p, g) for p, g in zip(prompts, gens)]
    results: list = [None] * len(prompts)

    def go(i):
        results[i] = list(sched.drain(seqs[i]))

    ts = [threading.Thread(target=go, args=(i,)) for i in range(len(prompts))]
    [t.start() for t in ts]
    [t.join(timeout=300) for t in ts]
    assert all(r is not None for r in results), "a stream never finished"
    return list(zip(results, seqs))


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


class TestVictimPolicy:
    """_pick_victim: min progress toward budget, requester and shielded
    slots excluded."""

    def _sched_with_slots(self, seqs):
        eng = _make()
        sched = eng.scheduler
        for i, s in enumerate(seqs):
            sched._slots[i] = s
        return sched

    def test_least_progress_loses(self):
        from fei_tpu.engine.scheduler import _Seq

        a = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None, stops=set(), budget=24)
        a.generated = [1] * 12  # 50%
        b = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None, stops=set(), budget=100)
        b.generated = [1] * 10  # 10% — least progress despite more tokens
        sched = self._sched_with_slots([a, b])
        assert sched._pick_victim(exclude=None) is b
        assert sched._pick_victim(exclude=b) is a

    def test_shielded_and_finished_never_picked(self):
        from fei_tpu.engine.scheduler import _Seq

        a = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None, stops=set(), budget=24)
        a.shield = True  # admitted, no dispatch survived yet
        b = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None, stops=set(), budget=24)
        b.finished = True
        sched = self._sched_with_slots([a, b])
        assert sched._pick_victim(exclude=None) is None

    def test_policy_env_validated(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_PREEMPT_POLICY", "meteor")
        with pytest.raises(EngineError):
            _make()


class TestAllocatorUnderPreemption:
    """Refcount invariants the preemption churn leans on."""

    def test_try_alloc_exhaustion_has_no_partial_effects(self):
        alloc = PageAllocator(num_pages=4, page_size=4)  # 3 allocatable
        assert alloc.try_alloc(0, 2) is not None
        free0 = alloc.free_pages
        assert alloc.try_alloc(1, 2) is None
        assert alloc.free_pages == free0
        assert alloc.pages_for(1) == []
        # fragmentation in contiguous mode is also a clean None
        assert alloc.try_alloc(1, 1) is not None
        assert alloc.free_pages == 0

    def test_pinned_prefix_pages_survive_victim_release(self):
        alloc = PageAllocator(num_pages=8, page_size=4)
        pages = alloc.alloc(0, 4)
        alloc.take_ref(pages[:2])  # the prefix registry's pin
        alloc.free(0)  # the victim's preemption releases its refs
        for p in pages[:2]:
            assert alloc.refcount(p) == 1  # registry ref survives
        for p in pages[2:]:
            assert alloc.refcount(p) == 0
        assert alloc.free_pages == 5
        # a new sequence can share the pinned pages (resume's prefix hit)
        alloc.share(1, pages[:2])
        assert [alloc.refcount(p) for p in pages[:2]] == [2, 2]
        alloc.drop_ref(pages[:2])  # registry eviction
        alloc.free(1)
        assert alloc.free_pages == 7  # everything returned exactly once

    def test_exhaustion_raises_on_the_legacy_path(self):
        alloc = PageAllocator(num_pages=4, page_size=4)
        with pytest.raises(EngineError, match="exhausted"):
            alloc.alloc(0, 99)

    def test_pool_gauges_track_alloc_free(self):
        alloc = PageAllocator(num_pages=8, page_size=4)
        assert _gauge("pool.pages_total") == 7
        assert _gauge("pool.pages_free") == 7
        alloc.alloc(0, 3)
        assert _gauge("pool.pages_in_use") == 3
        alloc.free(0)
        assert _gauge("pool.pages_free") == 7


class TestPreemptResume:
    def test_tight_pool_greedy_byte_identical(self):
        # reference on the SAME page geometry (page_size=4) with a page
        # count no reservation can exhaust: page size changes the attention
        # summation order, so a roomy-default reference is only argmax-
        # equal, not bit-equal — the claim here is that PRESSURE (preempt/
        # resume) changes nothing, so only the page count may differ
        gen = _gen()
        roomy = _tight(num_pages=64)
        # a chunk smaller than the prompt sends every admission — fresh
        # AND resumed — through the same chunked-paged prefill programs;
        # the default direct dense prefill is a different fused program
        # that rounds ~1 bf16 ulp apart, which matters only when a
        # preempted prompt must be recomputed after prefix-cache eviction
        roomy.scheduler.prefill_chunk = 8
        refs = [list(roomy.scheduler.stream(p, gen)) for p in PROMPTS]
        roomy.scheduler.close()

        p0 = _counter("scheduler.preemptions")
        eng = _tight()
        eng.scheduler.prefill_chunk = 8
        results = _run_concurrent(eng.scheduler, PROMPTS, gen)
        for i, (toks, _) in enumerate(results):
            assert toks == refs[i], f"stream {i} diverged after preemption"
        assert _counter("scheduler.preemptions") > p0
        assert _counter("scheduler.preempted_tokens_recomputed") > 0
        # a preempted request's trace shows the round trip, in order
        phases = [
            [p for p, _ in seq.trace.events] for _, seq in results
        ]
        preempted = [ph for ph in phases if "preempted" in ph]
        assert preempted, "no trace recorded a preemption"
        for ph in preempted:
            assert "resumed" in ph
            assert ph.index("resumed") > ph.index("preempted")
            assert ph[-1] == "completed"

    def test_tight_pool_seeded_byte_identical(self):
        """The PRNG-key capture/restore proof: seeded sampling resumes on
        the exact key the next step would have split. The reference runs
        on the same page geometry (see the greedy test) — seeded top-k is
        where a page-size-induced float reorder actually flips tokens."""
        gens = [
            _gen(temperature=1.0, top_k=40, seed=100 + i) for i in range(2)
        ]
        roomy = _tight(num_pages=64)
        roomy.scheduler.prefill_chunk = 8  # same programs as the resume
        refs = [
            list(roomy.scheduler.stream(p, g))
            for p, g in zip(PROMPTS[:2], gens)
        ]
        roomy.scheduler.close()

        p0 = _counter("scheduler.preemptions")
        eng = _tight()
        eng.scheduler.prefill_chunk = 8
        results = _run_concurrent(eng.scheduler, PROMPTS[:2], gens)
        for i, (toks, _) in enumerate(results):
            assert toks == refs[i], f"seeded stream {i} diverged"
        assert _counter("scheduler.preemptions") > p0

    @pytest.mark.slow  # pipeline `preemption` stage; tier-1 keeps the
    # byte-identity + warm-restart pins within the fast-lane budget
    def test_lazy_reservation_grows_mid_decode(self):
        """A short request + a long one on a pool that fits the short
        one's worst case plus only the long one's LAZY reservation: the
        long request admits lazily and grows into the pages the short
        one frees — no preemption needed, nothing fails."""
        p0 = _counter("scheduler.preemptions")
        g0 = _counter("scheduler.lazy_grown_pages")
        roomy = _make()
        ref_short = list(roomy.scheduler.stream(PROMPTS[0], _gen(max_new_tokens=4)))
        ref_long = list(roomy.scheduler.stream(PROMPTS[1], _gen()))
        roomy.scheduler.close()

        eng = _tight(prefix_cache=False)  # exact page accounting
        sched = eng.scheduler
        # short first: full worst case ceil(22/4)=6 of 13; the long one's
        # full 11 > 7 remaining, its lazy ceil(27/4)=7 <= 7 — admits lazy
        results = _run_concurrent(
            sched, PROMPTS[:2],
            [_gen(max_new_tokens=4), _gen()],
        )
        assert results[0][0] == ref_short
        assert results[1][0] == ref_long
        assert _counter("scheduler.lazy_grown_pages") > g0
        assert _counter("scheduler.preemptions") == p0

    @pytest.mark.slow
    def test_fault_forced_preemption_on_roomy_pool(self):
        """pool.alloc exhausted:4 walks the hybrid ladder end-to-end on a
        pool with plenty of pages: full reservation fails, lazy evicts
        then preempts, and still no request fails."""
        gen = _gen(max_new_tokens=8)
        roomy = _make(prefix_cache=True)
        refs = [list(roomy.scheduler.stream(p, gen)) for p in PROMPTS[:2]]
        roomy.scheduler.close()

        eng = _make(prefix_cache=True)
        sched = eng.scheduler
        held = sched.submit(PROMPTS[0], gen)  # a running victim candidate
        FAULTS.arm(
            "pool.alloc", "exhausted", count=4,
            match=lambda ctx: ctx["seq"].prompt_ids == PROMPTS[1],
        )
        toks1 = list(sched.stream(PROMPTS[1], gen))
        assert FAULTS.fired("pool.alloc") == 4
        assert toks1 == refs[1]
        # the other request (preempted or not) finished byte-identically
        assert list(sched.drain(held)) == refs[0]

    @pytest.mark.slow
    def test_policy_off_blocks_instead_of_preempting(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_PREEMPT_POLICY", "off")
        gen = _gen()
        # same page geometry as the pressured pool (see the greedy test)
        roomy = _tight(num_pages=64)
        refs = [list(roomy.scheduler.stream(p, gen)) for p in PROMPTS]
        roomy.scheduler.close()

        p0 = _counter("scheduler.preemptions")
        eng = _tight()
        results = _run_concurrent(eng.scheduler, PROMPTS, gen)
        for i, (toks, _) in enumerate(results):
            assert toks == refs[i]
        # legacy behavior: admissions waited for pages, nobody was evicted
        assert _counter("scheduler.preemptions") == p0

    @pytest.mark.slow
    def test_single_request_never_preempts(self):
        p0 = _counter("scheduler.preemptions")
        eng = _tight()
        toks = list(eng.scheduler.stream(PROMPT, _gen()))
        assert len(toks) == 24
        assert _counter("scheduler.preemptions") == p0

    def test_infeasible_request_still_rejected_up_front(self):
        eng = _tight()
        with pytest.raises(EngineError):
            eng.scheduler.submit(PROMPT, _gen(max_new_tokens=4096))


class TestDrainRestart:
    def test_drain_sheds_new_submits_with_retry_after(self):
        eng = _make()
        eng.begin_drain(deadline_s=5)
        assert eng.scheduler.wait_drained(timeout=10)
        assert _gauge("engine.draining") == 1
        with pytest.raises(EngineDrainingError) as e:
            eng.scheduler.submit(PROMPT, _gen())
        assert e.value.retry_after_s > 0

    def test_queued_requests_snapshot_and_warm_restart_replays(
        self, monkeypatch, tmp_path
    ):
        """The zero-loss proof, fully deterministic: requests parked in
        the queue drain to disk, a FRESH engine re-admits them, and each
        replays byte-identically to an undrained run."""
        gen = _gen()
        roomy = _make(prefix_cache=True)
        refs = [list(roomy.scheduler.stream(p, gen)) for p in PROMPTS[:2]]
        roomy.scheduler.close()

        eng = _make()
        sched = eng.scheduler
        monkeypatch.setattr(sched, "_start_thread", lambda: None)  # park
        seqs = [sched.submit(p, gen) for p in PROMPTS[:2]]
        s0 = _counter("scheduler.requests_snapshotted")
        eng.begin_drain(deadline_s=0, snapshot_dir=str(tmp_path))
        assert sched.wait_drained(timeout=10)
        assert _counter("scheduler.requests_snapshotted") == s0 + 2
        for s in seqs:
            assert s.trace.status == "snapshotted"
            # the old process's waiter gets a typed, Retry-After error
            with pytest.raises(EngineDrainingError):
                list(sched.drain(s))

        snaps = load_request_snapshots(str(tmp_path))
        assert len(snaps) == 2
        eng2 = _make(prefix_cache=True)
        restored = eng2.warm_restart(str(tmp_path))
        assert len(restored) == 2
        # at-most-once: the snapshot file is consumed
        assert load_request_snapshots(str(tmp_path)) == []
        assert eng2.warm_restart(str(tmp_path)) == []
        outs = [list(eng2.scheduler.drain(s)) for s in restored]
        assert outs == refs

    @pytest.mark.slow
    def test_mid_decode_drain_loses_nothing(self, tmp_path):
        """Drain while a request is actively decoding: whatever the
        deadline strands snapshots, and delivered-before + replayed-after
        reconstructs the exact reference stream."""
        gen = _gen(max_new_tokens=64)
        roomy = _make()
        # chunked-paged prefill everywhere (see the greedy byte-identity
        # test): the fresh engine's restart re-prefills the prompt through
        # the chunked programs, so the reference and the drained run must
        # compile the same ones
        roomy.scheduler.prefill_chunk = 8
        ref = list(roomy.scheduler.stream(PROMPT, gen))
        roomy.scheduler.close()

        eng = _make()
        eng.scheduler.prefill_chunk = 8
        sched = eng.scheduler
        seq = sched.submit(PROMPT, gen)
        it = sched.drain(seq)
        before = [next(it) for _ in range(4)]  # decoding is underway
        eng.begin_drain(deadline_s=0, snapshot_dir=str(tmp_path))
        assert sched.wait_drained(timeout=30)
        snapshotted = False
        try:  # collect whatever was delivered up to the snapshot point
            for t in it:
                before.append(t)
        except EngineDrainingError:
            snapshotted = True

        if snapshotted:
            assert seq.trace.status == "snapshotted"
            eng2 = _make()
            eng2.scheduler.prefill_chunk = 8
            restored = eng2.warm_restart(str(tmp_path))
            assert len(restored) == 1
            after = list(eng2.scheduler.drain(restored[0]))
            # the replay re-emits everything delivered pre-drain, then
            # continues: the restored stream IS the full reference
            assert after == ref
            assert after[: len(before)] == before
            assert _counter("scheduler.requests_restored") >= 1
        else:  # the deadline let it finish: complete, not snapshotted
            assert before == ref
            assert load_request_snapshots(str(tmp_path)) == []

    def test_drain_is_idempotent_and_sticky(self):
        eng = _make()
        eng.begin_drain(deadline_s=1)
        eng.begin_drain(deadline_s=99)  # no-op: first drain wins
        assert eng.scheduler.wait_drained(timeout=10)
        assert eng.scheduler.draining()

    def test_constrained_request_fails_typed_at_drain(self, monkeypatch):
        """Grammar automaton state is not host-portable: a constrained
        request cannot snapshot, so drain fails it with the typed
        draining error instead of silently dropping it."""
        eng = _make()
        sched = eng.scheduler
        monkeypatch.setattr(sched, "_start_thread", lambda: None)
        seq = sched.submit(PROMPT, _gen())
        seq.mask_fn = lambda toks: None  # host-masked == constrained
        eng.begin_drain(deadline_s=0)
        assert sched.wait_drained(timeout=10)
        with pytest.raises(EngineDrainingError):
            list(sched.drain(seq))
        assert seq.trace.status == "failed"


class TestCheckpointRoundtrip:
    def test_save_load_clear(self, tmp_path):
        snaps = [{"rid": "req-1", "prompt_ids": [1, 2], "generated": [3]}]
        save_request_snapshots(str(tmp_path), snaps)
        assert load_request_snapshots(str(tmp_path)) == snaps
        clear_request_snapshots(str(tmp_path))
        assert load_request_snapshots(str(tmp_path)) == []
        clear_request_snapshots(str(tmp_path))  # idempotent

    def test_corrupt_file_is_a_typed_error(self, tmp_path):
        (tmp_path / "requests.json").write_text("not json{")
        with pytest.raises(CheckpointError):
            load_request_snapshots(str(tmp_path))

    def test_wrong_version_rejected(self, tmp_path):
        (tmp_path / "requests.json").write_text(
            json.dumps({"version": 999, "requests": []})
        )
        with pytest.raises(CheckpointError):
            load_request_snapshots(str(tmp_path))


class TestServerDrain:
    def test_drain_endpoint_and_health_flip(self):
        from fei_tpu.agent.providers import JaxLocalProvider
        from fei_tpu.ui.server import ServeAPI

        eng = _make()
        api = ServeAPI(JaxLocalProvider(engine=eng), model_name="tiny")
        assert api.handle("GET", "/health", {}, {})[0] == 200

        res = api.handle("POST", "/drain", {"deadline_s": 2}, {})
        assert res[0] == 202 and res[1]["status"] == "draining"
        assert eng.scheduler.wait_drained(timeout=10)

        # /health flips so load balancers eject the replica...
        code, body, hdrs = api.handle("GET", "/health", {}, {})
        assert code == 503 and body["status"] == "draining"
        assert int(hdrs["Retry-After"]) >= 1
        # ...and new chat submits shed 503 + Retry-After
        chat = {"messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4}
        res = api.handle("POST", "/v1/chat/completions", chat, {})
        assert res[0] == 503 and int(res[2]["Retry-After"]) >= 1

    def test_drain_endpoint_validates_deadline(self):
        from fei_tpu.agent.providers import JaxLocalProvider
        from fei_tpu.ui.server import ServeAPI

        eng = _make()
        api = ServeAPI(JaxLocalProvider(engine=eng), model_name="tiny")
        assert api.handle("POST", "/drain", {"deadline_s": "soon"}, {})[0] == 400
