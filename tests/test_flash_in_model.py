"""The model's flash-attention path (FEI_TPU_FLASH=1) must match the XLA
oracle path end-to-end: same prefill logits, same greedy generation."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.engine import GenerationConfig, InferenceEngine
from fei_tpu.models.configs import get_model_config
from fei_tpu.models.llama import KVCache, forward, init_params


@pytest.fixture()
def flash_env(monkeypatch):
    monkeypatch.setenv("FEI_TPU_FLASH", "1")


class TestFlashPath:
    def test_prefill_logits_match(self, flash_env, monkeypatch):
        cfg = get_model_config("tiny", num_layers=2)
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, cfg.vocab_size)

        # highest precision: on TPU the oracle's fp32 matmuls otherwise run
        # as bf16 MXU passes while the Pallas kernel accumulates true fp32,
        # and the 2-layer end-to-end delta blows past any sane tolerance.
        with jax.default_matmul_precision("highest"):
            cache = KVCache.create(cfg, 2, 64, dtype=jnp.float32)
            flash_logits, _ = forward(params, cfg, tokens, cache)

            monkeypatch.setenv("FEI_TPU_FLASH", "0")
            cache = KVCache.create(cfg, 2, 64, dtype=jnp.float32)
            oracle_logits, _ = forward(params, cfg, tokens, cache)

        atol = 5e-3 if jax.default_backend() == "tpu" else 2e-3
        np.testing.assert_allclose(
            np.asarray(flash_logits), np.asarray(oracle_logits), atol=atol
        )

    def test_greedy_generation_matches(self, flash_env, monkeypatch):
        kw = dict(dtype=jnp.float32, seed=0, tokenizer="byte",
                  max_seq_len=128, num_layers=2)
        gen = GenerationConfig(max_new_tokens=16, temperature=0.0, ignore_eos=True)
        prompt_text = "flash parity probe"

        eng = InferenceEngine.from_config("tiny", **kw)
        flash_ids = eng.generate(eng.tokenizer.encode(prompt_text), gen).token_ids

        monkeypatch.setenv("FEI_TPU_FLASH", "0")
        eng = InferenceEngine.from_config("tiny", **kw)
        oracle_ids = eng.generate(eng.tokenizer.encode(prompt_text), gen).token_ids

        assert flash_ids == oracle_ids


class TestTrainingPathStaysDifferentiable:
    def test_grad_with_flash_forced(self, monkeypatch):
        """FEI_TPU_FLASH=1 must not route the cache-free training forward
        through the (VJP-less) Pallas kernel — jax.grad must still work."""
        monkeypatch.setenv("FEI_TPU_FLASH", "1")
        import optax

        cfg = get_model_config("tiny", num_layers=1)
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 96), 0, cfg.vocab_size)

        def loss_fn(p):
            from fei_tpu.models.llama import forward_train

            logits = forward_train(p, cfg, tokens[:, :-1], remat=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tokens[:, 1:]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert jnp.isfinite(loss)
        gnorm = jax.tree.reduce(
            lambda a, b: a + jnp.sum(jnp.abs(b)), grads, 0.0
        )
        assert float(gnorm) > 0
