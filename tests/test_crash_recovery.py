"""Crash consistency end to end: journal replay + fleet resurrection.

The claims under test (docs/ENGINE.md "Crash consistency", docs/FLEET.md
"Mid-stream failover"):
- a process that dies WITHOUT any cooperation (no drain, no close — the
  journal directory is all that survives) gets its in-flight sessions
  re-admitted by ``engine.warm_restart()`` and their streams replay
  BYTE-IDENTICAL to the uninterrupted reference, greedy and seeded
  (the journaled per-step PRNG keys re-enter the sampling chain
  exactly), single-chip and tp2 — and the reboot does NOT need the
  dead process's mesh: journaled sessions are host-side token state,
  so a tp2 journal recovers on a single chip (mesh is provenance;
  page_size is the one geometry axis recovery still refuses, with a
  visible ``engine.recovery_skipped.page_size`` counter);
- the fleet router resurrects a stream whose replica died AFTER tokens
  flowed: the delivered suffix teacher-forces onto a survivor via the
  per-frame ``fei`` extension ledger, the replayed prefix is
  suppressed, and the client sees ONE uninterrupted byte-identical
  stream under one stream id — greedy and seeded;
- with no survivor the failure degrades to the old error-frame
  contract, and tool-grammar turns never resurrect;
- the ``crash`` fault kind is a delay fuse (fires SIGKILL on the Nth
  check), and the snapshot writer fsyncs file and directory.

The real kill -9 over real processes is scripts/crash_smoke.py (the
``chaos_crash`` pipeline stage); here the engine dies by losing
everything except its journal directory, and replicas die by dropping
their transport mid-stream — same recovery surface, hermetic and fast.
"""

from __future__ import annotations

import os
import shutil

import pytest

from conftest import requires_shard_map
from fei_tpu.agent.providers import JaxLocalProvider
from fei_tpu.engine import faults as faults_mod
from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.faults import FAULTS
from fei_tpu.fleet.replica import InProcessReplica
from fei_tpu.fleet.router import Router, _parse_sse
from fei_tpu.ui.server import ServeAPI
from fei_tpu.utils.metrics import METRICS

PROMPT = list(range(1, 19))


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def _counter(name: str) -> float:
    return METRICS.snapshot()["counters"].get(name, 0)


def _gen(**kw) -> GenerationConfig:
    kw.setdefault("max_new_tokens", 24)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("ignore_eos", True)
    return GenerationConfig(**kw)


def _seeded_gen() -> GenerationConfig:
    return _gen(temperature=0.9, top_k=40, seed=7)


def _journal_engine(jdir: str, mesh: str | None = None,
                    sync: str = "batch") -> InferenceEngine:
    """A tiny paged engine with the session journal armed via env (the
    scheduler reads FEI_TPU_JOURNAL_* once, at construction)."""
    overrides = {"FEI_TPU_JOURNAL_DIR": jdir, "FEI_TPU_JOURNAL_SYNC": sync}
    if mesh:
        overrides["FEI_TPU_MESH"] = mesh
    old = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        return InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _mesh_engine(mesh: str) -> InferenceEngine:
    old = os.environ.get("FEI_TPU_MESH")
    os.environ["FEI_TPU_MESH"] = mesh
    try:
        return InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    finally:
        if old is None:
            os.environ.pop("FEI_TPU_MESH", None)
        else:
            os.environ["FEI_TPU_MESH"] = old


@pytest.fixture(scope="module")
def ref_tokens():
    """Uninterrupted greedy + seeded references from a journal-free
    engine (shared by every identity pin in this module)."""
    eng = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    try:
        greedy = list(eng.scheduler.stream(PROMPT, _gen()))
        seeded = list(eng.scheduler.stream(PROMPT, _seeded_gen()))
    finally:
        eng.close()
    return greedy, seeded


def _crash_and_copy(eng, jdir: str, crash_dir: str, n_pull: int = 5):
    """Simulate kill -9: pull a few delivered tokens, then freeze the
    journal directory AS THE DEAD PROCESS LEFT IT (no terminals, no
    drain — copied before any cooperative shutdown runs)."""
    s1 = eng.scheduler.submit(PROMPT, _gen())
    s2 = eng.scheduler.submit(PROMPT, _seeded_gen())
    got1 = [s1.out.get() for _ in range(n_pull)]
    got2 = [s2.out.get() for _ in range(n_pull)]
    assert eng.scheduler._journal.flush()
    shutil.copytree(jdir, crash_dir)
    return got1, got2


class TestJournalReplay:
    def test_byte_identity_after_crash(self, tmp_path, ref_tokens):
        """The tentpole pin: sessions mid-decode when the process died
        resume byte-identically from the journal alone — greedy AND
        seeded concurrently, delivered prefixes replayed exactly once."""
        jdir, crash_dir = str(tmp_path / "wal"), str(tmp_path / "dead")
        eng = _journal_engine(jdir)
        try:
            got1, got2 = _crash_and_copy(eng, jdir, crash_dir)
        finally:
            eng.close()

        ref_greedy, ref_seeded = ref_tokens
        assert got1 == ref_greedy[:len(got1)]
        assert got2 == ref_seeded[:len(got2)]

        c0 = _counter("journal.recovered_sessions")
        eng2 = _journal_engine(crash_dir)
        try:
            restored = eng2.warm_restart()
            assert len(restored) == 2
            outs = [list(eng2.scheduler.drain(s)) for s in restored]
            assert ref_greedy in outs
            assert ref_seeded in outs
            assert _counter("journal.recovered_sessions") - c0 == 2
            # a second restart finds nothing: segments were consumed
            assert eng2.warm_restart() == []
        finally:
            eng2.close()

    def test_recovery_skips_expired_deadline(self, tmp_path):
        from fei_tpu.engine.journal import SessionJournal

        jdir = str(tmp_path / "wal")
        j = SessionJournal(jdir)
        j.admit({"rid": "late", "prompt_ids": PROMPT,
                 "gen": {"max_new_tokens": 4, "ignore_eos": True},
                 "deadline_epoch": 1.0})  # expired decades ago
        assert j.flush()
        j.close()
        c0 = _counter("engine.recovery_skipped.deadline_expired")
        eng = _journal_engine(jdir)
        try:
            assert eng.warm_restart() == []
            # a dropped session must be visible, not silent
            assert _counter(
                "engine.recovery_skipped.deadline_expired"
            ) - c0 == 1
        finally:
            eng.close()

    def test_recovery_crosses_mesh(self, tmp_path):
        """A journaled session from a DIFFERENT mesh re-admits: sessions
        are host-side token state and tp serving is token-identical to
        single-chip, so mesh is provenance — the common TPU shrink (a
        chip dies, the replica re-forms smaller) loses nothing."""
        from fei_tpu.engine.journal import SessionJournal

        jdir = str(tmp_path / "wal")
        j = SessionJournal(jdir)
        j.admit({"rid": "alien", "prompt_ids": PROMPT,
                 "gen": {"max_new_tokens": 4, "ignore_eos": True},
                 "mesh": {"tp": 8}})
        assert j.flush()
        j.close()
        c0 = _counter("engine.cross_mesh_recoveries")
        eng = _journal_engine(jdir)
        try:
            restored = eng.warm_restart()
            assert len(restored) == 1
            assert _counter("engine.cross_mesh_recoveries") - c0 == 1
            toks = list(eng.scheduler.drain(restored[0]))
            assert len(toks) == 4
        finally:
            eng.close()

    def test_recovery_skips_page_size_mismatch(self, tmp_path):
        """page_size is the one geometry axis journal recovery refuses
        (it changes the paged kernel's summation order): the session
        drops with a visible counter instead of replaying wrong."""
        from fei_tpu.engine.journal import SessionJournal

        jdir = str(tmp_path / "wal")
        j = SessionJournal(jdir)
        j.admit({"rid": "coarse", "prompt_ids": PROMPT,
                 "gen": {"max_new_tokens": 4, "ignore_eos": True},
                 "page_size": 999})
        assert j.flush()
        j.close()
        c0 = _counter("engine.recovery_skipped.page_size")
        eng = _journal_engine(jdir)
        try:
            assert eng.warm_restart() == []
            assert _counter(
                "engine.recovery_skipped.page_size"
            ) - c0 == 1
        finally:
            eng.close()


@requires_shard_map
class TestJournalReplayTp2:
    """The same identity proof with decode dispatched through the
    shard_map'd kernel on a 2-way tensor-parallel mesh. Slow lane: the
    tp2 compile dominates tier-1's budget (same policy as
    test_sharded_serving); runs FOR REAL in the chaos_crash stage."""

    @pytest.mark.slow
    def test_tp2_byte_identity_after_crash(self, tmp_path):
        ref_eng = _mesh_engine("tp2")
        try:
            ref_greedy = list(ref_eng.scheduler.stream(PROMPT, _gen()))
            ref_seeded = list(
                ref_eng.scheduler.stream(PROMPT, _seeded_gen())
            )
        finally:
            ref_eng.close()
        jdir, crash_dir = str(tmp_path / "wal"), str(tmp_path / "dead")
        eng = _journal_engine(jdir, mesh="tp2")
        try:
            _crash_and_copy(eng, jdir, crash_dir)
        finally:
            eng.close()
        eng2 = _journal_engine(crash_dir, mesh="tp2")
        try:
            restored = eng2.warm_restart()
            outs = [list(eng2.scheduler.drain(s)) for s in restored]
            assert ref_greedy in outs
            assert ref_seeded in outs
        finally:
            eng2.close()


# -- fleet resurrection ---------------------------------------------------


def _make_api() -> ServeAPI:
    eng = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    return ServeAPI(JaxLocalProvider(engine=eng), model_name="tiny")


def _close_api(api: ServeAPI) -> None:
    api.provider.engine.scheduler.close()


def _content(frames) -> str:
    out = []
    for f in frames:
        info = _parse_sse(f)
        if not info or "error" in info:
            continue
        d = (info.get("choices") or [{}])[0].get("delta") or {}
        if d.get("content"):
            out.append(d["content"])
    return "".join(out)


def _error_frames(frames) -> list[dict]:
    return [dict(info["error"]) for f in frames
            if (info := _parse_sse(f)) and info.get("error")]


class _KillerReplica:
    """Wrap a replica: while armed, its next stream drops the transport
    after ``after`` content frames — what a kill -9 looks like from the
    router's side of the socket."""

    def __init__(self, inner, after: int = 2):
        self.inner = inner
        self.after = after
        self.armed = True

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def stream(self, body, headers=None):
        gen = self.inner.stream(body, headers)
        if not self.armed:
            return gen
        self.armed = False

        def killed():
            n = 0
            for f in gen:
                yield f
                info = _parse_sse(f)
                d = ((info or {}).get("choices") or [{}])[0].get(
                    "delta") or {}
                if d.get("content"):
                    n += 1
                    if n >= self.after:
                        raise ConnectionError("replica died mid-stream")
        return killed()


def _body(seeded: bool) -> dict:
    body = {"messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 24}
    if seeded:
        body.update(temperature=0.9, seed=7)
    else:
        body["temperature"] = 0
    return body


class TestRouterResurrection:
    @pytest.mark.parametrize("seeded", [False, True],
                             ids=["greedy", "seeded"])
    def test_mid_stream_failover_byte_identical(self, seeded):
        """A stream whose replica dies after tokens flowed continues on
        the survivor: same bytes, same stream id, zero duplicated or
        lost content."""
        body = _body(seeded)
        ref_api = _make_api()
        try:
            kw = ref_api._parse_request(dict(body), {})
            ref = _content(ref_api.stream_chat(dict(body), kw))
        finally:
            _close_api(ref_api)
        assert ref  # the reference stream produced text

        a = _KillerReplica(InProcessReplica("a", _make_api()), after=2)
        b = InProcessReplica("b", _make_api())
        router = Router([a, b], retries=2, backoff_s=0.0, health_ttl_s=0.0)
        c0 = _counter("router.resurrections")
        t0 = _counter("router.resurrection_replayed_tokens")
        try:
            frames = list(router.stream_chat(dict(body), {}))
        finally:
            _close_api(a.inner.api)
            _close_api(b.api)
        assert _error_frames(frames) == []
        assert _content(frames) == ref
        ids = {info["id"] for f in frames
               if (info := _parse_sse(f)) and info.get("id")}
        assert len(ids) == 1  # the splice is invisible to the client
        assert _counter("router.resurrections") - c0 == 1
        assert _counter("router.resurrection_replayed_tokens") - t0 > 0

    def test_no_survivor_degrades_to_error_frame(self):
        """With nowhere to resurrect, the old single-replica contract
        holds: a typed error frame, then [DONE] — never a hang."""
        a = _KillerReplica(InProcessReplica("a", _make_api()), after=2)
        router = Router([a], retries=1, backoff_s=0.0, health_ttl_s=0.0)
        c0 = _counter("router.resurrections")
        try:
            frames = list(router.stream_chat(_body(False), {}))
        finally:
            _close_api(a.inner.api)
        errs = _error_frames(frames)
        assert len(errs) == 1
        assert errs[0]["type"] == "server_error"
        assert frames[-1].strip() == b"data: [DONE]"
        assert _counter("router.resurrections") == c0

    def test_non_resumable_stream_keeps_old_contract(self):
        """Streams without the ``fei`` extension (non-engine providers)
        must not attempt resurrection — error frame, as before."""
        from fei_tpu.agent.providers import MockProvider, ProviderResponse

        api = ServeAPI(
            MockProvider(script=[ProviderResponse(content="hello there")]),
            model_name="mock",
        )
        a = _KillerReplica(InProcessReplica("a", api), after=1)
        b_api = ServeAPI(
            MockProvider(script=[ProviderResponse(content="hello there")]),
            model_name="mock",
        )
        b = InProcessReplica("b", b_api)
        router = Router([a, b], retries=2, backoff_s=0.0, health_ttl_s=0.0)
        c0 = _counter("router.resurrections")
        frames = list(router.stream_chat(_body(False), {}))
        assert len(_error_frames(frames)) == 1
        assert _counter("router.resurrections") == c0


# -- crash fault kind + fsync discipline ----------------------------------


class TestCrashFaultKind:
    def test_delay_fuse_fires_on_nth_check(self, monkeypatch):
        kills = []
        monkeypatch.setattr(faults_mod, "_hard_kill",
                            lambda point: kills.append(point))
        FAULTS.arm("replica.crash", "crash", count=3)
        for _ in range(2):
            FAULTS.check("replica.crash")
        assert kills == []  # the fuse is burning, not fired
        FAULTS.check("replica.crash")
        assert kills == ["replica.crash"]
        FAULTS.check("replica.crash")  # disarmed after firing
        assert kills == ["replica.crash"]
        assert FAULTS.fired("replica.crash") == 1

    def test_env_arming_accepts_crash(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_FAULT", "replica.crash:crash:8")
        FAULTS.load_env()
        assert FAULTS._armed["replica.crash"].kind == "crash"
        assert FAULTS._armed["replica.crash"].count == 8


class TestSnapshotFsync:
    def test_save_fsyncs_file_and_dir(self, tmp_path, monkeypatch):
        from fei_tpu.engine import checkpoint

        synced = []
        real_fsync = os.fsync
        monkeypatch.setattr(
            os, "fsync",
            lambda fd: (synced.append(fd), real_fsync(fd))[1],
        )
        checkpoint.save_request_snapshots(
            str(tmp_path), [{"rid": "r", "prompt_ids": [1], "gen": {}}],
            mesh={"tp": 1},
        )
        # one fsync for the tmp file pre-rename, one for the directory
        assert len(synced) >= 2
