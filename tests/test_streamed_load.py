"""Streamed sharded checkpoint loading (engine/weights.py).

The 70B-on-a-pod path (SURVEY.md §7 hard-part #4): each device shard is
read as a safetensors *slice* via jax.make_array_from_callback — the full
stacked tensor must never be materialized on host. These tests verify the
slice arithmetic (incl. the HF [out, in] -> ours [in, out] transpose),
equality with the eager path, int8 quantize-on-read, and that per-shard
reads really are partial.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.engine.weights import load_checkpoint
from fei_tpu.models.configs import get_model_config
from fei_tpu.models.llama import KVCache, forward
from fei_tpu.ops.quant import QTensor, dequantize
from fei_tpu.parallel.mesh import make_mesh
from fei_tpu.parallel.sharding import param_shardings_from_cfg

safetensors = pytest.importorskip("safetensors.numpy")


def _write_hf_llama(tmp_path, cfg, seed=0):
    base_rng = np.random.default_rng(seed)

    class _Scaled:
        # fan-in-ish scaling so the random model is numerically sane (an
        # unscaled standard-normal stack amplifies int8 error multiplicatively)
        def standard_normal(self, shape):
            return base_rng.standard_normal(shape) * 0.05

    rng = _Scaled()
    h, d = cfg.hidden_size, cfg.head_dim_
    H, K, I, L, V = (
        cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size,
        cfg.num_layers, cfg.vocab_size,
    )
    t = {
        "model.embed_tokens.weight": rng.standard_normal((V, h)).astype(np.float32),
        "model.norm.weight": np.ones(h, np.float32),
        "lm_head.weight": rng.standard_normal((V, h)).astype(np.float32),
    }
    for i in range(L):
        p = f"model.layers.{i}."
        t[p + "input_layernorm.weight"] = np.ones(h, np.float32)
        t[p + "post_attention_layernorm.weight"] = np.ones(h, np.float32)
        t[p + "self_attn.q_proj.weight"] = rng.standard_normal((H * d, h)).astype(np.float32)
        t[p + "self_attn.k_proj.weight"] = rng.standard_normal((K * d, h)).astype(np.float32)
        t[p + "self_attn.v_proj.weight"] = rng.standard_normal((K * d, h)).astype(np.float32)
        t[p + "self_attn.o_proj.weight"] = rng.standard_normal((h, H * d)).astype(np.float32)
        if cfg.is_moe:
            t[p + "block_sparse_moe.gate.weight"] = rng.standard_normal(
                (cfg.num_experts, h)
            ).astype(np.float32)
            for e in range(cfg.num_experts):
                q = p + f"block_sparse_moe.experts.{e}."
                t[q + "w1.weight"] = rng.standard_normal((I, h)).astype(np.float32)
                t[q + "w2.weight"] = rng.standard_normal((h, I)).astype(np.float32)
                t[q + "w3.weight"] = rng.standard_normal((I, h)).astype(np.float32)
        else:
            t[p + "mlp.gate_proj.weight"] = rng.standard_normal((I, h)).astype(np.float32)
            t[p + "mlp.up_proj.weight"] = rng.standard_normal((I, h)).astype(np.float32)
            t[p + "mlp.down_proj.weight"] = rng.standard_normal((h, I)).astype(np.float32)
    safetensors.save_file(t, str(tmp_path / "model.safetensors"))
    (tmp_path / "config.json").write_text(json.dumps({"vocab_size": cfg.vocab_size}))
    return t


def _trees_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(
            np.asarray(x, np.float32), np.asarray(y, np.float32)
        )


class TestStreamedLoad:
    def test_layernorm_family_without_bias_map_fails_cleanly(self):
        """A hypothetical non-parallel-block layernorm config (GPT-NeoX
        style) has no bias entries in the Llama layer map — planning must
        raise CheckpointError up front, not KeyError mid-plan (round-4
        advisory)."""
        from fei_tpu.engine.weights import _plans
        from fei_tpu.utils.errors import CheckpointError

        cfg = get_model_config("tiny", norm_kind="layernorm",
                               parallel_block=False)
        with pytest.raises(CheckpointError, match="layernorm family"):
            _plans(reader=None, cfg=cfg)  # plans never read at build time

    def test_streamed_equals_eager(self, tmp_path):
        cfg = get_model_config("tiny")
        _write_hf_llama(tmp_path, cfg)
        mesh = make_mesh({"tp": 2, "dp": 4})
        _, eager = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        cfg2, streamed = load_checkpoint(
            str(tmp_path), cfg, dtype=jnp.float32,
            shardings=param_shardings_from_cfg(cfg, mesh),
        )
        _trees_equal(eager, streamed)
        # really sharded: wq's out dim split over tp
        assert "tp" in str(streamed["layers"]["wq"].sharding.spec)

        tokens = jnp.array([[1, 2, 3, 4]], jnp.int32)
        cache = KVCache.create(cfg2, 1, 8, jnp.float32)
        logits, _ = forward(streamed, cfg2, tokens, cache)
        want, _ = forward(eager, cfg2, tokens, cache)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(want), atol=2e-4
        )

    def test_streamed_moe(self, tmp_path):
        cfg = get_model_config("tiny-moe")
        _write_hf_llama(tmp_path, cfg)
        mesh = make_mesh({"ep": 2, "tp": 2, "dp": 2})
        _, eager = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        _, streamed = load_checkpoint(
            str(tmp_path), cfg, dtype=jnp.float32,
            shardings=param_shardings_from_cfg(cfg, mesh),
        )
        _trees_equal(eager, streamed)
        assert "ep" in str(streamed["layers"]["w_gate"].sharding.spec)

    def test_partial_reads_only(self, tmp_path, monkeypatch):
        """Sharded loads must read slices, not whole tensors: spy on the
        reader and assert no wq read spans the full out dim on a tp-split
        mesh (each of 2 shards should ask for half the columns)."""
        from fei_tpu.engine import weights as W

        cfg = get_model_config("tiny")
        _write_hf_llama(tmp_path, cfg)
        mesh = make_mesh({"tp": 2, "dp": 4})
        seen = []
        orig = W._ShardReader.read

        def spy(self, name, idx, transpose, expect_hf=None):
            seen.append((name, idx))
            return orig(self, name, idx, transpose, expect_hf)

        monkeypatch.setattr(W._ShardReader, "read", spy)
        load_checkpoint(
            str(tmp_path), cfg, dtype=jnp.float32,
            shardings=param_shardings_from_cfg(cfg, mesh),
        )
        out_dim = cfg.num_heads * cfg.head_dim_
        wq_reads = [
            idx for name, idx in seen if "q_proj" in name
        ]
        assert wq_reads, "no q_proj slice reads recorded"
        for idx in wq_reads:
            cols = idx[-1]
            assert (cols.stop - (cols.start or 0)) <= out_dim // 2

    def test_streamed_int8(self, tmp_path):
        """Quantize-on-read: QTensor leaves, sharded, matching host-side
        quantization of the eager weights."""
        from fei_tpu.ops.quant import quantize_params

        cfg = get_model_config("tiny")
        _write_hf_llama(tmp_path, cfg)
        mesh = make_mesh({"tp": 2, "dp": 4})
        _, eager = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        cfg2, qstreamed = load_checkpoint(
            str(tmp_path), cfg, dtype=jnp.float32,
            shardings=param_shardings_from_cfg(cfg, mesh),
            quantize="int8",
        )
        wq = qstreamed["layers"]["wq"]
        assert isinstance(wq, QTensor) and wq.q.dtype == jnp.int8
        qeager = quantize_params(eager)
        # row-parallel wo: scales must be *global* over the sharded
        # contraction dim — identical to the unsharded quantization
        np.testing.assert_allclose(
            np.asarray(qstreamed["layers"]["wo"].s),
            np.asarray(qeager["layers"]["wo"].s), rtol=1e-6,
        )
        np.testing.assert_array_equal(
            np.asarray(qstreamed["layers"]["wo"].q),
            np.asarray(qeager["layers"]["wo"].q),
        )
        # and the quantized model still runs sharded
        tokens = jnp.array([[5, 6, 7]], jnp.int32)
        cache = KVCache.create(cfg2, 1, 8, jnp.float32)
        logits, _ = forward(qstreamed, cfg2, tokens, cache)
        want, _ = forward(eager, cfg2, tokens, cache)
        rel = np.abs(np.asarray(logits) - np.asarray(want)).max()
        rel /= np.abs(np.asarray(want)).max()
        assert rel < 0.03

    def test_shape_mismatch_fails_loudly(self, tmp_path):
        """A config smaller than the checkpoint must error, not silently
        truncate via slice reads."""
        from fei_tpu.utils.errors import CheckpointError

        cfg = get_model_config("tiny")
        _write_hf_llama(tmp_path, cfg)
        (tmp_path / "config.json").unlink()  # nothing to self-correct from
        from dataclasses import replace

        wrong = replace(cfg, intermediate_size=cfg.intermediate_size // 2)
        with pytest.raises(CheckpointError, match="shape"):
            load_checkpoint(str(tmp_path), wrong, dtype=jnp.float32)

    def test_engine_from_config_streams(self, tmp_path):
        from fei_tpu.engine import GenerationConfig, InferenceEngine

        cfg = get_model_config("tiny")
        _write_hf_llama(tmp_path, cfg)
        mesh = make_mesh({"tp": 2, "dp": 4})
        eng = InferenceEngine.from_config(
            "tiny", tokenizer="byte", checkpoint_dir=str(tmp_path),
            mesh=mesh, quantize="int8", max_seq_len=64, dtype=jnp.float32,
        )
        assert isinstance(eng.params["layers"]["wq"], QTensor)
        assert eng.mesh is mesh
        ids = eng.tokenizer.encode("hi", add_bos=True)
        res = eng.generate(ids, GenerationConfig(max_new_tokens=4, temperature=0.0))
        assert len(res.token_ids) == 4
