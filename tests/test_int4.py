"""Weight-only int4 (QTensor4 + Pallas grouped-dequant matmul).

Layers of guarantee, mirroring the int8 suite (test_quant.py):
- quantize4/dequantize roundtrip error is bounded by the group scale step
- the XLA two-dot fallback equals an explicit dequantize-then-matmul
- the Pallas kernel (interpret mode on CPU) equals the XLA fallback
- an int4-quantized tiny model decodes greedily identically to the same
  model with explicitly dequantized weights (the engine e2e contract)
- mixed-tree rules: lm_head and MoE experts stay int8 (ops.quant._int4_ok)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.ops.quant import (
    QTensor,
    QTensor4,
    dequantize,
    mm,
    quantize4,
    quantize_params,
)
from fei_tpu.ops.pallas.int4_matmul import int4_mm, int4_mm_xla


class TestQuantize4:
    def test_roundtrip_error_bounded_by_group_step(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (1024, 256)) * 0.05
        qt = quantize4(w)
        assert qt.p.shape == (512, 256) and qt.p.dtype == jnp.int8
        assert qt.s.shape == (8, 256) and qt.group_size == 128
        wd = dequantize(qt, jnp.float32)
        # per-(group, channel) step = amax/7; error <= step/2
        grouped = np.asarray(w, np.float32).reshape(8, 128, 256)
        step = np.abs(grouped).max(axis=1) / 7.0
        err = np.abs(np.asarray(wd).reshape(8, 128, 256) - grouped)
        assert (err <= step[:, None, :] / 2 + 1e-7).all()

    def test_packing_is_lossless(self):
        """Nibble pack/unpack preserves every int4 level including -7/7."""
        w = jax.random.normal(jax.random.PRNGKey(1), (512, 128))
        qt = quantize4(w)
        from fei_tpu.ops.quant import unpack4

        lo, hi = unpack4(qt.p)
        q = np.concatenate([np.asarray(lo), np.asarray(hi)], axis=0)
        assert q.min() >= -7 and q.max() <= 7
        # re-derive the reference quantization directly
        w32 = np.asarray(w, np.float32).reshape(4, 128, 128)
        s = np.abs(w32).max(axis=1, keepdims=True) / 7.0
        ref = np.clip(np.round(w32 / s), -7, 7).reshape(512, 128)
        np.testing.assert_array_equal(q, ref)

    def test_odd_contraction_rejected(self):
        with pytest.raises(ValueError):
            quantize4(jnp.ones((100, 64)))


class TestInt4Matmul:
    def test_xla_fallback_matches_dequant_oracle(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (2048, 512)) * 0.05
        qt = quantize4(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 2048), jnp.bfloat16)
        oracle = (
            x.astype(jnp.float32) @ dequantize(qt, jnp.bfloat16).astype(jnp.float32)
        ).astype(jnp.bfloat16)
        out = int4_mm_xla(x, qt)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(oracle, np.float32),
            atol=0.02,  # bf16 dot rounding between the two formulations
        )

    @pytest.mark.parametrize("M,K,N", [(1, 2048, 256), (33, 4096, 512)])
    def test_kernel_matches_fallback(self, M, K, N):
        import fei_tpu.ops.pallas.int4_matmul as m

        w = jax.random.normal(jax.random.PRNGKey(0), (K, N)) * 0.05
        qt = quantize4(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (M, K), jnp.bfloat16)
        before = m._kernel_invocations
        out_k = int4_mm(x, qt)  # interpret mode on CPU
        assert m._kernel_invocations == before + 1  # kernel, not fallback
        out_x = int4_mm_xla(x, qt)
        np.testing.assert_allclose(
            np.asarray(out_k, np.float32), np.asarray(out_x, np.float32),
            atol=5e-3,
        )

    def test_small_shapes_use_fallback(self):
        """Shapes the kernel can't tile route through XLA, not an error."""
        w = jax.random.normal(jax.random.PRNGKey(0), (512, 64)) * 0.05
        qt = quantize4(w)
        x = jnp.ones((2, 512), jnp.bfloat16)
        out = mm(x, qt)
        assert out.shape == (2, 64)

    def test_mm_dispatch_3d(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (2048, 256)) * 0.05
        qt = quantize4(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 2048), jnp.bfloat16)
        assert mm(x, qt).shape == (2, 3, 256)


class TestMosaicPreflight:
    """The preflight must run EAGERLY even when int4_mm is being traced
    inside an enclosing jit (the engine's normal call site) — a mid-trace
    probe that touches tracers would latch the XLA fallback forever.
    FEI_TPU_INT4_PREFLIGHT=1 forces the probe on CPU (interpret mode)."""

    def test_preflight_under_jit_selects_kernel(self, monkeypatch):
        import fei_tpu.ops.pallas.int4_matmul as m

        monkeypatch.setenv("FEI_TPU_INT4_PREFLIGHT", "1")
        monkeypatch.setattr(m, "_mosaic_probe_cache", {})
        w = jax.random.normal(jax.random.PRNGKey(0), (2048, 256)) * 0.05
        qt = quantize4(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 2048), jnp.bfloat16)
        before = m._kernel_invocations

        out = jax.jit(lambda x: int4_mm(x, qt))(x)

        # the probe ran on its own (eager) thread mid-trace and latched ok
        assert list(m._mosaic_probe_cache.values()) == [True]
        assert m._kernel_invocations == before + 1  # Pallas path, not XLA
        out_x = int4_mm_xla(x, qt)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(out_x, np.float32),
            atol=2e-2,
        )

    def test_failed_preflight_latches_fallback(self, monkeypatch):
        import fei_tpu.ops.pallas.int4_matmul as m

        monkeypatch.setenv("FEI_TPU_INT4_PREFLIGHT", "1")
        monkeypatch.setattr(m, "_mosaic_probe_cache", {})

        def boom(*a, **k):
            raise RuntimeError("mosaic says no")

        monkeypatch.setattr(m, "_int4_mm_kernel", boom)
        w = jax.random.normal(jax.random.PRNGKey(0), (2048, 256)) * 0.05
        qt = quantize4(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 2048), jnp.bfloat16)
        before = m._kernel_invocations

        out = jax.jit(lambda x: int4_mm(x, qt))(x)

        # rejection latched; the call routed through XLA without raising
        assert list(m._mosaic_probe_cache.values()) == [False]
        assert m._kernel_invocations == before
        np.testing.assert_allclose(
            np.asarray(out, np.float32),
            np.asarray(int4_mm_xla(x, qt), np.float32),
            atol=2e-2,
        )


class TestMixedTreeRules:
    def test_lm_head_and_moe_experts_stay_int8(self):
        params = {
            "layers": {
                "router": jnp.ones((2, 512, 8)),
                "wq": jnp.ones((2, 512, 512)),
                "w_gate": jnp.ones((2, 8, 512, 1024)),
            },
            "lm_head": jnp.ones((512, 1024)),
        }
        out = quantize_params(params, bits=4)
        assert isinstance(out["layers"]["wq"], QTensor4)
        assert isinstance(out["layers"]["w_gate"], QTensor)  # moe expert
        assert isinstance(out["lm_head"], QTensor)

    def test_lm_head_int4_opt_in(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_INT4_LM_HEAD", "1")
        params = {"lm_head": jnp.ones((512, 1024))}
        out = quantize_params(params, bits=4)
        assert isinstance(out["lm_head"], QTensor4)

    def test_ineligible_contraction_falls_back_to_int8(self):
        params = {"layers": {"wq": jnp.ones((2, 100, 128))}}
        out = quantize_params(params, bits=4)
        assert isinstance(out["layers"]["wq"], QTensor)


@pytest.mark.slow  # fast lane: -m 'not slow'
class TestEngineInt4:
    # Environment precondition: the int4 kernel contraction (split
    # lo/hi two-dot with result-side group scaling, f32 accumulation —
    # ops/pallas/int4_matmul.py) and the oracle's bf16-rounded
    # dequantize-then-single-dot were never bitwise-equal; on CPU XLA
    # the tiny model's logit gap is ~1 bf16 ulp and commit a48a9e0
    # (per-layer lax.map init draws) landed weights where the rounding
    # difference flips the argmax mid-stream. The identity holds under
    # Mosaic on TPU, where the onchip pipeline's kernels stage runs it.
    @pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="int4 kernel/oracle parity needs TPU Mosaic rounding; "
               "CPU XLA's two-dot fallback rounds ~1 ulp differently "
               "and flips the greedy argmax for the tiny test model",
    )
    def test_greedy_decode_matches_dequantized_oracle(self):
        """The engine e2e contract: an int4 engine decodes token-identically
        to the same weights explicitly dequantized to bf16 (h=512 so the
        attention/mlp linears are int4-eligible)."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine
        from fei_tpu.ops.quant import dequantize_params

        kw = dict(
            dtype=jnp.bfloat16, seed=0, tokenizer="byte", max_seq_len=64,
            num_layers=2, hidden_size=512, intermediate_size=1024,
            num_heads=8, num_kv_heads=4,
        )
        gen = GenerationConfig(max_new_tokens=12, temperature=0.0, ignore_eos=True)
        prompt = "int4 parity probe"

        eng4 = InferenceEngine.from_config("tiny", quantize="int4", **kw)
        assert any(
            isinstance(leaf, QTensor4)
            for leaf in jax.tree.leaves(
                eng4.params, is_leaf=lambda x: isinstance(x, QTensor4)
            )
        )
        ids4 = eng4.generate(eng4.tokenizer.encode(prompt), gen).token_ids

        eng = InferenceEngine.from_config("tiny", **kw)
        eng.params = dequantize_params(eng4.params, jnp.bfloat16)
        ids = eng.generate(eng.tokenizer.encode(prompt), gen).token_ids
        assert ids4 == ids

    def test_checkpoint_roundtrip_preserves_qtensor4(self, tmp_path):
        """Orbax round-trips NamedTuples as dicts; the restore retype must
        rebuild QTensor4 (and not confuse it with int8 QTensor)."""
        from fei_tpu.engine.weights import restore_checkpoint, save_checkpoint
        from fei_tpu.ops.quant import quantize as quantize8

        w = jax.random.normal(jax.random.PRNGKey(0), (512, 128)) * 0.05
        tree = {
            "layers": {"wq": quantize4(w), "wo": quantize8(w)},
            "norm": jnp.ones((4,)),
        }
        path = str(tmp_path / "ckpt")
        save_checkpoint(tree, path)
        back = restore_checkpoint(path)
        assert isinstance(back["layers"]["wq"], QTensor4)
        assert isinstance(back["layers"]["wo"], QTensor)
        np.testing.assert_array_equal(
            np.asarray(back["layers"]["wq"].p), np.asarray(tree["layers"]["wq"].p)
        )
        np.testing.assert_allclose(
            np.asarray(back["layers"]["wq"].s), np.asarray(tree["layers"]["wq"].s)
        )

    def test_streamed_int4_load_matches_host_quant(self, tmp_path):
        """HF-dir load with quantize='int4': eligible leaves land packed
        and bit-identical to quantize4 of the eagerly-loaded weights;
        lm_head stays int8; the loaded model runs close to the bf16 one."""
        from test_streamed_load import _write_hf_llama

        from fei_tpu.engine.weights import load_checkpoint
        from fei_tpu.models.configs import get_model_config
        from fei_tpu.models.llama import KVCache, forward

        cfg = get_model_config(
            "tiny", hidden_size=512, intermediate_size=1024,
            num_heads=8, num_kv_heads=4,
        )
        _write_hf_llama(tmp_path, cfg)
        _, eager = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        cfg2, q4 = load_checkpoint(
            str(tmp_path), cfg, dtype=jnp.float32, quantize="int4"
        )
        wq = q4["layers"]["wq"]
        assert isinstance(wq, QTensor4)
        assert isinstance(q4["lm_head"], QTensor)
        ref = quantize4(eager["layers"]["wq"])
        np.testing.assert_array_equal(np.asarray(wq.p), np.asarray(ref.p))
        np.testing.assert_allclose(
            np.asarray(wq.s), np.asarray(ref.s), rtol=1e-6
        )
        # run-parity vs the dequantized oracle (mm-path correctness; the
        # quantization ERROR itself is pinned by the roundtrip-bound test —
        # on this test's unscaled random stack it amplifies multiplicatively
        # and is not a meaningful accuracy statement)
        from fei_tpu.ops.quant import dequantize_params

        tokens = jnp.array([[5, 6, 7]], jnp.int32)
        cache = KVCache.create(cfg2, 1, 8, jnp.float32)
        logits, _ = forward(q4, cfg2, tokens, cache)
        want, _ = forward(
            dequantize_params(q4, jnp.float32), cfg2, tokens, cache
        )
        rel = np.abs(np.asarray(logits) - np.asarray(want)).max()
        rel /= np.abs(np.asarray(want)).max()
        assert rel < 0.03  # bf16 dot rounding between the two formulations

    def test_paged_scheduler_serves_int4(self):
        """Continuous-batching serving path on int4 weights: two concurrent
        greedy streams decode token-identically to the dense int4 engine."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine

        kw = dict(
            dtype=jnp.bfloat16, seed=0, tokenizer="byte", max_seq_len=64,
            num_layers=2, hidden_size=512, intermediate_size=1024,
            num_heads=8, num_kv_heads=4,
        )
        gen = GenerationConfig(max_new_tokens=10, temperature=0.0, ignore_eos=True)
        prompt = "int4 paged serving probe"

        dense = InferenceEngine.from_config("tiny", quantize="int4", **kw)
        want = dense.generate(dense.tokenizer.encode(prompt), gen).token_ids

        paged = InferenceEngine.from_config(
            "tiny", quantize="int4", paged=True, batch_size=2, page_size=8,
            **kw,
        )
        try:
            ids = paged.tokenizer.encode(prompt)
            import concurrent.futures as cf

            with cf.ThreadPoolExecutor(2) as ex:
                outs = list(
                    ex.map(
                        lambda _: list(paged.scheduler.stream(ids, gen)),
                        range(2),
                    )
                )
            assert outs[0] == outs[1] == want
        finally:
            paged.close()

@pytest.mark.slow  # fast lane: -m 'not slow'
class TestInt4Mesh:
    """Mesh composition tests — need multiple devices (the on-chip pipeline
    runs this file against the single real chip: these must skip, not
    error, there)."""

    @pytest.fixture(autouse=True)
    def _needs_devices(self):
        if len(jax.devices()) < 2:
            pytest.skip("mesh tests need >=2 devices")

    def test_sharded_kernel_no_weight_gather(self):
        """int4_mm_sharded must not all-gather the packed weight (the
        global-view pallas_call does — 13 collectives measured on tp=2);
        the shard_map form runs the kernel on each device's N-shard with
        zero collectives, matching the unsharded result."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from fei_tpu.ops.pallas.int4_matmul import int4_mm_sharded
        from fei_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
        K, N = 2048, 512
        w = jax.random.normal(jax.random.PRNGKey(0), (K, N)) * 0.05
        qt = quantize4(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, K), jnp.bfloat16)
        ps = jax.device_put(qt.p, NamedSharding(mesh, P(None, "tp")))
        ss = jax.device_put(qt.s, NamedSharding(mesh, P(None, "tp")))

        f = jax.jit(
            lambda x, p, s: int4_mm_sharded(x, QTensor4(p=p, s=s), mesh)
        )
        out = f(x, ps, ss)
        ref = int4_mm(x, qt)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=5e-3,
        )
        txt = f.lower(x, ps, ss).compile().as_text()
        assert "all-gather" not in txt and "all-reduce" not in txt

    def test_engine_tp_mesh_matches_local_params(self):
        """from_config with a tp mesh: column-parallel linears are QTensor4
        (served by the shard_map kernel), row-parallel wo/w_down stay int8,
        and prefill logits match an unsharded forward over the identical
        param values."""
        from fei_tpu.engine import InferenceEngine
        from fei_tpu.models.llama import KVCache, forward
        from fei_tpu.ops.quant import QTensor
        from fei_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
        kw = dict(
            dtype=jnp.bfloat16, seed=0, tokenizer="byte", max_seq_len=64,
            num_layers=2, hidden_size=512, intermediate_size=1024,
            num_heads=8, num_kv_heads=4,
        )
        eng = InferenceEngine.from_config(
            "tiny", quantize="int4", mesh=mesh, **kw
        )
        layers = eng.params["layers"]
        assert isinstance(layers["wq"], QTensor4)
        assert isinstance(layers["wo"], QTensor)  # contract-sharded: int8
        assert isinstance(layers["w_down"], QTensor)

        ids = eng.tokenizer.encode("int4 tp mesh probe")
        logits, _ = eng.prefill([ids], eng.new_cache(1))

        local = jax.device_get(eng.params)  # same values, unplaced
        cache = KVCache.create(eng.cfg, 1, eng.max_seq_len, dtype=eng.dtype)
        bucket = 16
        while bucket < len(ids):
            bucket *= 2
        bucket_tokens = jnp.array(
            [list(ids) + [0] * (bucket - len(ids))], jnp.int32
        )
        want, _ = forward(local, eng.cfg, bucket_tokens, cache)
        want_last = want[0, len(ids) - 1, :]
        np.testing.assert_allclose(
            np.asarray(logits[0], np.float32), np.asarray(want_last, np.float32),
            atol=5e-2, rtol=1e-2,
        )

    def test_streamed_int4_load_sharded(self, tmp_path):
        """HF load with int4 + tp shardings: column-parallel leaves land as
        N-sharded QTensor4, contract-sharded wo/w_down fall back to int8,
        and the sharded model runs."""
        from test_streamed_load import _write_hf_llama

        from fei_tpu.engine.weights import load_checkpoint
        from fei_tpu.models.configs import get_model_config
        from fei_tpu.models.llama import KVCache, forward
        from fei_tpu.ops.quant import QTensor
        from fei_tpu.parallel.mesh import make_mesh
        from fei_tpu.parallel.sharding import param_shardings_from_cfg

        cfg = get_model_config(
            "tiny", hidden_size=512, intermediate_size=1024,
            num_heads=8, num_kv_heads=4,
        )
        _write_hf_llama(tmp_path, cfg)
        if len(jax.devices()) < 8:
            pytest.skip("sharded streamed load needs the 8-device mesh")
        mesh = make_mesh({"tp": 2, "dp": 4})
        cfg2, q4 = load_checkpoint(
            str(tmp_path), cfg, dtype=jnp.float32,
            shardings=param_shardings_from_cfg(cfg, mesh),
            quantize="int4",
        )
        assert isinstance(q4["layers"]["wq"], QTensor4)
        assert isinstance(q4["layers"]["wo"], QTensor)
        # packed bytes equal the host quantization of the eager weights
        _, eager = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        ref = quantize4(eager["layers"]["wq"])
        np.testing.assert_array_equal(
            np.asarray(q4["layers"]["wq"].p), np.asarray(ref.p)
        )
        tokens = jnp.array([[5, 6, 7]], jnp.int32)
        cache = KVCache.create(cfg2, 1, 8, jnp.float32)
        logits, _ = forward(q4, cfg2, tokens, cache)
        assert np.isfinite(np.asarray(logits)).all()
