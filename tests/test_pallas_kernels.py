"""Pallas kernels vs the XLA-native oracle (fei_tpu.ops.attention).

Runs in interpret mode on the CPU test mesh; the same kernel code compiles
on TPU. Tolerances are loose-ish because the oracle softmax is fp32 while
the kernels accumulate blockwise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import requires_shard_map

from fei_tpu.ops.attention import attention
from fei_tpu.ops.pallas import flash_attention, paged_attention


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=jnp.float32).astype(dtype) * 0.3


def _atol():
    # On real TPU the MXU's default-precision fp32 matmul accumulates
    # differently from the fp32 interpret-mode oracle; 2e-3 holds in
    # interpret, 5e-3 on chip. Lazy so collection doesn't init the backend.
    return 5e-3 if jax.default_backend() == "tpu" else 2e-3


class TestFlashAttention:
    @pytest.mark.parametrize("T,S,q_start", [(16, 64, 0), (64, 64, 0), (8, 128, 40)])
    def test_matches_oracle(self, T, S, q_start):
        B, H, K, D = 2, 4, 2, 64
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = _rand(ks[0], (B, T, H, D))
        k = _rand(ks[1], (B, S, K, D))
        v = _rand(ks[2], (B, S, K, D))
        starts = jnp.array([q_start, q_start], dtype=jnp.int32)
        kv_len = starts + T

        positions = starts[:, None] + jnp.arange(T)[None, :]
        want = attention(q, k, v, positions, kv_len)
        got = flash_attention(q, k, v, starts, kv_len, block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    def test_ragged_batch(self):
        """Different cache offsets per sequence."""
        B, T, H, K, D, S = 2, 4, 4, 4, 32, 64
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = _rand(ks[0], (B, T, H, D))
        k = _rand(ks[1], (B, S, K, D))
        v = _rand(ks[2], (B, S, K, D))
        starts = jnp.array([5, 23], dtype=jnp.int32)
        kv_len = starts + T

        positions = starts[:, None] + jnp.arange(T)[None, :]
        want = attention(q, k, v, positions, kv_len)
        got = flash_attention(q, k, v, starts, kv_len, block_q=8, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    def test_unaligned_lengths_padded(self):
        """T not a multiple of block_q — wrapper pads and slices."""
        B, T, H, K, D, S = 1, 37, 2, 1, 32, 50
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q = _rand(ks[0], (B, T, H, D))
        k = _rand(ks[1], (B, S, K, D))
        v = _rand(ks[2], (B, S, K, D))
        starts = jnp.zeros((B,), jnp.int32)
        kv_len = starts + T

        positions = starts[:, None] + jnp.arange(T)[None, :]
        want = attention(q, k, v, positions, kv_len)
        got = flash_attention(q, k, v, starts, kv_len, block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    def test_bf16(self):
        B, T, H, K, D = 1, 32, 4, 2, 64
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q = _rand(ks[0], (B, T, H, D), jnp.bfloat16)
        k = _rand(ks[1], (B, T, K, D), jnp.bfloat16)
        v = _rand(ks[2], (B, T, K, D), jnp.bfloat16)
        starts = jnp.zeros((B,), jnp.int32)
        kv_len = starts + T
        positions = jnp.arange(T)[None, :]

        want = attention(q, k, v, positions, kv_len)
        got = flash_attention(q, k, v, starts, kv_len, block_q=16, block_k=16)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2
        )


class TestFlashAttentionVJP:
    """Pallas flash backward vs jax.grad through the XLA oracle."""

    def _grads(self, fn, q, k, v, starts, kv_len, positions):
        def loss_flash(q, k, v):
            return jnp.sum(fn(q, k, v) ** 2)

        return jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("T,S,q_start", [(32, 32, 0), (16, 64, 17)])
    def test_grads_match_oracle(self, T, S, q_start):
        B, H, K, D = 2, 4, 2, 32  # GQA groups=2
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q = _rand(ks[0], (B, T, H, D))
        k = _rand(ks[1], (B, S, K, D))
        v = _rand(ks[2], (B, S, K, D))
        starts = jnp.array([q_start, q_start], dtype=jnp.int32)
        kv_len = starts + T
        positions = starts[:, None] + jnp.arange(T)[None, :]

        flash_fn = lambda q, k, v: flash_attention(
            q, k, v, starts, kv_len, block_q=16, block_k=16
        )
        oracle_fn = lambda q, k, v: attention(q, k, v, positions, kv_len)
        got = self._grads(flash_fn, q, k, v, starts, kv_len, positions)
        want = self._grads(oracle_fn, q, k, v, starts, kv_len, positions)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=_atol() * 2,
                err_msg=f"d{name} mismatch",
            )

    def test_grads_unaligned(self):
        """T/S not multiples of the blocks: padded rows must not leak grads."""
        B, T, H, K, D, S = 1, 21, 2, 1, 32, 30
        ks = jax.random.split(jax.random.PRNGKey(8), 3)
        q = _rand(ks[0], (B, T, H, D))
        k = _rand(ks[1], (B, S, K, D))
        v = _rand(ks[2], (B, S, K, D))
        starts = jnp.zeros((B,), jnp.int32)
        kv_len = starts + T
        positions = starts[:, None] + jnp.arange(T)[None, :]

        flash_fn = lambda q, k, v: flash_attention(
            q, k, v, starts, kv_len, block_q=8, block_k=16
        )
        oracle_fn = lambda q, k, v: attention(q, k, v, positions, kv_len)
        got = self._grads(flash_fn, q, k, v, starts, kv_len, positions)
        want = self._grads(oracle_fn, q, k, v, starts, kv_len, positions)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=_atol() * 2)

    def test_train_forward_uses_flash(self, monkeypatch):
        """forward_train differentiates with FEI_TPU_FLASH=1 (kernel VJP)."""
        from fei_tpu.models.configs import get_model_config
        from fei_tpu.models.llama import forward_train, init_params

        monkeypatch.setenv("FEI_TPU_FLASH", "1")
        cfg = get_model_config("tiny")
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        tokens = jnp.array([[1, 5, 9, 2, 7, 3, 8, 4]], jnp.int32)

        def loss(p):
            logits = forward_train(p, cfg, tokens, remat=True)
            return jnp.mean(logits ** 2)

        grads = jax.grad(loss)(params)
        gnorm = sum(
            float(jnp.sum(g.astype(jnp.float32) ** 2))
            for g in jax.tree.leaves(grads)
        )
        assert np.isfinite(gnorm) and gnorm > 0


class TestPagedAttention:
    def _setup(self, key, B, H, K, D, page_size, pages_per_seq, lengths):
        """Build a paged pool + a contiguous view of the same data."""
        ks = jax.random.split(key, 3)
        P = B * pages_per_seq + 1  # pool bigger than needed; page 0 unused
        k_pages = _rand(ks[0], (P, K, page_size, D))
        v_pages = _rand(ks[1], (P, K, page_size, D))
        # block table: pages assigned in shuffled order so the kernel's
        # table indirection (not pool order) is what's exercised
        rng = np.random.default_rng(0)
        perm = rng.permutation(np.arange(1, P))
        table = perm[: B * pages_per_seq].reshape(B, pages_per_seq)
        block_table = jnp.asarray(table, dtype=jnp.int32)

        S = page_size * pages_per_seq

        def contig(pages):
            # [pps, K, ps, D] -> [S, K, D]
            return jnp.stack(
                [
                    jnp.moveaxis(pages[table[b]], 1, 2).reshape(S, K, D)
                    for b in range(B)
                ]
            )

        k_contig = contig(k_pages)
        v_contig = contig(v_pages)
        q = _rand(ks[2], (B, H, D))
        return q, k_pages, v_pages, block_table, k_contig, v_contig

    def test_matches_oracle(self):
        B, H, K, D, page_size, pps = 2, 4, 2, 64, 16, 4
        lengths = jnp.array([50, 17], dtype=jnp.int32)
        q, kp, vp, bt, kc, vc = self._setup(
            jax.random.PRNGKey(0), B, H, K, D, page_size, pps, lengths
        )

        # oracle: decode token at position length-1 against contiguous cache
        positions = (lengths - 1)[:, None]
        want = attention(q[:, None], kc, vc, positions, lengths)[:, 0]
        got = paged_attention(q, kp, vp, bt, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    def test_single_page(self):
        B, H, K, D, page_size = 1, 2, 2, 32, 8
        lengths = jnp.array([3], dtype=jnp.int32)
        q, kp, vp, bt, kc, vc = self._setup(
            jax.random.PRNGKey(1), B, H, K, D, page_size, 1, lengths
        )
        positions = (lengths - 1)[:, None]
        want = attention(q[:, None], kc, vc, positions, lengths)[:, 0]
        got = paged_attention(q, kp, vp, bt, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    def test_full_pages(self):
        """Length exactly fills every page."""
        B, H, K, D, page_size, pps = 1, 4, 4, 32, 8, 3
        lengths = jnp.array([24], dtype=jnp.int32)
        q, kp, vp, bt, kc, vc = self._setup(
            jax.random.PRNGKey(2), B, H, K, D, page_size, pps, lengths
        )
        positions = (lengths - 1)[:, None]
        want = attention(q[:, None], kc, vc, positions, lengths)[:, 0]
        got = paged_attention(q, kp, vp, bt, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())


class TestPagedBlockAttention:
    """Multi-query block kernel (speculative verification): per-row causal
    limits over the paged pool, history read once for the whole block."""

    def _setup(self, key, B, T, H, K, D, page_size, pps):
        ks = jax.random.split(key, 3)
        P = B * pps + 1
        k_pages = _rand(ks[0], (P, K, page_size, D))
        v_pages = _rand(ks[1], (P, K, page_size, D))
        rng = np.random.default_rng(7)
        perm = rng.permutation(np.arange(1, P))
        table = perm[: B * pps].reshape(B, pps)
        block_table = jnp.asarray(table, dtype=jnp.int32)
        q = _rand(ks[2], (B, T, H, D))
        return q, k_pages, v_pages, block_table

    def _per_position_oracle(self, q, kp, vp, bt, base, **scales):
        """T single-query kernel calls — the exact semantics the block
        kernel must reproduce (same pool state, incremented limits)."""
        B, T, H, D = q.shape
        outs = [
            paged_attention(q[:, i], kp, vp, bt, base + i + 1, **scales)
            for i in range(T)
        ]
        return jnp.stack(outs, axis=1)

    def test_matches_per_position(self):
        from fei_tpu.ops.pallas.paged_attention import paged_attention_block

        B, T, H, K, D, ps, pps = 2, 5, 4, 2, 64, 16, 4
        base = jnp.array([33, 11], dtype=jnp.int32)  # kv before the block
        q, kp, vp, bt = self._setup(
            jax.random.PRNGKey(3), B, T, H, K, D, ps, pps
        )
        want = self._per_position_oracle(q, kp, vp, bt, base)
        got = paged_attention_block(q, kp, vp, bt, base)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    def test_t1_equals_single_query(self):
        from fei_tpu.ops.pallas.paged_attention import paged_attention_block

        B, T, H, K, D, ps, pps = 1, 1, 4, 4, 32, 8, 3
        base = jnp.array([13], dtype=jnp.int32)
        q, kp, vp, bt = self._setup(
            jax.random.PRNGKey(4), B, T, H, K, D, ps, pps
        )
        want = paged_attention(q[:, 0], kp, vp, bt, base + 1)
        got = paged_attention_block(q, kp, vp, bt, base)[:, 0]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    def test_int8_pool(self):
        from fei_tpu.ops.pallas.paged_attention import paged_attention_block

        B, T, H, K, D, ps, pps = 2, 3, 4, 2, 32, 8, 4
        base = jnp.array([9, 20], dtype=jnp.int32)
        q, kp, vp, bt = self._setup(
            jax.random.PRNGKey(5), B, T, H, K, D, ps, pps
        )

        def rowquant(pages):
            # per-(page, head, slot) symmetric int8 over D — the pool's
            # storage layout, scales [P, K, 1, ps]
            amax = jnp.max(jnp.abs(pages), axis=-1, keepdims=True)
            s = jnp.where(amax == 0, 1.0, amax / 127.0)
            qv = jnp.clip(jnp.round(pages / s), -127, 127).astype(jnp.int8)
            return qv, jnp.moveaxis(s, -1, -2)

        kq, ksc = rowquant(kp)
        vq, vsc = rowquant(vp)
        want = self._per_position_oracle(
            q, kq, vq, bt, base, k_scales=ksc, v_scales=vsc
        )
        got = paged_attention_block(
            q, kq, vq, bt, base, k_scales=ksc, v_scales=vsc
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())

    @requires_shard_map
    def test_sharded_matches_local(self):
        from fei_tpu.ops.pallas.paged_attention import (
            paged_attention_block,
            paged_attention_block_sharded,
        )
        from fei_tpu.parallel.mesh import make_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        B, T, H, K, D, ps, pps = 2, 4, 4, 2, 32, 8, 4
        base = jnp.array([21, 6], dtype=jnp.int32)
        q, kp, vp, bt = self._setup(
            jax.random.PRNGKey(6), B, T, H, K, D, ps, pps
        )
        mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
        want = paged_attention_block(q, kp, vp, bt, base)
        got = paged_attention_block_sharded(q, kp, vp, bt, base, mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=_atol())
