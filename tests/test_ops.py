"""Numerics tests for fei_tpu.ops against plain-numpy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.ops.attention import attention
from fei_tpu.ops.moe import moe_mlp
from fei_tpu.ops.rmsnorm import rms_norm
from fei_tpu.ops.rope import apply_rope, compute_rope_freqs


def test_rmsnorm_matches_reference():
    x = np.random.default_rng(0).standard_normal((2, 5, 16)).astype(np.float32)
    w = np.random.default_rng(1).standard_normal(16).astype(np.float32)
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-5)
    want = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_rope_identity_at_position_zero():
    cos, sin = compute_rope_freqs(8, 16, theta=10000.0)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((1, 1, 2, 8)), jnp.float32)
    pos = jnp.zeros((1, 1), dtype=jnp.int32)
    np.testing.assert_allclose(np.asarray(apply_rope(x, cos, sin, pos)), np.asarray(x), atol=1e-6)


def test_rope_preserves_norm_and_relative_phase():
    cos, sin = compute_rope_freqs(8, 64, theta=10000.0)
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((1, 1, 1, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 1, 1, 8)), jnp.float32)
    for p in (3, 17):
        rq = apply_rope(q, cos, sin, jnp.full((1, 1), p, jnp.int32))
        np.testing.assert_allclose(
            float(jnp.linalg.norm(rq)), float(jnp.linalg.norm(q)), rtol=1e-5
        )
    # <rope(q,p), rope(k,p+d)> depends only on d (relative position property)
    def dot(pq, pk):
        rq = apply_rope(q, cos, sin, jnp.full((1, 1), pq, jnp.int32))
        rk = apply_rope(k, cos, sin, jnp.full((1, 1), pk, jnp.int32))
        return float(jnp.sum(rq * rk))

    assert dot(5, 9) == pytest.approx(dot(20, 24), rel=1e-4)


def test_attention_matches_naive_softmax():
    rng = np.random.default_rng(3)
    B, T, H, K, D, S = 2, 4, 4, 2, 8, 4
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, S, K, D)).astype(np.float32)
    v = rng.standard_normal((B, S, K, D)).astype(np.float32)
    pos = np.tile(np.arange(T), (B, 1)).astype(np.int32)
    got = np.asarray(
        attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(pos), S)
    )
    # naive reference with explicit GQA expansion + causal mask
    k_full = np.repeat(k, H // K, axis=2)  # [B,S,H,D]
    v_full = np.repeat(v, H // K, axis=2)
    want = np.zeros_like(got)
    for b in range(B):
        for h in range(H):
            scores = q[b, :, h] @ k_full[b, :, h].T / np.sqrt(D)
            mask = np.tril(np.ones((T, S), dtype=bool))
            scores = np.where(mask, scores, -np.inf)
            p = np.exp(scores - scores.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            want[b, :, h] = p @ v_full[b, :, h]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_attention_respects_kv_length():
    rng = np.random.default_rng(4)
    B, T, H, K, D, S = 1, 1, 2, 2, 4, 8
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, K, D)), jnp.float32)
    pos = jnp.full((B, T), 100, jnp.int32)  # causal never binds; only kv_length
    out3 = attention(q, k, v, pos, jnp.array([3]))
    # zeroing the masked tail must not change the result
    k2 = k.at[:, 3:].set(999.0)
    v2 = v.at[:, 3:].set(999.0)
    out3b = attention(q, k2, v2, pos, jnp.array([3]))
    np.testing.assert_allclose(np.asarray(out3), np.asarray(out3b), atol=1e-5)


def test_moe_topk_gating():
    rng = np.random.default_rng(5)
    B, T, H, I, E = 1, 3, 8, 16, 4
    x = jnp.asarray(rng.standard_normal((B, T, H)), jnp.float32)
    router = jnp.asarray(rng.standard_normal((H, E)), jnp.float32)
    wg = jnp.asarray(rng.standard_normal((E, H, I)) * 0.1, jnp.float32)
    wu = jnp.asarray(rng.standard_normal((E, H, I)) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.standard_normal((E, I, H)) * 0.1, jnp.float32)
    out = moe_mlp(x, router, wg, wu, wd, num_experts_per_tok=2)
    assert out.shape == (B, T, H)
    # with k == E the result equals a softmax-weighted dense mixture
    out_full = moe_mlp(x, router, wg, wu, wd, num_experts_per_tok=E)
    logits = np.asarray(x) @ np.asarray(router)
    w_all = np.exp(logits - logits.max(-1, keepdims=True))
    w_all /= w_all.sum(-1, keepdims=True)
    expert_outs = []
    for e in range(E):
        act = np.asarray(x) @ np.asarray(wg)[e]
        act = act / (1 + np.exp(-act))  # silu
        expert_outs.append((act * (np.asarray(x) @ np.asarray(wu)[e])) @ np.asarray(wd)[e])
    want = sum(w_all[..., e, None] * expert_outs[e] for e in range(E))
    np.testing.assert_allclose(np.asarray(out_full), want, rtol=1e-3, atol=1e-4)
