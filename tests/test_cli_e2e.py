"""The packaged CLI as a subprocess — the real `fei --message` product
surface (reference: fei/__main__.py + fei/ui/cli.py). An Assistant-level
test cannot catch entry-point regressions (argparse wiring, platform
selection, import cost); this one runs the module the way a user does.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=420, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "fei_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


class TestCliE2E:
    def test_help_is_fast_and_jaxless(self):
        t0 = time.time()
        out = _run(["--help"], timeout=60)
        dt = time.time() - t0
        assert out.returncode == 0, out.stderr[-500:]
        assert "--message" in out.stdout
        # argparse must not pay a backend import; generous bound for cold
        # interpreter + package import on a loaded machine
        assert dt < 30, f"--help took {dt:.1f}s"

    def test_message_round_trip_on_cpu(self):
        """JAX_PLATFORMS=cpu must be honored end-to-end: with the pinned
        TPU platform down this would hang forever instead (the regression
        this test exists for)."""
        out = _run(
            ["--message", "say hi"],
            extra_env={"FEI_TPU_JAX_LOCAL_MODEL": "tiny"},
        )
        assert out.returncode == 0, out.stderr[-1000:]
        # random weights emit noise, but the warning proves the provider
        # constructed and the turn completed through the real stack
        assert "RANDOM tiny weights" in out.stderr

    def test_mock_provider_task_loop(self):
        out = _run(["--provider", "mock", "--message", "hello there"])
        assert out.returncode == 0, out.stderr[-1000:]
        assert "[mock] echo" in out.stdout
