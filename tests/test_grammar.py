"""Grammar-constrained decoding: schema → DFA → token masks → valid JSON.

Every test decodes with a real engine (tiny model, random weights) or walks
the token tables directly; the invariant is that *anything* the constrained
decoder emits parses as JSON valid under the schema.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.grammar import (
    JsonSchemaGrammar,
    TokenGrammar,
    compile_tool_call_grammar,
)
from fei_tpu.engine.tokenizer import ByteTokenizer


def _accepts(tg: TokenGrammar, text: str) -> bool:
    ids = tg.tokenizer.encode(text)
    return tg.walk(ids) == tg.accept


@pytest.fixture(scope="module")
def tok():
    return ByteTokenizer()


class TestCharDFA:
    def test_flat_object(self, tok):
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {
                    "pattern": {"type": "string"},
                    "limit": {"type": "integer"},
                },
            },
            tok,
        )
        assert _accepts(tg, '{"pattern":"*.py","limit":10}')
        assert _accepts(tg, '{"pattern":"a\\"b","limit":-3}')
        assert not _accepts(tg, '{"limit":10,"pattern":"x"}')  # fixed order
        assert not _accepts(tg, '{"pattern":"x","limit":1.5}')  # int, not float
        assert not _accepts(tg, '{"pattern":"x"}')  # missing property

    def test_number_and_boolean(self, tok):
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {
                    "score": {"type": "number"},
                    "flag": {"type": "boolean"},
                },
            },
            tok,
        )
        assert _accepts(tg, '{"score":3.25,"flag":true}')
        assert _accepts(tg, '{"score":-7,"flag":false}')
        assert not _accepts(tg, '{"score":.5,"flag":true}')  # bare leading dot
        assert not _accepts(tg, '{"score":1,"flag":maybe}')

    def test_enum(self, tok):
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {
                    "mode": {"enum": ["fast", "full", "files"]},
                },
            },
            tok,
        )
        assert _accepts(tg, '{"mode":"fast"}')
        assert _accepts(tg, '{"mode":"files"}')  # shared "f" prefix
        assert not _accepts(tg, '{"mode":"slow"}')

    def test_array_and_nested_object(self, tok):
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {
                    "names": {"type": "array", "items": {"type": "string"}},
                    "opts": {
                        "type": "object",
                        "properties": {"depth": {"type": "integer"}},
                    },
                },
            },
            tok,
        )
        assert _accepts(tg, '{"names":["a","b"],"opts":{"depth":2}}')
        assert _accepts(tg, '{"names":[],"opts":{"depth":0}}')
        assert not _accepts(tg, '{"names":["a",],"opts":{"depth":2}}')

    def test_enum_prefix_values(self, tok):
        """Enum values whose encodings are prefixes of each other (1 / 12):
        both must be generatable and nothing beyond them legal."""
        for order in ([1, 12], [12, 1]):
            tg = compile_tool_call_grammar(
                {"type": "object", "properties": {"n": {"enum": order}}}, tok
            )
            assert _accepts(tg, '{"n":1}')
            assert _accepts(tg, '{"n":12}')
            assert not _accepts(tg, '{"n":122}')
            assert not _accepts(tg, '{"n":2}')

    def test_no_leading_zeros(self, tok):
        tg = compile_tool_call_grammar(
            {"type": "object", "properties": {"n": {"type": "number"}}}, tok
        )
        assert _accepts(tg, '{"n":0}')
        assert _accepts(tg, '{"n":0.5}')
        assert _accepts(tg, '{"n":-0.5}')
        assert not _accepts(tg, '{"n":012}')  # json.loads rejects this
        assert not _accepts(tg, '{"n":-01}')

    def test_top_level_number_terminates(self, tok):
        """A bare number grammar must be able to stop (stop tokens legal in
        the digit loop) and its forced-completion distance must be finite."""
        tg = TokenGrammar(JsonSchemaGrammar({"type": "integer"}), tok)
        s = tg.walk(tok.encode("42"))
        assert s >= 0
        assert tg.mask_table[s, tok.eos_token_id]
        assert tg.min_dist[s] <= 1
        assert tg.walk(tok.encode("42") + [tok.eos_token_id]) == tg.accept

    def test_null_and_union(self, tok):
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {"v": {"type": ["string", "null"]}},
            },
            tok,
        )
        assert _accepts(tg, '{"v":"x"}')
        assert _accepts(tg, '{"v":null}')
        assert not _accepts(tg, '{"v":3}')

    def test_optional_properties_skippable(self, tok):
        """Properties absent from ``required`` may be skipped (in schema
        order); required ones may not — round-1 advisory finding."""
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {
                    "pattern": {"type": "string"},
                    "path": {"type": "string"},
                    "limit": {"type": "integer"},
                },
                "required": ["pattern"],
            },
            tok,
        )
        assert _accepts(tg, '{"pattern":"x"}')
        assert _accepts(tg, '{"pattern":"x","limit":3}')
        assert _accepts(tg, '{"pattern":"x","path":"p"}')
        assert _accepts(tg, '{"pattern":"x","path":"p","limit":3}')
        assert not _accepts(tg, '{"path":"p"}')  # missing required
        assert not _accepts(tg, '{}')
        assert not _accepts(tg, '{"limit":3,"pattern":"x"}')  # order fixed

    def test_all_optional_allows_empty_object(self, tok):
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {"a": {"type": "integer"}, "b": {"type": "boolean"}},
                "required": [],
            },
            tok,
        )
        assert _accepts(tg, "{}")
        assert _accepts(tg, '{"a":1}')
        assert _accepts(tg, '{"b":true}')
        assert _accepts(tg, '{"a":1,"b":false}')
        assert not _accepts(tg, '{"b":false,"a":1}')

    def test_no_required_key_keeps_all_mandatory(self, tok):
        """Without a ``required`` list the generator still emits every
        property (deterministic reading of unannotated schemas)."""
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {"a": {"type": "integer"}, "b": {"type": "boolean"}},
            },
            tok,
        )
        assert _accepts(tg, '{"a":1,"b":true}')
        assert not _accepts(tg, '{"a":1}')

    def test_unknown_required_name_rejected(self, tok):
        """A required name not present in properties is a schema bug; the
        compiler must fail loudly, not silently make everything optional."""
        from fei_tpu.utils.errors import EngineError

        with pytest.raises(EngineError):
            compile_tool_call_grammar(
                {
                    "type": "object",
                    "properties": {"query": {"type": "string"}},
                    "required": ["Query"],
                },
                tok,
            )

    def test_shared_prefix_property_names(self, tok):
        tg = compile_tool_call_grammar(
            {
                "type": "object",
                "properties": {
                    "file": {"type": "string"},
                    "file_path": {"type": "string"},
                },
                "required": ["file_path"],
            },
            tok,
        )
        assert _accepts(tg, '{"file":"a","file_path":"b"}')
        assert _accepts(tg, '{"file_path":"b"}')
        assert not _accepts(tg, '{"file":"a"}')

    def test_optional_schema_constrained_decode_parses(self, tok):
        """Sampled constrained decode over an optional-property schema must
        always produce schema-valid JSON (required present, order kept)."""
        engine = InferenceEngine.from_config(
            "tiny", dtype=jnp.float32, seed=0, tokenizer="byte",
            max_seq_len=256, num_layers=2,
        )
        schema = {
            "type": "object",
            "properties": {
                "query": {"type": "string"},
                "recursive": {"type": "boolean"},
                "limit": {"type": "integer"},
            },
            "required": ["query"],
        }
        tg = compile_tool_call_grammar(schema, engine.tokenizer)
        for seed in (0, 1, 2, 3):
            gen = GenerationConfig(max_new_tokens=100, temperature=1.2, seed=seed)
            res = engine.generate(
                engine.tokenizer.encode("go:"), gen,
                logit_mask_fn=tg.logit_mask_fn(max_tokens=100),
            )
            obj = json.loads(res.text)
            assert "query" in obj
            assert set(obj) <= {"query", "recursive", "limit"}

    def test_stop_only_at_accept(self, tok):
        tg = compile_tool_call_grammar(
            {"type": "object", "properties": {"n": {"type": "integer"}}}, tok
        )
        mid = tg.walk(tok.encode('{"n":4'))
        assert mid >= 0 and mid != tg.accept
        assert not tg.mask_table[mid, tok.eos_token_id]
        done = tg.walk(tok.encode('{"n":42}'))
        assert done == tg.accept
        assert tg.mask_table[done, tok.eos_token_id]


class TestConstrainedDecode:
    @pytest.fixture(scope="class")
    def engine(self):
        return InferenceEngine.from_config(
            "tiny", dtype=jnp.float32, seed=0, tokenizer="byte",
            max_seq_len=256, num_layers=2,
        )

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_sampled_output_is_schema_valid(self, engine, seed):
        schema = {
            "type": "object",
            "properties": {
                "file_path": {"type": "string"},
                "recursive": {"type": "boolean"},
                "max_results": {"type": "integer"},
            },
        }
        tg = compile_tool_call_grammar(schema, engine.tokenizer)
        gen = GenerationConfig(max_new_tokens=120, temperature=1.0, seed=seed)
        result = engine.generate(
            engine.tokenizer.encode("call the tool:"),
            gen,
            logit_mask_fn=tg.logit_mask_fn(max_tokens=120),
        )
        text = result.text
        obj = json.loads(text)
        assert set(obj) == {"file_path", "recursive", "max_results"}
        assert isinstance(obj["file_path"], str)
        assert isinstance(obj["recursive"], bool)
        assert isinstance(obj["max_results"], int)

    def test_greedy_completes(self, engine):
        schema = {"type": "object", "properties": {"q": {"type": "string"}}}
        tg = compile_tool_call_grammar(schema, engine.tokenizer)
        gen = GenerationConfig(max_new_tokens=80, temperature=0.8, seed=7)
        result = engine.generate(
            engine.tokenizer.encode("x"), gen,
            logit_mask_fn=tg.logit_mask_fn(max_tokens=80),
        )
        obj = json.loads(result.text)
        assert isinstance(obj["q"], str)


class TestRealVocabScale:
    """VERDICT r1 weak-spot #9: the lift and the mask pipeline at Llama-3
    vocab scale (128,256), with a locally built HF tokenizer (zero egress)."""

    @pytest.fixture(scope="class")
    def big_tok(self, tmp_path_factory):
        transformers = pytest.importorskip("transformers")
        tokenizers = pytest.importorskip("tokenizers")
        from tokenizers import Tokenizer, decoders, models, pre_tokenizers

        V = 128_256
        # realistic-ish multi-char tokens: printable singles, then pairs,
        # then triples until the vocab is full
        chars = [chr(i) for i in range(32, 127)]
        vocab: dict[str, int] = {}

        def add(tok):
            if tok not in vocab and len(vocab) < V - 2:
                vocab[tok] = len(vocab)

        for c in chars:
            add(c)
        for a in chars:
            for b in chars:
                add(a + b)
        import itertools

        for a, b, c in itertools.product(chars, chars, chars):
            if len(vocab) >= V - 2:
                break
            add(a + b + c)
        t = Tokenizer(models.WordLevel(vocab, unk_token=" "))
        t.pre_tokenizer = pre_tokenizers.Split("", "isolated")
        t.decoder = decoders.Fuse()
        fast = transformers.PreTrainedTokenizerFast(
            tokenizer_object=t, bos_token="<|bos|>", eos_token="<|eot|>"
        )
        path = tmp_path_factory.mktemp("bigtok")
        fast.save_pretrained(str(path))
        from fei_tpu.engine.tokenizer import HFTokenizer

        tok = HFTokenizer(str(path))
        assert tok.vocab_size >= 128_000
        return tok

    def test_lift_cost_and_size(self, big_tok):
        schema = {
            "type": "object",
            "properties": {
                "file_path": {"type": "string"},
                "pattern": {"type": "string"},
                "max_results": {"type": "integer"},
                "recursive": {"type": "boolean"},
            },
            "required": ["file_path", "pattern"],
        }
        tg = compile_tool_call_grammar(schema, big_tok)
        V = big_tok.vocab_size
        n_states = tg.table.shape[0]
        # measured + recorded: the vectorized lift must stay interactive
        assert tg.lift_seconds < 60, f"lift took {tg.lift_seconds:.1f}s"
        # int16 at this scale (state count far below 32k)
        assert tg.table.dtype == np.int16
        assert tg.table_bytes == n_states * V * 2
        print(
            f"\n[lift] states={n_states} vocab={V} "
            f"time={tg.lift_seconds:.2f}s table={tg.table_bytes/1e6:.1f}MB"
        )

    def test_constrained_decode_at_scale(self, big_tok):
        """Constrained output through the mask pipeline parses and matches
        the schema at 128k vocab."""
        import json

        schema = {
            "type": "object",
            "properties": {
                "path": {"type": "string"},
                "limit": {"type": "integer"},
            },
            "required": ["path", "limit"],
        }
        tg = compile_tool_call_grammar(schema, big_tok)
        rng = np.random.default_rng(0)
        # random legal walk using the mask tables (tokenizer-level check —
        # the engine pipeline is covered by TestOnDeviceConstrained)
        s, out = tg.entry, []
        for _ in range(64):
            if s == tg.accept or s < 0:
                break
            legal = np.flatnonzero(tg.mask_table[s])
            assert legal.size, "dead state in constrained walk"
            t = int(rng.choice(legal))
            out.append(t)
            s = int(tg.table[s, t])
        text = big_tok.decode(out)
        if s != tg.accept:  # walk may still be mid-object; only check prefix
            assert text.startswith('{"path":"')
        else:
            obj = json.loads(text)
            assert set(obj) == {"path", "limit"}


class TestOnDeviceConstrained:
    @pytest.fixture(scope="class")
    def engine(self):
        return InferenceEngine.from_config(
            "tiny", dtype=jnp.float32, seed=0, tokenizer="byte",
            max_seq_len=256, num_layers=2,
        )

    @pytest.mark.parametrize("seed", [0, 5])
    def test_matches_host_masked_stream(self, engine, seed):
        """The on-device DFA scan must emit exactly the tokens the host
        per-step mask path emits (same seed, same sampling)."""
        schema = {
            "type": "object",
            "properties": {
                "query": {"type": "string"},
                "limit": {"type": "integer"},
            },
        }
        tg = compile_tool_call_grammar(schema, engine.tokenizer)
        gen = GenerationConfig(max_new_tokens=96, temperature=1.0, seed=seed)
        want = engine.generate(
            engine.tokenizer.encode("x"), gen,
            logit_mask_fn=tg.logit_mask_fn(max_tokens=96),
        ).token_ids
        got = engine.generate_constrained(
            engine.tokenizer.encode("x"), tg, gen, chunk=16
        ).token_ids
        assert got == want

    def test_output_always_parses(self, engine):
        schema = {
            "type": "object",
            "properties": {
                "names": {"type": "array", "items": {"type": "string"}},
                "deep": {
                    "type": "object",
                    "properties": {"flag": {"type": "boolean"}},
                },
            },
        }
        tg = compile_tool_call_grammar(schema, engine.tokenizer)
        for seed in (1, 2, 3):
            gen = GenerationConfig(max_new_tokens=120, temperature=1.2, seed=seed)
            res = engine.generate_constrained(
                engine.tokenizer.encode("call:"), tg, gen, chunk=32
            )
            obj = json.loads(res.text)
            assert isinstance(obj["names"], list)
            assert isinstance(obj["deep"]["flag"], bool)

    def test_paged_constrained_matches_dense(self):
        """generate_constrained must honor paged mode and produce the same
        tokens as the dense engine."""
        schema = {"type": "object", "properties": {"q": {"type": "string"}}}
        kw = dict(dtype=jnp.float32, seed=0, tokenizer="byte",
                  max_seq_len=256, num_layers=2)
        gen = GenerationConfig(max_new_tokens=60, temperature=1.0, seed=4)
        dense = InferenceEngine.from_config("tiny", **kw)
        tg = compile_tool_call_grammar(schema, dense.tokenizer)
        want = dense.generate_constrained(
            dense.tokenizer.encode("y"), tg, gen, chunk=16
        ).token_ids
        paged = InferenceEngine.from_config(
            "tiny", paged=True, page_size=16, **kw
        )
        got = paged.generate_constrained(
            paged.tokenizer.encode("y"), tg, gen, chunk=16
        ).token_ids
        assert got == want
        assert paged._allocator.free_pages == paged._allocator.num_pages - 1
        json.loads(paged.tokenizer.decode(got))


class TestConstrainedServingInteractions:
    def test_constrained_chunked_prefill_prefix_cache(self, monkeypatch):
        """Grammar-constrained decode through the scheduler with a
        chunk-prefilled long prompt AND prefix caching: the mask pipeline,
        chunked admission, and page reuse must compose — constrained output
        still parses, and a second request reuses the cached prefix."""
        monkeypatch.setenv("FEI_TPU_PREFILL_CHUNK", "16")
        schema = {"type": "object", "properties": {"q": {"type": "string"}}}
        eng = InferenceEngine.from_config(
            "tiny", paged=True, page_size=16, batch_size=2,
            dtype=jnp.float32, seed=0, tokenizer="byte",
            max_seq_len=256, num_layers=2, prefix_cache=True,
        )
        tg = compile_tool_call_grammar(schema, eng.tokenizer)
        gen = GenerationConfig(max_new_tokens=48, temperature=0.0)
        system = "shared system prompt " * 4  # > several pages, chunked
        for i in range(2):
            prompt = eng.tokenizer.encode(system + f"request {i}", add_bos=True)
            toks = list(
                eng.scheduler.stream(
                    prompt, gen, logit_mask_fn=tg.logit_mask_fn(gen.max_new_tokens)
                )
            )
            text = eng.tokenizer.decode(
                [t for t in toks if t not in eng.tokenizer.stop_token_ids]
            )
            obj = json.loads(text)  # constrained output must parse
            assert set(obj).issubset({"q"})
        assert len(eng.scheduler._prefix._entries) > 0
