"""Memdir subsystem tests: store atomicity, search QL, filters, archiver,
folders, HTTP server (in-process), CLI."""

import json
import os
import time
import urllib.request

import pytest

from fei_tpu.memory.memdir.archiver import MemoryArchiver, Rule
from fei_tpu.memory.memdir.filters import FilterManager, MemoryFilter
from fei_tpu.memory.memdir.folders import MemdirFolderManager
from fei_tpu.memory.memdir.search import (
    format_results,
    parse_search_args,
    search_memories,
)
from fei_tpu.memory.memdir.store import (
    MemdirStore,
    generate_filename,
    parse_filename,
    parse_memory_file,
    render_memory_file,
)
from fei_tpu.utils.errors import MemoryError_


@pytest.fixture
def store(tmp_path):
    return MemdirStore(str(tmp_path / "Memdir"))


class TestStore:
    def test_filename_roundtrip(self):
        name = generate_filename("FS")
        meta = parse_filename(name)
        assert meta is not None and meta["flags"] == "FS"

    def test_file_codec(self):
        raw = render_memory_file({"Subject": "s", "Tags": "a,b"}, "body\ntext")
        headers, body = parse_memory_file(raw)
        assert headers == {"Subject": "s", "Tags": "a,b"}
        assert body == "body\ntext"

    def test_save_is_atomic_delivery(self, store):
        mem = store.save("hello world", tags=["x"])
        new_dir = os.path.join(store.base, "new")
        assert os.listdir(new_dir) == [mem.filename]
        assert os.listdir(os.path.join(store.base, "tmp")) == []

    def test_get_and_mark_seen(self, store):
        mem = store.save("content here")
        got = store.get(mem.id)
        assert got.content == "content here" and got.status == "new"
        seen = store.mark_seen(mem.id)
        assert seen.status == "cur" and "S" in seen.flags
        assert store.get(mem.id).status == "cur"

    def test_move_across_folders(self, store):
        mem = store.save("task item")
        moved = store.move(mem.id, ".Projects")
        assert moved.folder == ".Projects" and moved.status == "cur"
        assert store.list("", "new") == []

    def test_flags_rewrite(self, store):
        mem = store.save("x", flags="S")
        updated = store.update_flags(mem.id, "FP")
        assert updated.flags == "FP"

    def test_soft_delete_to_trash(self, store):
        mem = store.save("bye")
        assert store.delete(mem.id)
        assert store.get(mem.id).folder == ".Trash"

    def test_hard_delete(self, store):
        mem = store.save("gone")
        assert store.delete(mem.id, hard=True)
        assert store.get(mem.id) is None

    def test_folder_traversal_rejected(self, store):
        with pytest.raises(MemoryError_):
            store.folder_path("../evil")

    def test_rewrite_headers(self, store):
        mem = store.save("body", headers={"Subject": "old"})
        store.rewrite_headers(mem.id, {"Status": "done"})
        got = store.get(mem.id)
        assert got.headers["Status"] == "done" and got.content == "body"


class TestSearch:
    def seed(self, store):
        store.save("python decorators are neat", tags=["python", "learning"])
        store.save("urgent: fix the build", flags="FP",
                   headers={"Subject": "urgent: fix the build"})
        store.save("grocery list: milk", tags=["personal"])
        m = store.save("old note about jax")
        # backdate the old note by renaming with an old timestamp
        old_name = m.filename
        parts = old_name.split(".")
        parts[0] = str(int(time.time()) - 120 * 86400)
        new_name = ".".join(parts)
        os.rename(os.path.join(store.base, "new", old_name),
                  os.path.join(store.base, "new", new_name))

    def test_keyword_or(self, store):
        self.seed(store)
        q = parse_search_args("python milk")
        res = search_memories(store, q)
        assert len(res) == 2

    def test_tag_and_flag_filters(self, store):
        self.seed(store)
        assert len(search_memories(store, parse_search_args("#python"))) == 1
        assert len(search_memories(store, parse_search_args("+F"))) == 1

    def test_field_conditions(self, store):
        self.seed(store)
        res = search_memories(store, parse_search_args("Subject:urgent"))
        assert len(res) == 1
        assert len(search_memories(store, parse_search_args("status=new"))) == 4
        assert search_memories(store, parse_search_args("status!=new")) == []

    def test_relative_date(self, store):
        self.seed(store)
        res = search_memories(store, parse_search_args("date<now-90d"))
        assert len(res) == 1 and "jax" in res[0].content

    def test_regex_and_limit_sort(self, store):
        self.seed(store)
        res = search_memories(store, parse_search_args(r"/fix the \w+/"))
        assert len(res) == 1
        res = search_memories(store, parse_search_args("sort:date limit:2"))
        assert len(res) == 2
        assert res[0].timestamp <= res[1].timestamp  # ascending sort

    def test_formats(self, store):
        self.seed(store)
        mems = search_memories(store, parse_search_args("#python"))
        assert "python" in format_results(mems, "compact")
        parsed = json.loads(format_results(mems, "json"))
        assert parsed[0]["tags"] == ["python", "learning"]
        assert format_results(mems, "csv").startswith("id,folder")


class TestFilters:
    def test_default_rules_route_and_promote(self, store):
        store.save("learning python generators today")
        store.save("just a plain note")
        stats = FilterManager(store).process_memories()
        assert stats["processed"] == 2
        # python memory moved to .Projects/Python with tag
        routed = store.list(".Projects/Python", "cur", with_content=True)
        assert len(routed) == 1 and "python" in routed[0].tags
        # plain note promoted new→cur in place
        assert len(store.list("", "cur")) == 1
        assert store.list("", "new") == []

    def test_custom_filter_flags(self, store):
        store.save("deploy tonight", headers={"Subject": "urgent deploy"})
        filt = MemoryFilter("urgent", {"Subject": "urgent"}, {"flag": "F"})
        FilterManager(store, [filt]).process_memories()
        mems = store.list("", "cur")
        assert mems and "F" in mems[0].flags


class TestArchiver:
    def _backdate(self, store, mem, days):
        parts = mem.filename.split(".")
        parts[0] = str(int(time.time()) - days * 86400)
        new_name = ".".join(parts)
        os.rename(os.path.join(store.folder_path(mem.folder), mem.status, mem.filename),
                  os.path.join(store.folder_path(mem.folder), mem.status, new_name))

    def test_age_archive(self, store):
        old = store.save("ancient wisdom")
        self._backdate(store, old, 120)
        store.save("fresh note")
        stats = MemoryArchiver(store).archive_old_memories()
        assert stats["archived"] == 1
        year = time.localtime(time.time() - 120 * 86400).tm_year
        assert len(store.list(f".Archive/{year}", "cur")) == 1

    def test_trash_expiry_counts_from_trashing_not_creation(self, store):
        old = store.save("created long ago")
        self._backdate(store, old, 120)
        old = store.get(old.id)
        store.delete(old.id)  # just moved to trash now
        # same maintenance pass must NOT delete it: 0 days in trash
        assert MemoryArchiver(store).empty_trash() == 0
        assert store.get(old.id) is not None
        # once it has sat in trash past trash_days it goes
        removed = MemoryArchiver(store).empty_trash(now=time.time() + 45 * 86400)
        assert removed == 1 and store.get(old.id) is None

    def test_rule_tag_trash(self, store):
        store.save("scratch", tags=["tmp"])
        arch = MemoryArchiver(store)
        arch.add_rule(Rule("tmp-to-trash", tags=["tmp"], action="trash"))
        stats = arch.archive_old_memories()
        assert stats["trashed"] == 1

    def test_retention_evicts_least_important(self, store):
        keep = store.save("keep me", flags="P")
        store.save("evict me 1")
        store.save("evict me 2")
        evicted = MemoryArchiver(store).apply_retention("", max_memories=1)
        assert evicted == 2
        assert store.get(keep.id).folder == ""

    def test_status_rewrite(self, store):
        store.save("[x] finished the thing")
        updated = MemoryArchiver(store).update_statuses()
        assert updated == 1
        mems = store.list("", "new", with_content=True)
        assert mems[0].headers["Status"] == "completed"


class TestFolders:
    def test_create_normalizes_dot(self, store):
        mgr = MemdirFolderManager(store)
        assert mgr.create_folder("Projects/Go") == ".Projects/Go"
        assert ".Projects/Go" in mgr.list_folders()

    def test_delete_protects_special_and_preserves(self, store):
        mgr = MemdirFolderManager(store)
        with pytest.raises(MemoryError_):
            mgr.delete_folder(".Trash")
        mgr.create_folder("Tmp")
        mem = store.save("in tmp", folder=".Tmp")
        with pytest.raises(MemoryError_):
            mgr.delete_folder("Tmp")
        mgr.delete_folder("Tmp", force=True)
        assert store.get(mem.id).folder == ".Trash"

    def test_rename_and_stats(self, store):
        mgr = MemdirFolderManager(store)
        mgr.create_folder("A")
        store.save("x", folder=".A", flags="F", tags=["t1"])
        mgr.rename_folder("A", "B")
        stats = mgr.get_folder_stats("B")
        assert stats["total"] == 1 and stats["by_flag"]["F"] == 1
        assert stats["by_tag"] == {"t1": 1}

    def test_copy_and_bulk_tag(self, store):
        mgr = MemdirFolderManager(store)
        store.save("one")
        store.save("two")
        assert mgr.copy_folder("", "Backup") == 2
        assert mgr.bulk_tag_folder("Backup", ["archived"]) == 2
        mems = store.list(".Backup", "new", with_content=True)
        assert all("archived" in m.tags for m in mems)

    def test_make_symlinks(self, store):
        """Dot-less navigation links (parity: ref folders.py:382)."""
        import os

        mgr = MemdirFolderManager(store)
        mgr.create_folder("Projects/Go")
        links = mgr.make_symlinks()
        by_name = {os.path.relpath(l, os.path.join(store.base, "links")): l
                   for l in links}
        assert "Projects/Go" in by_name
        link = by_name["Projects/Go"]
        assert os.path.islink(link)
        assert os.path.realpath(link) == os.path.realpath(
            store.folder_path(".Projects/Go")
        )
        # idempotent: second run refreshes, never errors
        assert sorted(mgr.make_symlinks()) == sorted(links)
        # refuses to clobber a real file
        clobber = os.path.join(store.base, "links", "Real")
        open(clobber, "w").write("x")
        mgr.create_folder("Real")
        with pytest.raises(MemoryError_):
            mgr.make_symlinks()


class TestServer:
    @pytest.fixture
    def server(self, tmp_path):
        from fei_tpu.memory.memdir.server import MemdirServer

        srv = MemdirServer(str(tmp_path / "Memdir"), port=0, api_key="testkey")
        srv.start_background()
        yield srv
        srv.shutdown()

    def _req(self, server, method, path, body=None, key="testkey"):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"X-API-Key": key, "Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read())

    def test_health_no_auth(self, server):
        status, body = self._req(server, "GET", "/health", key="wrong")
        assert status == 200 and body["status"] == "ok"

    def test_auth_required(self, server):
        status, body = self._req(server, "GET", "/memories", key="wrong")
        assert status == 401

    def test_crud_cycle(self, server):
        status, body = self._req(server, "POST", "/memories",
                                 {"content": "via http", "tags": ["api"]})
        assert status == 201
        mid = body["memory"]["id"]
        status, body = self._req(server, "GET", f"/memories/{mid}")
        assert status == 200 and body["memory"]["content"] == "via http"
        status, body = self._req(server, "PUT", f"/memories/{mid}",
                                 {"folder": ".Projects"})
        assert status == 200 and body["memory"]["folder"] == ".Projects"
        status, body = self._req(server, "DELETE", f"/memories/{mid}")
        assert status == 200
        status, body = self._req(server, "GET", f"/memories/{mid}")
        assert body["memory"]["folder"] == ".Trash"

    def test_search_endpoint(self, server):
        self._req(server, "POST", "/memories",
                  {"content": "searchable python text", "tags": ["python"]})
        status, body = self._req(
            server, "GET", "/search?q=%23python&with_content=true"
        )
        assert status == 200 and body["count"] == 1
        assert "searchable" in body["results"][0]["content"]

    def test_folders_and_filters(self, server):
        status, body = self._req(server, "POST", "/folders", {"name": "Inbox"})
        assert status == 201 and body["folder"] == ".Inbox"
        status, body = self._req(server, "GET", "/folders")
        assert ".Inbox" in body["folders"]
        self._req(server, "POST", "/memories", {"content": "python rocks"})
        status, body = self._req(server, "POST", "/filters/run", {})
        assert status == 200 and body["stats"]["processed"] == 1


class TestCLI:
    def test_create_list_search_view(self, tmp_path, capsys):
        from fei_tpu.memory.memdir.cli import main

        base = str(tmp_path / "Memdir")
        assert main(["--base", base, "create", "hello from cli",
                     "--tags", "cli,demo"]) == 0
        out = capsys.readouterr().out
        mid = out.split()[1]
        assert main(["--base", base, "list"]) == 0
        assert "hello from cli" in capsys.readouterr().out
        assert main(["--base", base, "search", "#cli"]) == 0
        assert mid in capsys.readouterr().out
        assert main(["--base", base, "view", mid]) == 0
        assert "hello from cli" in capsys.readouterr().out
        # view marks seen → promoted to cur
        assert main(["--base", base, "list", "--status", "cur"]) == 0
        assert mid in capsys.readouterr().out


class TestSamples:
    def test_create_samples_populates_folders(self, tmp_path):
        from fei_tpu.memory.memdir.samples import create_samples
        from fei_tpu.memory.memdir.search import parse_search_args, search_memories
        from fei_tpu.memory.memdir.store import MemdirStore

        store = MemdirStore(str(tmp_path / "Memdir"))
        n = create_samples(store)
        assert n == 20
        folders = store.list_folders()
        for f in ("", ".Projects", ".ToDoLater", ".Archive", ".Trash"):
            assert f in folders
        assert len(search_memories(store, parse_search_args("#tpu"))) >= 3
        # archive folder got its seeded entries
        archived = search_memories(store, parse_search_args("folder:.Archive"))
        assert len(archived) >= 2
