"""Paged-NATIVE chunked prefill: admission writes K/V straight into pool
pages and attends via the multi-query block kernel through a one-slot pool
view — no dense staging cache, no completion scatter, no prefix gather.
Must be token-identical to the dense-staging path it replaces
(FEI_TPU_PAGED_PREFILL=0), including prefix-cache reuse and int8 pools.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp
import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine

pytestmark = pytest.mark.slow  # fast lane: -m 'not slow' (docs/TESTING.md)

PROMPT = [(7 * i + 11) % 200 + 10 for i in range(560)]  # 2 chunks + partial
GEN = GenerationConfig(max_new_tokens=12, ignore_eos=True)


def _engine(monkeypatch, native: bool, **kw):
    monkeypatch.setenv("FEI_TPU_PAGED_PREFILL", "1" if native else "0")
    # fp32: the native path's block-kernel accumulation order differs from
    # the staging path's dense forward at bf16 rounding level, and a
    # 700-token random tiny model has near-tie argmaxes that flip on
    # ~1e-2 logit noise. fp32 keeps the comparison about CORRECTNESS
    # (state machine, page writes, masks), not accumulation order.
    kw.setdefault("dtype", jnp.float32)
    return InferenceEngine.from_config(
        "tiny", paged=True, batch_size=2, max_seq_len=2048, **kw
    )


class TestPagedNativePrefill:
    def test_long_prompt_matches_staging_path(self, monkeypatch):
        legacy = _engine(monkeypatch, native=False)
        want = list(legacy.scheduler.stream(PROMPT, GEN))

        native = _engine(monkeypatch, native=True)
        got = list(native.scheduler.stream(PROMPT, GEN))
        assert got == want
        # the staging machinery must never have compiled
        assert native.scheduler._chunk_jit == {}
        assert native.scheduler._gather_jit == {}
        assert native.scheduler._pchunk_jit  # and the native path did

    def test_interleaves_with_live_decode(self, monkeypatch):
        gen_live = GenerationConfig(max_new_tokens=48, ignore_eos=True)
        live_prompt = list(range(40, 72))
        legacy = _engine(monkeypatch, native=False)
        want_live = list(legacy.scheduler.stream(live_prompt, gen_live))
        want_long = list(legacy.scheduler.stream(PROMPT, GEN))

        native = _engine(monkeypatch, native=True)
        results: dict = {}
        started = threading.Event()

        def live():
            out = []
            for i, tok in enumerate(
                native.scheduler.stream(live_prompt, gen_live)
            ):
                out.append(tok)
                if i == 4:
                    started.set()
            results["live"] = out

        def long_admit():
            started.wait(timeout=60)
            results["long"] = list(native.scheduler.stream(PROMPT, GEN))

        ts = [threading.Thread(target=live), threading.Thread(target=long_admit)]
        [t.start() for t in ts]
        [t.join(timeout=600) for t in ts]
        # chunks of the native admission interleave with the live stream
        # and neither corrupts the other
        assert results["live"] == want_live
        assert results["long"] == want_long

    def test_prefix_cache_hit_reuses_pages_in_place(self, monkeypatch):
        legacy = _engine(monkeypatch, native=False, prefix_cache=True)
        l1 = list(legacy.scheduler.stream(PROMPT, GEN))
        l2 = list(legacy.scheduler.stream(PROMPT, GEN))  # gathered prefix

        native = _engine(monkeypatch, native=True, prefix_cache=True)
        n1 = list(native.scheduler.stream(PROMPT, GEN))
        n2 = list(native.scheduler.stream(PROMPT, GEN))  # in-place prefix
        assert n1 == l1
        assert n2 == l2 == n1
        # prefix reuse happened without the gather machinery
        assert native.scheduler._gather_jit == {}

    def test_int8_pool_parity(self, monkeypatch):
        legacy = _engine(monkeypatch, native=False, kv_quant="int8")
        want = list(legacy.scheduler.stream(PROMPT, GEN))
        native = _engine(monkeypatch, native=True, kv_quant="int8")
        got = list(native.scheduler.stream(PROMPT, GEN))
        assert got == want

    def test_partial_final_chunk_and_page_misalignment(self, monkeypatch):
        # n chosen so the final chunk is partial AND n is not page-aligned
        prompt = PROMPT[:397]
        legacy = _engine(monkeypatch, native=False)
        want = list(legacy.scheduler.stream(prompt, GEN))
        native = _engine(monkeypatch, native=True)
        got = list(native.scheduler.stream(prompt, GEN))
        assert got == want

    def test_kernel_failure_falls_back_to_staging(self, monkeypatch):
        """A compile-stage failure of the native chunk program (the
        realistic Mosaic-rejection case) must not kill the streams: the
        admission restarts on the dense-staging path, permanently."""
        legacy = _engine(monkeypatch, native=False)
        want = list(legacy.scheduler.stream(PROMPT, GEN))

        native = _engine(monkeypatch, native=True)

        def boom(C, final):
            def fn(*a, **k):
                raise RuntimeError("Mosaic said no")

            return fn

        monkeypatch.setattr(native.scheduler, "_paged_chunk_fn", boom)
        got = list(native.scheduler.stream(PROMPT, GEN))
        assert got == want
        assert native.scheduler.paged_native_prefill is False
        # and the NEXT admission goes straight to staging
        got2 = list(native.scheduler.stream(PROMPT, GEN))
        assert got2 == want

    def test_near_capacity_prompt_with_prefix_pads_hit_null_page(
        self, monkeypatch
    ):
        """The clamp hazard: a prefix-hit admission near max_seq_len whose
        final chunk's pad positions run past the table capacity. The pads
        must land in the null page, not clamp onto the last real page and
        overwrite live prompt K/V."""
        # width = 2048/64 = 32 pages; prompt 2030 + budget 12 fills the
        # table; prefix from run 1 makes run 2's chunk starts unaligned
        prompt = [(3 * i + 5) % 150 + 30 for i in range(2030)]
        gen = GenerationConfig(max_new_tokens=12, ignore_eos=True)
        legacy = _engine(monkeypatch, native=False, prefix_cache=True)
        l1 = list(legacy.scheduler.stream(prompt, gen))
        l2 = list(legacy.scheduler.stream(prompt, gen))

        native = _engine(monkeypatch, native=True, prefix_cache=True)
        n1 = list(native.scheduler.stream(prompt, gen))
        n2 = list(native.scheduler.stream(prompt, gen))  # prefix-hit run
        assert n1 == l1
        assert n2 == l2

    def test_kernel_failure_with_prefix_requeues(self, monkeypatch):
        """First-chunk failure on a PREFIX-HIT admission must also flip
        the flag and requeue — not fail this request forever."""
        legacy = _engine(monkeypatch, native=False, prefix_cache=True)
        w1 = list(legacy.scheduler.stream(PROMPT, GEN))
        w2 = list(legacy.scheduler.stream(PROMPT, GEN))

        native = _engine(monkeypatch, native=True, prefix_cache=True)
        first = list(native.scheduler.stream(PROMPT, GEN))  # native admit
        assert first == w1

        def boom(C, final):
            def fn(*a, **k):
                raise RuntimeError("Mosaic said no")

            return fn

        monkeypatch.setattr(native.scheduler, "_paged_chunk_fn", boom)
        second = list(native.scheduler.stream(PROMPT, GEN))  # prefix hit
        assert second == w2
        assert native.scheduler.paged_native_prefill is False


class TestSchedulerLifecycle:
    def test_idle_park_and_restart(self, monkeypatch):
        import time

        eng = _engine(monkeypatch, native=True)
        gen = GenerationConfig(max_new_tokens=4, ignore_eos=True)
        a = list(eng.scheduler.stream(list(range(20, 40)), gen))
        sched = eng.scheduler
        sched._IDLE_PARKS = 3  # park after ~0.3 s idle
        deadline = time.time() + 20
        while time.time() < deadline:
            t = sched._thread
            if t is None or not t.is_alive():
                break
            time.sleep(0.1)
        t = sched._thread
        assert t is None or not t.is_alive(), "loop never parked"
        # a new request restarts the loop transparently
        b = list(eng.scheduler.stream(list(range(20, 40)), gen))
        assert b == a

    def test_close_fails_inflight_and_restarts(self, monkeypatch):
        eng = _engine(monkeypatch, native=True)
        gen = GenerationConfig(max_new_tokens=4, ignore_eos=True)
        list(eng.scheduler.stream(list(range(20, 40)), gen))
        eng.close()
        # closed loop drains; a later submit restarts it
        got = list(eng.scheduler.stream(list(range(20, 40)), gen))
        assert len(got) == 4
