"""int8 paged KV cache: per-slot scales, in-kernel dequantization.

KV pages dominate serving HBM for the agent task loop (conversations grow
without bound); int8 pools halve KV bytes so one pool holds ~2x the
conversation tokens. These tests pin the write-path quantization, the
kernel's folded dequant against the bf16 oracle, and the end-to-end
scheduler path with an int8 pool.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.engine.paged_cache import (
    PagedKVCache,
    paged_attention_reference,
    quant_kv_rows,
    write_token_kv,
)
from fei_tpu.models.configs import get_model_config
from fei_tpu.ops.pallas import paged_attention


def _rand(key, shape):
    return jax.random.normal(key, shape) * 0.5


class TestQuantKVRows:
    def test_roundtrip_bound(self):
        x = _rand(jax.random.PRNGKey(0), (4, 2, 32))
        q, s = quant_kv_rows(x)
        back = q.astype(jnp.float32) * s[..., None]
        amax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
        assert np.all(
            np.abs(np.asarray(back) - np.asarray(x)) <= amax / 254 + 1e-7
        )

    def test_zero_rows_safe(self):
        q, s = quant_kv_rows(jnp.zeros((2, 3, 8)))
        assert not np.any(np.isnan(np.asarray(s)))


class TestInt8PagedKernel:
    def _setup(self, B=2, H=4, K=2, D=64, ps=16, pps=4, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        P = B * pps + 1
        # build an int8 pool from random bf16-scale values
        k_raw = _rand(ks[0], (P, K, ps, D))
        v_raw = _rand(ks[1], (P, K, ps, D))
        kq, ksc = quant_kv_rows(k_raw)  # [P,K,ps,D] int8, [P,K,ps]
        vq, vsc = quant_kv_rows(v_raw)
        ksc = ksc[:, :, None, :]  # [P, K, 1, ps]
        vsc = vsc[:, :, None, :]
        rng = np.random.default_rng(0)
        table = rng.permutation(np.arange(1, P))[: B * pps].reshape(B, pps)
        bt = jnp.asarray(table, jnp.int32)
        q = _rand(ks[2], (B, H, D))
        lengths = jnp.array([ps * pps - 3, 7][:B], jnp.int32)
        return q, kq, vq, ksc, vsc, bt, lengths

    def test_matches_dequant_oracle(self):
        q, kq, vq, ksc, vsc, bt, lengths = self._setup()
        want = paged_attention_reference(
            q, kq, vq, bt, lengths, k_scales=ksc, v_scales=vsc
        )
        got = paged_attention(
            q, kq, vq, bt, lengths, k_scales=ksc, v_scales=vsc
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=3e-3
        )

    def test_int8_close_to_fp_attention(self):
        """Quantize-dequantize error stays small end-to-end through the
        kernel (vs attention over the unquantized values)."""
        B, H, K, D, ps, pps = 1, 2, 2, 32, 8, 2
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        P = B * pps + 1
        k_raw = _rand(ks[0], (P, K, ps, D))
        v_raw = _rand(ks[1], (P, K, ps, D))
        q = _rand(ks[2], (B, H, D))
        bt = jnp.arange(1, 1 + B * pps, dtype=jnp.int32).reshape(B, pps)
        lengths = jnp.array([13], jnp.int32)

        fp = paged_attention(q, k_raw, v_raw, bt, lengths)
        kq, ksc = quant_kv_rows(k_raw)
        vq, vsc = quant_kv_rows(v_raw)
        got = paged_attention(
            q, kq, vq, bt, lengths,
            k_scales=ksc[:, :, None, :], v_scales=vsc[:, :, None, :],
        )
        rel = np.abs(np.asarray(got) - np.asarray(fp)).max()
        rel /= np.abs(np.asarray(fp)).max()
        assert rel < 0.05, f"int8 KV relative error {rel}"


class TestInt8WritePath:
    def test_write_token_roundtrip(self):
        K, ps, D, P = 2, 8, 16, 4
        kp = jnp.zeros((P, K, ps, D), jnp.int8)
        vp = jnp.zeros((P, K, ps, D), jnp.int8)
        ksc = jnp.ones((P, K, 1, ps), jnp.float32)
        vsc = jnp.ones((P, K, 1, ps), jnp.float32)
        bt = jnp.array([[2, 3]], jnp.int32)
        k_new = _rand(jax.random.PRNGKey(0), (1, K, D))
        v_new = _rand(jax.random.PRNGKey(1), (1, K, D))
        lengths = jnp.array([ps + 3], jnp.int32)  # lands in page 3, slot 3

        kp, vp, ksc, vsc = write_token_kv(
            kp, vp, k_new, v_new, bt, lengths, k_scales=ksc, v_scales=vsc
        )
        back = np.asarray(kp[3, :, 3, :], np.float32) * np.asarray(
            ksc[3, :, 0, 3]
        )[:, None]
        amax = np.abs(np.asarray(k_new[0])).max(axis=-1, keepdims=True)
        assert np.all(np.abs(back - np.asarray(k_new[0])) <= amax / 254 + 1e-7)


@pytest.mark.slow  # fast lane: -m 'not slow'
class TestInt8Serving:
    def test_scheduler_int8_kv(self):
        from fei_tpu.engine import GenerationConfig, InferenceEngine

        eng = InferenceEngine.from_config(
            "tiny", tokenizer="byte", max_seq_len=64,
            paged=True, batch_size=2, page_size=8, kv_quant="int8",
        )
        pool = eng._ensure_pool()
        assert pool.k_pages.dtype == jnp.int8 and pool.quantized
        gen = GenerationConfig(max_new_tokens=6, temperature=0.0, ignore_eos=True)
        prompt = eng.tokenizer.encode("hello world", add_bos=True)
        results = [None, None]

        def consume(i):
            results[i] = list(eng.scheduler.stream(prompt, gen))

        threads = [threading.Thread(target=consume, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r is not None and len(r) == 6 for r in results)
        assert results[0] == results[1]  # greedy determinism

    def test_int8_kv_tracks_bf16_kv(self):
        """Same engine/weights, int8 vs bf16 pools: greedy streams agree on
        a short horizon (the int8 error is far below sampling boundaries
        for a well-scaled tiny model)."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine

        gen = GenerationConfig(max_new_tokens=8, temperature=0.0, ignore_eos=True)
        outs = {}
        for mode in (None, "int8"):
            eng = InferenceEngine.from_config(
                "tiny", tokenizer="byte", max_seq_len=64,
                paged=True, batch_size=1, page_size=8, kv_quant=mode,
            )
            prompt = eng.tokenizer.encode("determinism", add_bos=True)
            outs[mode] = list(eng.scheduler.stream(prompt, gen))
        assert len(outs["int8"]) == len(outs[None]) == 8
        assert outs["int8"] == outs[None]

    def test_sharded_paged_serving(self):
        """Multi-chip paged serving: pool kv-heads sharded over tp, the
        kernel under shard_map — greedy stream matches the single-chip
        engine exactly (bf16 and int8 pools)."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine
        from fei_tpu.parallel.mesh import make_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        gen = GenerationConfig(max_new_tokens=6, temperature=0.0, ignore_eos=True)
        for mode in (None, "int8"):
            outs = {}
            for mesh in (None, make_mesh({"tp": 2}, devices=jax.devices()[:2])):
                eng = InferenceEngine.from_config(
                    "tiny", tokenizer="byte", max_seq_len=64,
                    paged=True, batch_size=1, page_size=8,
                    kv_quant=mode, mesh=mesh, dtype=jnp.float32,
                )
                prompt = eng.tokenizer.encode("shard me", add_bos=True)
                outs[mesh is None] = list(eng.scheduler.stream(prompt, gen))
            assert outs[True] == outs[False], f"kv_quant={mode}"

    def test_pool_bytes_halved(self):
        cfg = get_model_config("tiny")
        bf16 = PagedKVCache.create(cfg, 16, 2, 4, page_size=8)
        q8 = PagedKVCache.create(cfg, 16, 2, 4, page_size=8, kv_quant="int8")
        bf16_kv = bf16.k_pages.nbytes + bf16.v_pages.nbytes
        q8_kv = (
            q8.k_pages.nbytes + q8.v_pages.nbytes
            + q8.k_scales.nbytes + q8.v_scales.nbytes
        )
        # analytic ratio: 0.5 (int8 vs bf16) + 2/D scale overhead. tiny's
        # D=16 gives 0.625; Llama-class D=128 gives ~0.516
        expect = 0.5 + 2.0 / cfg.head_dim_
        assert q8_kv <= expect * bf16_kv + 1
