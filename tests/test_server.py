"""OpenAI-compatible serving endpoint (fei serve / ui/server.py).

The reference consumed this API shape from the outside (LiteLLM,
fei/core/assistant.py:524-530); serving it over the in-tree engine
completes the switchover story — anything speaking the OpenAI protocol
(including our own RemoteProvider) can point at the paged serving stack.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from fei_tpu.agent.providers import (
    JaxLocalProvider,
    MockProvider,
    ProviderResponse,
    RemoteProvider,
    ToolCall,
)
from fei_tpu.engine.engine import InferenceEngine
from fei_tpu.ui.server import ServeAPI, ServingServer


def _post(port: int, path: str, payload: dict, key: str | None = None,
          stream: bool = False):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={
            "Content-Type": "application/json",
            **({"Authorization": f"Bearer {key}"} if key else {}),
        },
        method="POST",
    )
    resp = urllib.request.urlopen(req, timeout=300)
    if stream:
        return resp
    return json.loads(resp.read())


@pytest.fixture(scope="module")
def mock_server():
    provider = MockProvider()
    api = ServeAPI(provider, model_name="mock-model")
    server = ServingServer(api)
    server.start()
    yield server, provider
    server.stop()


@pytest.fixture(scope="module")
def local_server():
    engine = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    provider = JaxLocalProvider(engine=engine)
    api = ServeAPI(provider, model_name="tiny")
    server = ServingServer(api)
    server.start()
    yield server
    server.stop()


class TestProtocolShape:
    def test_health_and_models(self, mock_server):
        server, _ = mock_server
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health", timeout=10
        ) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/v1/models", timeout=10
        ) as r:
            models = json.loads(r.read())
        assert models["data"][0]["id"] == "mock-model"

    def test_chat_completion_shape(self, mock_server):
        server, provider = mock_server
        provider.script.append(ProviderResponse("hello from the engine"))
        body = _post(server.port, "/v1/chat/completions", {
            "messages": [{"role": "system", "content": "be brief"},
                         {"role": "user", "content": "hi"}],
        })
        assert body["object"] == "chat.completion"
        choice = body["choices"][0]
        assert choice["message"]["content"] == "hello from the engine"
        assert choice["finish_reason"] == "stop"
        assert set(body["usage"]) == {"prompt_tokens", "completion_tokens",
                                      "total_tokens"}
        # system turn was lifted into the provider's system parameter
        assert provider.calls[-1]["system"] == "be brief"
        assert provider.calls[-1]["messages"][-1]["content"] == "hi"

    def test_tool_call_round_trip(self, mock_server):
        """assistant tool_calls serialize to the OpenAI envelope, and a
        follow-up request carrying them (plus the tool result) converts
        back to the internal shape."""
        server, provider = mock_server
        provider.script.append(ProviderResponse(
            "", [ToolCall("call_1", "GrepTool", {"pattern": "x"})], "tool_use"
        ))
        tools = [{"type": "function", "function": {
            "name": "GrepTool", "description": "search",
            "parameters": {"type": "object",
                           "properties": {"pattern": {"type": "string"}}},
        }}]
        body = _post(server.port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "find x"}],
            "tools": tools,
        })
        choice = body["choices"][0]
        assert choice["finish_reason"] == "tool_calls"
        tc = choice["message"]["tool_calls"][0]
        assert tc["function"]["name"] == "GrepTool"
        assert json.loads(tc["function"]["arguments"]) == {"pattern": "x"}
        # the provider saw the internal tool schema
        assert provider.calls[-1]["tools"][0]["name"] == "GrepTool"
        assert "input_schema" in provider.calls[-1]["tools"][0]

        provider.script.append(ProviderResponse("done"))
        body2 = _post(server.port, "/v1/chat/completions", {
            "messages": [
                {"role": "user", "content": "find x"},
                {"role": "assistant", "content": None, "tool_calls": [tc]},
                {"role": "tool", "tool_call_id": "call_1", "content": "match"},
            ],
        })
        assert body2["choices"][0]["message"]["content"] == "done"
        sent = provider.calls[-1]["messages"]
        assert sent[1]["tool_calls"][0]["arguments"] == {"pattern": "x"}
        assert sent[2]["role"] == "tool"

    def test_malformed_json_is_400(self, mock_server):
        server, _ = mock_server
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/chat/completions",
            data=b'{"messages": [truncated',
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 400

    def test_non_object_body_is_400(self, mock_server):
        """json.loads accepts bare strings/lists — the handler must 400
        them instead of crashing on body.get()."""
        server, _ = mock_server
        for raw in (b'"just a string"', b'[1, 2, 3]', b'42'):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/chat/completions",
                data=raw,
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 400, raw
            payload = json.loads(e.value.read())
            assert payload["error"]["type"] == "invalid_request_error"

    def test_malformed_messages_shape_is_400(self, mock_server):
        """Non-list messages / non-dict entries must raise ValueError in
        _parse_request (-> 400), never AttributeError (-> 500) — the
        fleet router relies on the error class to tell a client error
        from a replica failure."""
        server, _ = mock_server
        for bad in ("not-a-list", [7], [None],
                    [{"role": "user", "content": "x"}, "trailer"]):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(server.port, "/v1/chat/completions", {"messages": bad})
            assert e.value.code == 400, bad
            payload = json.loads(e.value.read())
            assert payload["error"]["type"] == "invalid_request_error"

    def test_bad_stream_request_is_400_not_dropped(self, mock_server):
        server, _ = mock_server
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(server.port, "/v1/chat/completions", {
                "messages": [{"role": "user", "content": "x"}],
                "stream": True, "temperature": "hot",
            }, stream=True)
        assert e.value.code == 400

    def test_provider_error_is_500_json(self, mock_server):
        server, provider = mock_server

        class Boom(Exception):
            pass

        def raise_boom(*a, **k):
            raise Boom("engine fell over")

        orig = provider.complete
        provider.complete = raise_boom
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(server.port, "/v1/chat/completions",
                      {"messages": [{"role": "user", "content": "x"}]})
            assert e.value.code == 500
            assert "engine fell over" in json.loads(e.value.read())[
                "error"]["message"]
        finally:
            provider.complete = orig

    def test_content_parts_flatten(self, mock_server):
        server, provider = mock_server
        provider.script.append(ProviderResponse("ok"))
        _post(server.port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": [
                {"type": "text", "text": "part one "},
                {"type": "text", "text": "part two"},
            ]}],
        })
        assert provider.calls[-1]["messages"][0]["content"] == (
            "part one part two"
        )

    def test_auth_required_when_keyed(self):
        api = ServeAPI(MockProvider(), api_key="sekrit")
        server = ServingServer(api)
        server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(server.port, "/v1/chat/completions",
                      {"messages": [{"role": "user", "content": "x"}]})
            assert e.value.code == 401
            api.provider.script.append(ProviderResponse("ok"))
            body = _post(server.port, "/v1/chat/completions",
                         {"messages": [{"role": "user", "content": "x"}]},
                         key="sekrit")
            assert body["choices"][0]["message"]["content"] == "ok"
            # RFC 7235: the auth scheme token is case-insensitive
            api.provider.script.append(ProviderResponse("ok2"))
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/chat/completions",
                data=json.dumps(
                    {"messages": [{"role": "user", "content": "x"}]}
                ).encode(),
                headers={"Content-Type": "application/json",
                         "Authorization": "bearer sekrit"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                assert json.loads(r.read())[
                    "choices"][0]["message"]["content"] == "ok2"
        finally:
            server.stop()


class TestLocalEngineServing:
    def test_completion_and_stream_agree(self, local_server):
        msgs = [{"role": "user", "content": "stream parity"}]
        req = {"messages": msgs, "max_tokens": 16, "temperature": 0.0}
        full = _post(local_server.port, "/v1/chat/completions", req)
        content = full["choices"][0]["message"]["content"]
        assert full["usage"]["completion_tokens"] > 0

        resp = _post(local_server.port, "/v1/chat/completions",
                     {**req, "stream": True}, stream=True)
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        deltas, finish = [], None
        for line in resp:
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                break
            chunk = json.loads(payload)
            choice = chunk["choices"][0]
            if "content" in choice["delta"]:
                deltas.append(choice["delta"]["content"])
            if choice["finish_reason"]:
                finish = choice["finish_reason"]
        assert finish == "stop"
        assert "".join(deltas) == content

    def test_concurrent_requests_interleave(self, local_server):
        results: dict[int, str] = {}

        def go(i):
            body = _post(local_server.port, "/v1/chat/completions", {
                "messages": [{"role": "user", "content": f"req {i}"}],
                "max_tokens": 12, "temperature": 0.0,
            })
            results[i] = body["choices"][0]["message"]["content"]

        ts = [threading.Thread(target=go, args=(i,)) for i in range(3)]
        [t.start() for t in ts]
        [t.join(timeout=120) for t in ts]
        assert len(results) == 3
        # determinism: identical prompt through the live server matches
        again = _post(local_server.port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "req 0"}],
            "max_tokens": 12, "temperature": 0.0,
        })
        assert again["choices"][0]["message"]["content"] == results[0]

    def test_self_loop_via_remote_provider(self, local_server):
        """The full circle: our RemoteProvider (the reference's transport
        shape) talks to our own serving endpoint."""
        rp = RemoteProvider(
            provider="openai",
            model="tiny",
            api_base=f"http://127.0.0.1:{local_server.port}/v1",
        )
        resp = rp.complete(
            [{"role": "user", "content": "loop"}], max_tokens=8
        )
        assert isinstance(resp.content, str)
        assert resp.usage.get("completion_tokens", 0) >= 0


class TestObservability:
    """The /metrics + /v1/traces surface after real engine traffic."""

    def _get(self, port: int, path: str):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=60
        )

    def test_metrics_and_traces_after_streamed_completion(self, local_server):
        resp = _post(local_server.port, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "observe me"}],
            "max_tokens": 8, "temperature": 0.0, "stream": True,
        }, stream=True)
        deltas = []
        for line in resp:
            line = line.strip()
            if line.startswith(b"data: ") and line != b"data: [DONE]":
                chunk = json.loads(line[len(b"data: "):])
                deltas.append(chunk["choices"][0]["delta"].get("content", ""))
        assert "".join(deltas)  # the stream produced tokens

        with self._get(local_server.port, "/metrics") as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert "fei_ttft_seconds_bucket{le=" in text
        assert "fei_scheduler_queue_depth" in text
        assert "# TYPE fei_ttft_seconds histogram" in text
        assert "fei_scheduler_requests_completed_total" in text

        with self._get(local_server.port, "/v1/traces?limit=10") as r:
            traces = json.loads(r.read())
        assert traces["object"] == "list"
        done = [t for t in traces["data"] if t["status"] == "completed"]
        assert done, f"no completed trace in {traces['data']!r}"
        tr = done[0]
        phases = [s["phase"] for s in tr["spans"]]
        assert phases[0] == "queued"
        assert "first_token" in phases
        assert phases[-1] == "completed"
        ts = [s["ts"] for s in tr["spans"]]
        assert ts == sorted(ts)  # monotonically ordered phase timestamps
        assert tr["completion_tokens"] > 0

    def test_metrics_is_pre_auth_but_traces_requires_key(self):
        api = ServeAPI(MockProvider(), api_key="sekrit")
        server = ServingServer(api)
        server.start()
        try:
            with self._get(server.port, "/metrics") as r:
                assert r.status == 200
            with pytest.raises(urllib.error.HTTPError) as e:
                self._get(server.port, "/v1/traces")
            assert e.value.code == 401
        finally:
            server.stop()

    def test_profile_capture_round_trip(self, local_server, tmp_path):
        req = urllib.request.Request(
            f"http://127.0.0.1:{local_server.port}/debug/profile",
            data=json.dumps({"seconds": 0.2,
                             "trace_dir": str(tmp_path)}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                body = json.loads(r.read())
        except urllib.error.HTTPError as e:
            # the capture hook must fail as JSON, never a dropped socket
            assert e.code == 500
            pytest.skip("jax.profiler capture unavailable on this backend")
        assert body["object"] == "profile"
        assert body["trace_dir"] == str(tmp_path)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(local_server.port, "/debug/profile", {"seconds": -1})
        assert e.value.code == 400
