"""Orbax checkpoint/resume: roundtrip fidelity, retention, latest-step
selection, sharded restore, and train-loop resume equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.engine.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from fei_tpu.engine.train import TrainConfig, make_train_step
from fei_tpu.models.configs import get_model_config
from fei_tpu.models.llama import init_params
from fei_tpu.utils.errors import CheckpointError


@pytest.fixture()
def cfg_params():
    cfg = get_model_config("tiny", num_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


class TestCheckpointRoundtrip:
    def test_save_restore_params(self, tmp_path, cfg_params):
        _, params = cfg_params
        save_checkpoint(str(tmp_path / "ckpt"), 0, params)
        out = restore_checkpoint(str(tmp_path / "ckpt"), target={"params": params})
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            out["params"], params,
        )

    def test_latest_step_and_retention(self, tmp_path, cfg_params):
        _, params = cfg_params
        d = str(tmp_path / "ckpt")
        for s in (1, 2, 3, 4, 5):
            save_checkpoint(d, s, params, max_to_keep=2)
        assert latest_step(d) == 5
        # retention: restoring an evicted step fails, latest works
        out = restore_checkpoint(d, target={"params": params})
        assert out is not None
        with pytest.raises(Exception):
            restore_checkpoint(d, step=1, target={"params": params})

    def test_missing_dir_raises(self, tmp_path):
        with pytest.raises(CheckpointError):
            restore_checkpoint(str(tmp_path / "nope"))

    def test_sharded_restore(self, tmp_path, cfg_params):
        from fei_tpu.parallel.mesh import make_mesh
        from fei_tpu.parallel.sharding import param_shardings

        cfg, params = cfg_params
        d = str(tmp_path / "ckpt")
        save_checkpoint(d, 0, params)
        n = min(2, len(jax.devices()))
        mesh = make_mesh({"tp": n}, devices=jax.devices()[:n])
        sh = param_shardings(params, mesh, cfg.is_moe)
        out = restore_checkpoint(
            d, target={"params": params}, shardings={"params": sh}
        )
        wq = out["params"]["layers"]["wq"]
        assert wq.sharding == sh["layers"]["wq"]
        np.testing.assert_array_equal(
            np.asarray(wq), np.asarray(params["layers"]["wq"])
        )


class TestTrainResume:
    def test_resume_matches_uninterrupted(self, tmp_path, cfg_params):
        cfg, params = cfg_params
        _, step_fn = make_train_step(cfg, TrainConfig(remat=False))
        from fei_tpu.engine.train import make_optimizer

        opt = make_optimizer(TrainConfig(remat=False))
        opt_state = opt.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0, cfg.vocab_size)

        # step_fn donates params/opt_state: give each branch its own copy
        def dup(t):
            return jax.tree.map(jnp.copy, t)

        # 4 uninterrupted steps
        p, s = dup(params), dup(opt_state)
        for _ in range(4):
            p, s, loss_a = step_fn(p, s, tokens)

        # 2 steps, checkpoint, restore, 2 more
        p2, s2 = dup(params), dup(opt_state)
        for _ in range(2):
            p2, s2, _ = step_fn(p2, s2, tokens)
        d = str(tmp_path / "resume")
        save_checkpoint(d, 2, p2, opt_state=s2)
        out = restore_checkpoint(d, target={"params": p2, "opt_state": s2})
        # copy before the donating step_fn: restored arrays can be backed
        # by tensorstore-owned buffers, and donating those intermittently
        # segfaults when XLA reuses the storage in place
        p3, s3 = dup(out["params"]), dup(out["opt_state"])
        for _ in range(2):
            p3, s3, loss_b = step_fn(p3, s3, tokens)

        np.testing.assert_allclose(float(loss_a), float(loss_b), atol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            p, p3,
        )
