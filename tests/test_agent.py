"""Agent-loop tests: provider contract, tool rounds, task executor, CLI.

Mirrors the reference's mock-LLM pattern (fei/tests/test_litellm.py:51-110):
the MockProvider plays the role of the patched litellm_completion.
"""

import asyncio
import json

import pytest

from fei_tpu.agent import (
    Assistant,
    ConversationManager,
    MockProvider,
    ProviderResponse,
    TaskExecutor,
    ToolCall,
)
from fei_tpu.agent.providers import (
    extract_tool_calls,
    render_tool_prompt,
    stream_visible,
)
from fei_tpu.tools import ToolRegistry, create_code_tools


def make_assistant(script, registry=None):
    provider = MockProvider(script)
    return Assistant(provider=provider, tool_registry=registry), provider


class TestExtractToolCalls:
    def test_extracts_and_strips(self):
        text = 'Let me look.\n<tool_call>{"name": "GlobTool", "arguments": {"pattern": "*.py"}}</tool_call>'
        content, calls = extract_tool_calls(text)
        assert content == "Let me look."
        assert calls[0].name == "GlobTool"
        assert calls[0].arguments == {"pattern": "*.py"}

    def test_multiple_calls(self):
        text = (
            '<tool_call>{"name": "A", "arguments": {}}</tool_call>'
            '<tool_call>{"name": "B", "arguments": {"x": 1}}</tool_call>'
        )
        _, calls = extract_tool_calls(text)
        assert [c.name for c in calls] == ["A", "B"]

    def test_malformed_json_ignored(self):
        content, calls = extract_tool_calls("<tool_call>{not json}</tool_call>ok")
        assert calls == [] and content == "ok"

    def test_stream_visible_holds_partial_tag(self):
        assert stream_visible("Sure. <tool_ca") == "Sure. "
        assert stream_visible("Sure. <tool_cat") == "Sure. <tool_cat"

    def test_stream_visible_strips_block_keeps_tail(self):
        text = 'before <tool_call>{"name":"A","arguments":{}}</tool_call> after'
        assert stream_visible(text) == "before  after"
        # open block held back entirely
        assert stream_visible('x <tool_call>{"name"') == "x "

    def test_stream_visible_monotonic(self):
        full = 'hi <tool_call>{"name":"A","arguments":{}}</tool_call> bye'
        prev = ""
        for i in range(len(full) + 1):
            vis = stream_visible(full[:i])
            assert vis.startswith(prev)
            prev = vis

    def test_tool_prompt_lists_tools(self):
        reg = ToolRegistry()
        create_code_tools(reg)
        prompt = render_tool_prompt(reg.get_schemas())
        assert "GlobTool" in prompt and "<tool_call>" in prompt


class TestAssistantLoop:
    def test_plain_chat(self):
        assistant, provider = make_assistant([ProviderResponse("hello there")])
        out = asyncio.run(assistant.chat("hi"))
        assert out == "hello there"
        roles = [m["role"] for m in assistant.conversation.messages]
        assert roles == ["user", "assistant"]

    def test_tool_round_trip(self, tmp_path):
        (tmp_path / "a.py").write_text("x = 1\n")
        reg = ToolRegistry()
        create_code_tools(reg)
        script = [
            f'<tool_call>{{"name": "GlobTool", "arguments": {{"pattern": "*.py", "path": "{tmp_path}"}}}}</tool_call>',
            ProviderResponse("I found one python file."),
        ]
        assistant, provider = make_assistant(script, reg)
        out = asyncio.run(assistant.chat("what python files are there?"))
        assert out == "I found one python file."
        # second provider call must carry the tool result message
        second = provider.calls[1]["messages"]
        tool_msgs = [m for m in second if m["role"] == "tool"]
        assert len(tool_msgs) == 1
        assert "a.py" in tool_msgs[0]["content"]

    def test_tool_error_fed_back(self):
        reg = ToolRegistry()
        create_code_tools(reg)
        script = [
            '<tool_call>{"name": "View", "arguments": {"file_path": "/definitely/missing"}}</tool_call>',
            ProviderResponse("the file is missing"),
        ]
        assistant, provider = make_assistant(script, reg)
        out = asyncio.run(assistant.chat("read it"))
        assert out == "the file is missing"
        tool_msg = [m for m in provider.calls[1]["messages"] if m["role"] == "tool"][0]
        assert "error" in tool_msg["content"]

    def test_round_limit(self):
        reg = ToolRegistry()
        reg.register_tool("Loop", "loops", {"type": "object", "properties": {}},
                          lambda: {"ok": True})
        looping = '<tool_call>{"name": "Loop", "arguments": {}}</tool_call>'
        assistant, provider = make_assistant([looping] * 20, reg)
        assistant.max_tool_rounds = 3
        asyncio.run(assistant.chat("go"))
        assert len(provider.calls) == 4  # initial + 3 rounds

    def test_empty_response_salvaged_from_tool_output(self):
        reg = ToolRegistry()
        reg.register_tool("Info", "info", {"type": "object", "properties": {}},
                          lambda: {"data": 42})
        script = [
            '<tool_call>{"name": "Info", "arguments": {}}</tool_call>',
            ProviderResponse(""),
        ]
        assistant, _ = make_assistant(script, reg)
        out = asyncio.run(assistant.chat("info please"))
        assert "42" in out

    def test_streaming_callback(self):
        deltas = []
        assistant, _ = make_assistant([ProviderResponse("streamed reply")])
        assistant.on_text = deltas.append
        out = asyncio.run(assistant.chat("hi"))
        assert out == "streamed reply"
        assert "".join(deltas) == "streamed reply"


class TestConversationManager:
    def test_tool_results_stringified(self):
        conv = ConversationManager()
        call = ToolCall("id1", "T", {})
        conv.add_tool_results([(call, {"a": 1})])
        assert json.loads(conv.messages[0]["content"]) == {"a": 1}

    def test_trim_respects_budget_and_pairs(self):
        conv = ConversationManager(max_context_tokens=50)
        conv.add_user_message("word " * 100)
        conv.add_assistant_message("reply", [ToolCall("i", "T", {})])
        conv.add_tool_results([(ToolCall("i", "T", {}), "out")])
        conv.add_user_message("latest question")
        conv.add_assistant_message("latest answer")
        roles = [m["role"] for m in conv.messages]
        assert "tool" not in roles or roles.index("tool") != 0  # never orphaned
        assert conv.token_estimate() <= 50 or len(conv.messages) == 2


class TestTaskExecutor:
    def test_completes_on_signal(self):
        script = [
            ProviderResponse("step one done"),
            ProviderResponse("all finished [TASK_COMPLETE]"),
        ]
        assistant, provider = make_assistant(script)
        ctx = asyncio.run(TaskExecutor(assistant, max_iterations=5).execute_task("do it"))
        assert ctx.completed and ctx.iterations == 2
        assert ctx.final_response == "all finished"
        # first prompt wraps task in the protocol scaffold
        assert "[TASK_COMPLETE]" in provider.calls[0]["messages"][0]["content"]

    def test_iteration_cap(self):
        assistant, _ = make_assistant([ProviderResponse("still going")] * 10)
        ctx = asyncio.run(TaskExecutor(assistant, max_iterations=3).execute_task("loop"))
        assert not ctx.completed and ctx.iterations == 3

    def test_interactive_stop(self):
        assistant, _ = make_assistant([ProviderResponse("going")] * 10)
        ctx = asyncio.run(
            TaskExecutor(assistant, max_iterations=10).execute_interactive(
                "t", confirm=lambda ctx, resp: ctx.iterations < 2
            )
        )
        assert ctx.iterations == 2


class TestJaxLocalProvider:
    def test_end_to_end_tiny_engine(self):
        import jax.numpy as jnp

        from fei_tpu.agent.providers import JaxLocalProvider
        from fei_tpu.engine import InferenceEngine

        engine = InferenceEngine.from_config(
            "tiny", dtype=jnp.float32, max_seq_len=512, tokenizer="byte"
        )
        provider = JaxLocalProvider(engine=engine, gen_overrides={"ignore_eos": True})
        resp = provider.complete(
            [{"role": "user", "content": "hello"}], system="be brief", max_tokens=8
        )
        assert isinstance(resp.content, str)
        assert resp.usage["completion_tokens"] == 8

    def test_speculation_toggle_is_output_invariant(self, monkeypatch):
        """The provider's greedy path uses prompt-lookup speculation by
        default; disabling it must not change a single token."""
        import jax.numpy as jnp

        from fei_tpu.agent.providers import JaxLocalProvider
        from fei_tpu.engine import InferenceEngine

        engine = InferenceEngine.from_config(
            "tiny", dtype=jnp.float32, max_seq_len=512, tokenizer="byte"
        )
        provider = JaxLocalProvider(engine=engine, gen_overrides={"ignore_eos": True})
        msgs = [{"role": "user", "content": "echo echo echo echo"}]
        outs = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("FEI_TPU_SPECULATE", flag)
            outs[flag] = provider.complete(msgs, max_tokens=12).content
        assert outs["1"] == outs["0"]

    def test_stream_detok_byte_identical(self, monkeypatch):
        """The stream loop detokenizes incrementally (bounded pending
        window + cached context decode) instead of re-decoding the whole
        sequence per token; the streamed text must stay byte-identical
        to a from-scratch decode of every emitted token id."""
        import jax.numpy as jnp

        from fei_tpu.agent.providers import (
            JaxLocalProvider,
            extract_tool_calls,
            stream_visible,
        )
        from fei_tpu.engine import InferenceEngine

        monkeypatch.setenv("FEI_TPU_SPECULATE", "0")
        engine = InferenceEngine.from_config(
            "tiny", dtype=jnp.float32, max_seq_len=512, tokenizer="byte"
        )
        provider = JaxLocalProvider(engine=engine, gen_overrides={"ignore_eos": True})
        captured: list[int] = []
        real = engine.generate_stream

        def spy(ids, gen, **kw):
            for t in real(ids, gen, **kw):
                captured.append(t)
                yield t

        monkeypatch.setattr(engine, "generate_stream", spy)
        # byte tokenizer + a random tiny model: the stream crosses plenty of
        # invalid / partial UTF-8 boundaries, the hard case for folding
        gen = provider.stream(
            [{"role": "user", "content": "héllo ✓ bytes"}], max_tokens=48
        )
        chunks = []
        while True:
            try:
                chunks.append(next(gen))
            except StopIteration as fin:
                resp = fin.value
                break
        assert len(captured) == 48
        full = engine.tokenizer.decode(captured)
        assert "".join(chunks) == stream_visible(full, provider.tool_trigger)
        content, _ = extract_tool_calls(full, provider.tool_trigger)
        assert resp.content == content
        assert resp.usage["completion_tokens"] == 48

    def test_assistant_over_local_engine(self):
        import jax.numpy as jnp

        from fei_tpu.agent.providers import JaxLocalProvider
        from fei_tpu.engine import InferenceEngine

        engine = InferenceEngine.from_config(
            "tiny", dtype=jnp.float32, max_seq_len=512, tokenizer="byte"
        )
        provider = JaxLocalProvider(engine=engine, gen_overrides={"ignore_eos": True})
        assistant = Assistant(provider=provider, max_tokens=8)
        out = asyncio.run(assistant.chat("2+2?"))
        assert isinstance(out, str)


class TestCLI:
    def test_one_shot_mock(self, capsys, tmp_path, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(cli, "HISTORY_FILE", str(tmp_path / "history.json"))
        rc = cli.main(["--provider", "mock", "--no-stream", "--message", "ping"])
        assert rc == 0
        assert "[mock] echo: ping" in capsys.readouterr().out

    def test_history_subcommand(self, capsys, tmp_path, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(cli, "HISTORY_FILE", str(tmp_path / "history.json"))
        cli.main(["--provider", "mock", "--no-stream", "--message", "remember me"])
        rc = cli.main(["history", "list"])
        out = capsys.readouterr().out
        assert rc == 0 and "remember me" in out


class TestAskAndSearch:
    """fei ask / fei search (parity: ref fei/ui/cli.py:572-728, without the
    reference's hardcoded fallback API key)."""

    _RESULTS = {
        "web": {
            "results": [
                {"title": "JAX docs", "url": "https://jax.dev",
                 "description": "Composable transforms."},
                {"title": "Pallas guide", "url": "https://jax.dev/pallas",
                 "description": "TPU kernels."},
            ]
        }
    }

    def test_search_subcommand(self, capsys, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(
            cli, "run_search",
            lambda q, count=5, manager=None: cli._extract_search_results(
                self._RESULTS
            ),
        )
        rc = cli.main(["search", "jax"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "JAX docs" in out and "https://jax.dev" in out

    def test_search_failure_is_readable(self, capsys, monkeypatch):
        import fei_tpu.ui.cli as cli

        def boom(q, count=5, manager=None):
            raise RuntimeError("no brave key configured")

        monkeypatch.setattr(cli, "run_search", boom)
        rc = cli.main(["search", "jax"])
        assert rc == 1
        assert "no brave key" in capsys.readouterr().err

    def test_ask_stuffs_results_into_prompt(self, capsys, tmp_path, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(cli, "HISTORY_FILE", str(tmp_path / "h.json"))
        monkeypatch.setattr(
            cli, "run_search",
            lambda q, count=5, manager=None: cli._extract_search_results(
                self._RESULTS
            ),
        )
        rc = cli.main(
            ["--provider", "mock", "--no-stream", "ask", "what is jax?"]
        )
        out = capsys.readouterr().out
        assert rc == 0
        # MockProvider echoes a (truncated) prefix of its prompt back: the
        # stuffed-search preamble must have reached the model
        assert "Answer the question using the web search results" in out
        assert "Search results for: what is" in out
        # and the ask landed in history
        hist = cli.History(str(tmp_path / "h.json"))
        assert any(e["prompt"].startswith("[ask]") for e in hist.entries)

    def test_ask_no_search(self, capsys, tmp_path, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(cli, "HISTORY_FILE", str(tmp_path / "h.json"))

        def never(*a, **k):
            raise AssertionError("search must not run with --no-search")

        monkeypatch.setattr(cli, "run_search", never)
        rc = cli.main(
            ["--provider", "mock", "--no-stream", "ask", "--no-search", "2+2?"]
        )
        assert rc == 0
        assert "2+2?" in capsys.readouterr().out

    def test_extract_mcp_content_envelope(self):
        import fei_tpu.ui.cli as cli

        rows = cli._extract_search_results(
            {"content": [{"type": "text", "text": "Title — example.com"}]}
        )
        assert rows and "example.com" in rows[0]["description"]


class TestHistoryLoad:
    def test_load_replays_into_conversation(self, tmp_home, capsys, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(
            cli, "HISTORY_FILE",
            str(tmp_home / "history.json"),
        )
        hist = cli.History(str(tmp_home / "history.json"))
        hist.add("what is a mesh?", "a named device grid")

        args = cli.parse_args(["--provider", "mock", "history", "load", "0"])
        # avoid entering the interactive loop: stub chat_loop
        monkeypatch.setattr(cli, "chat_loop", lambda assistant, history: 0)
        captured = {}

        real_build = cli.build_assistant

        def spy_build(a):
            assistant = real_build(a)
            captured["assistant"] = assistant
            return assistant

        monkeypatch.setattr(cli, "build_assistant", spy_build)
        rc = cli.handle_history_command(args)
        assert rc == 0
        out = capsys.readouterr().out
        assert "what is a mesh?" in out
        msgs = captured["assistant"].conversation.messages
        assert msgs[0]["role"] == "user"
        assert msgs[1]["role"] == "assistant"

    def test_load_bad_index(self, tmp_home, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(cli, "HISTORY_FILE", str(tmp_home / "h.json"))
        args = cli.parse_args(["history", "load", "7"])
        assert cli.handle_history_command(args) == 1


class TestCLIStats:
    def test_stats_flag_prints_summary(self, capsys, tmp_path, monkeypatch):
        import fei_tpu.ui.cli as cli

        monkeypatch.setattr(cli, "HISTORY_FILE", str(tmp_path / "h.json"))
        rc = cli.main(
            ["--provider", "mock", "--no-stream", "--stats", "--message", "hi"]
        )
        err = capsys.readouterr().err
        assert rc == 0
        assert "-- stats" in err and "tokens:" in err
