"""Device-native grammar constraints in the continuous-batching scheduler.

VERDICT round-2 weakness #4: the paged path used to evaluate grammar masks
on host and upload a [B, vocab] bool mask every step. Now per-slot DFA
states ride the same tiny [B] upload as the token ids and the mask is
computed INSIDE the compiled step from the on-device table —
``scheduler.host_mask_uploads`` proves zero per-step mask uploads for
grammar requests. Parity targets the dense fused scan
(engine.generate_constrained / generate_stream_toolcalls).
"""

from __future__ import annotations

import json
import threading

import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.grammar import (
    JsonSchemaGrammar,
    TokenGrammar,
    char_walk,
    compile_agent_tool_grammar,
)
from fei_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.slow  # fast lane: -m 'not slow' (docs/TESTING.md)

SCHEMA = {
    "type": "object",
    "properties": {
        "path": {"type": "string"},
        "recursive": {"type": "boolean"},
        "depth": {"type": "integer"},
    },
    "required": ["path"],
}

TOOLS = [
    {"name": "LS", "description": "list", "input_schema": SCHEMA},
    {
        "name": "Grep",
        "description": "search",
        "input_schema": {
            "type": "object",
            "properties": {"pattern": {"type": "string"}},
            "required": ["pattern"],
        },
    },
]


def _uploads() -> float:
    return METRICS.snapshot()["counters"].get("scheduler.host_mask_uploads", 0)


@pytest.fixture(scope="module")
def engines():
    dense = InferenceEngine.from_config("tiny")
    paged = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    return dense, paged


@pytest.fixture(scope="module")
def grammar(engines):
    dense, _ = engines
    return TokenGrammar(JsonSchemaGrammar(SCHEMA), dense.tokenizer)


class TestPagedConstrainedNative:
    def test_paged_constrained_matches_dense(self, engines, grammar):
        dense, paged = engines
        prompt = list(range(7, 19))
        gen = GenerationConfig(max_new_tokens=48)
        ref = dense.generate_constrained(prompt, grammar, gen)
        before = _uploads()
        got = paged.generate_constrained(prompt, grammar, gen)
        assert _uploads() == before, "grammar request paid host mask uploads"
        assert got.token_ids == ref.token_ids, (got.text, ref.text)
        # and the output is a complete valid instance of the schema
        assert char_walk(grammar, got.text) == grammar.accept
        json.loads(got.text)

    def test_constrained_batches_with_free_stream(self, engines, grammar):
        _, paged = engines
        gen_free = GenerationConfig(max_new_tokens=24, ignore_eos=True)
        gen_con = GenerationConfig(max_new_tokens=48)
        free_prompt = list(range(30, 40))
        solo = list(paged.scheduler.stream(free_prompt, gen_free))

        results: dict = {}

        def free():
            results["free"] = list(
                paged.scheduler.stream(free_prompt, gen_free)
            )

        def constrained():
            results["con"] = paged.generate_constrained(
                list(range(7, 19)), grammar, gen_con
            )

        ts = [threading.Thread(target=free), threading.Thread(target=constrained)]
        before = _uploads()
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert _uploads() == before
        # the grammar mask must not leak into the unconstrained slot
        assert results["free"] == solo
        assert char_walk(grammar, results["con"].text) == grammar.accept

    def test_second_distinct_grammar_falls_back_to_host(self, engines):
        _, paged = engines
        g1 = compile_agent_tool_grammar(TOOLS[:1], paged.tokenizer)
        g2 = compile_agent_tool_grammar(TOOLS[1:], paged.tokenizer)
        # budget must exceed both grammars' shortest complete call
        gen = GenerationConfig(max_new_tokens=64)
        sched = paged.scheduler
        sa = sched.submit(list(range(7, 15)), gen, grammar=g1)
        sb = sched.submit(list(range(9, 17)), gen, grammar=g2)
        # the second grammar cannot share the device table while the first
        # is in flight: it must serve via host masks, not fail
        assert sb.grammar is None and sb.mask_fn is not None
        a = list(sched.drain(sa))
        b = list(sched.drain(sb))
        assert char_walk(g1, paged.tokenizer.decode(a)) == g1.accept
        assert char_walk(g2, paged.tokenizer.decode(b)) == g2.accept

    def test_paged_toolcall_native_no_host_masks(self, engines):
        _, paged = engines
        grammar = compile_agent_tool_grammar(TOOLS, paged.tokenizer)
        probe = GenerationConfig(max_new_tokens=8, ignore_eos=True)
        prompt = None
        for base in range(5, 60, 3):
            cand = [base, base + 1, base + 2, base + 3]
            first = next(iter(paged.scheduler.stream(cand, probe)), None)
            if first is not None and paged.tokenizer.decode([first]):
                prompt = cand
                trigger = paged.tokenizer.decode([first])
                break
        assert prompt is not None
        before = _uploads()
        toks = list(
            paged.generate_stream_toolcalls(
                prompt, GenerationConfig(max_new_tokens=96),
                grammar=grammar, trigger=trigger,
            )
        )
        assert _uploads() == before, "toolcall request paid host mask uploads"
        text = paged.tokenizer.decode(toks)
        if trigger in text and text.endswith("</tool_call>"):
            payload = text.split(trigger, 1)[1][: -len("</tool_call>")]
            obj = json.loads(payload)
            assert obj["name"] in {t["name"] for t in TOOLS}
        else:
            assert "</tool_call>" not in text


class TestToolcallFallbackTermination:
    def test_fallback_toolcall_ends_at_acceptance(self):
        """A host-mask fallback tool-call request (second distinct grammar
        in flight) must end its turn at DFA acceptance like the native
        path — not burn the remaining budget on stop tokens when
        ignore_eos leaves the stop set empty."""
        paged = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
        g1 = compile_agent_tool_grammar(TOOLS[:1], paged.tokenizer)
        g2 = compile_agent_tool_grammar(TOOLS[1:], paged.tokenizer)
        gen = GenerationConfig(max_new_tokens=200, ignore_eos=True)
        sched = paged.scheduler
        # g1 native and still in flight while g2 submits -> g2 falls back
        sa = sched.submit(list(range(7, 15)), gen, grammar=g1)
        sb = sched.submit(
            list(range(9, 17)), gen, grammar=g2, grammar_trigger="",
        )
        assert sb.grammar is None and sb.mask_fn is not None
        a = list(sched.drain(sa))
        b = list(sched.drain(sb))
        # empty trigger engages the masker at the first walkable token
        # (free-phase noise may precede the call); acceptance must END the
        # stream well before the 200-token budget, with a complete valid
        # call as the tail
        text = paged.tokenizer.decode(b)
        assert sb.gaccepted, text
        assert len(b) < 120, (len(b), text)
        assert any(
            char_walk(g2, text[i:]) == g2.accept
            for i, ch in enumerate(text) if ch == "{"
        ), text
        assert char_walk(g1, paged.tokenizer.decode(a)) == g1.accept
