"""Device-native grammar constraints in the continuous-batching scheduler.

VERDICT round-2 weakness #4: the paged path used to evaluate grammar masks
on host and upload a [B, vocab] bool mask every step. Now per-slot DFA
states ride the same tiny [B] upload as the token ids and the mask is
computed INSIDE the compiled step from the on-device table —
``scheduler.host_mask_uploads`` proves zero per-step mask uploads for
grammar requests. Parity targets the dense fused scan
(engine.generate_constrained / generate_stream_toolcalls).
"""

from __future__ import annotations

import json
import threading

import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.grammar import (
    JsonSchemaGrammar,
    TokenGrammar,
    char_walk,
    compile_agent_tool_grammar,
)
from fei_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.slow  # fast lane: -m 'not slow' (docs/TESTING.md)

SCHEMA = {
    "type": "object",
    "properties": {
        "path": {"type": "string"},
        "recursive": {"type": "boolean"},
        "depth": {"type": "integer"},
    },
    "required": ["path"],
}

TOOLS = [
    {"name": "LS", "description": "list", "input_schema": SCHEMA},
    {
        "name": "Grep",
        "description": "search",
        "input_schema": {
            "type": "object",
            "properties": {"pattern": {"type": "string"}},
            "required": ["pattern"],
        },
    },
]


def _uploads() -> float:
    return METRICS.snapshot()["counters"].get("scheduler.host_mask_uploads", 0)


@pytest.fixture(scope="module")
def engines():
    dense = InferenceEngine.from_config("tiny")
    paged = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
    return dense, paged


@pytest.fixture(scope="module")
def grammar(engines):
    dense, _ = engines
    return TokenGrammar(JsonSchemaGrammar(SCHEMA), dense.tokenizer)


class TestPagedConstrainedNative:
    def test_paged_constrained_matches_dense(self, engines, grammar):
        dense, paged = engines
        prompt = list(range(7, 19))
        gen = GenerationConfig(max_new_tokens=48)
        ref = dense.generate_constrained(prompt, grammar, gen)
        before = _uploads()
        got = paged.generate_constrained(prompt, grammar, gen)
        assert _uploads() == before, "grammar request paid host mask uploads"
        assert got.token_ids == ref.token_ids, (got.text, ref.text)
        # and the output is a complete valid instance of the schema
        assert char_walk(grammar, got.text) == grammar.accept
        json.loads(got.text)

    def test_constrained_batches_with_free_stream(self, engines, grammar):
        _, paged = engines
        gen_free = GenerationConfig(max_new_tokens=24, ignore_eos=True)
        gen_con = GenerationConfig(max_new_tokens=48)
        free_prompt = list(range(30, 40))
        solo = list(paged.scheduler.stream(free_prompt, gen_free))

        results: dict = {}

        def free():
            results["free"] = list(
                paged.scheduler.stream(free_prompt, gen_free)
            )

        def constrained():
            results["con"] = paged.generate_constrained(
                list(range(7, 19)), grammar, gen_con
            )

        ts = [threading.Thread(target=free), threading.Thread(target=constrained)]
        before = _uploads()
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert _uploads() == before
        # the grammar mask must not leak into the unconstrained slot
        assert results["free"] == solo
        assert char_walk(grammar, results["con"].text) == grammar.accept

    def test_second_distinct_grammar_falls_back_to_host(self, engines):
        _, paged = engines
        g1 = compile_agent_tool_grammar(TOOLS[:1], paged.tokenizer)
        g2 = compile_agent_tool_grammar(TOOLS[1:], paged.tokenizer)
        # budget must exceed both grammars' shortest complete call
        gen = GenerationConfig(max_new_tokens=64)
        sched = paged.scheduler
        sa = sched.submit(list(range(7, 15)), gen, grammar=g1)
        sb = sched.submit(list(range(9, 17)), gen, grammar=g2)
        # the second grammar cannot share the device table while the first
        # is in flight: it must serve via host masks, not fail
        assert sb.grammar is None and sb.mask_fn is not None
        a = list(sched.drain(sa))
        b = list(sched.drain(sb))
        assert char_walk(g1, paged.tokenizer.decode(a)) == g1.accept
        assert char_walk(g2, paged.tokenizer.decode(b)) == g2.accept

    def test_paged_toolcall_native_no_host_masks(self, engines):
        _, paged = engines
        grammar = compile_agent_tool_grammar(TOOLS, paged.tokenizer)
        probe = GenerationConfig(max_new_tokens=8, ignore_eos=True)
        prompt = None
        for base in range(5, 60, 3):
            cand = [base, base + 1, base + 2, base + 3]
            first = next(iter(paged.scheduler.stream(cand, probe)), None)
            if first is not None and paged.tokenizer.decode([first]):
                prompt = cand
                trigger = paged.tokenizer.decode([first])
                break
        assert prompt is not None
        before = _uploads()
        toks = list(
            paged.generate_stream_toolcalls(
                prompt, GenerationConfig(max_new_tokens=96),
                grammar=grammar, trigger=trigger,
            )
        )
        assert _uploads() == before, "toolcall request paid host mask uploads"
        text = paged.tokenizer.decode(toks)
        if trigger in text and text.endswith("</tool_call>"):
            payload = text.split(trigger, 1)[1][: -len("</tool_call>")]
            obj = json.loads(payload)
            assert obj["name"] in {t["name"] for t in TOOLS}
        else:
            assert "</tool_call>" not in text


def _clean_char(eng, tok) -> str | None:
    """The token's text iff it is one printable char that round-trips."""
    text = eng.tokenizer.decode([tok])
    if (
        len(text) == 1
        and text.isprintable()
        and eng.tokenizer.encode(text) == [tok]
    ):
        return text
    return None


class TestTurboFreePhase:
    """The grammar FREE phase (gstate < 0) turbo-scans speculatively: the
    host walks the scanned tokens through the TriggerScanner at delivery,
    and a trigger completing mid-scan rolls the pool length and rng key
    back to the exact token before re-entering device-native constrained
    decode. Parity against multistep=1 (the per-token reference) is the
    contract — token-for-token, greedy AND seeded."""

    def _engine(self, multistep, monkeypatch):
        monkeypatch.setenv("FEI_TPU_SCHED_MULTISTEP", str(multistep))
        return InferenceEngine.from_config("tiny", paged=True, batch_size=2)

    def _find_trigger(self, eng, probe_gen):
        """(prompt, trigger): a greedy/seeded free stream whose token at
        index 2..4 is one clean char not occurring earlier in the decoded
        stream — so the trigger completes inside the first turbo scan
        (scan step idx-1 of n>=4: the first stream token arrives at
        admission, before any scan)."""
        for base in range(5, 90, 3):
            cand = [base, base + 1, base + 2, base + 3]
            stream = list(eng.scheduler.stream(cand, probe_gen))
            for idx in (2, 3, 4):
                if len(stream) <= idx:
                    continue
                ch = _clean_char(eng, stream[idx])
                if ch is None:
                    continue
                if ch in eng.tokenizer.decode(stream[:idx]):
                    continue  # would complete earlier
                return cand, ch
        pytest.skip("no prompt yields a clean trigger at index 2..4")

    @pytest.mark.parametrize(
        "kw",
        [
            dict(temperature=0.0),
            dict(temperature=0.9, top_k=20, seed=11),
        ],
        ids=["greedy", "seeded"],
    )
    def test_trigger_mid_scan_rollback_parity(self, monkeypatch, kw):
        e1 = self._engine(1, monkeypatch)
        e8 = self._engine(8, monkeypatch)
        probe_gen = GenerationConfig(max_new_tokens=8, ignore_eos=True, **kw)
        prompt, trigger = self._find_trigger(e1, probe_gen)
        gen = GenerationConfig(max_new_tokens=64, ignore_eos=True, **kw)
        ref = list(e1.generate_stream_toolcalls(
            prompt, gen,
            grammar=compile_agent_tool_grammar(TOOLS, e1.tokenizer),
            trigger=trigger,
        ))
        before = METRICS.snapshot()["counters"].get(
            "scheduler.turbo_rollbacks", 0
        )
        got = list(e8.generate_stream_toolcalls(
            prompt, gen,
            grammar=compile_agent_tool_grammar(TOOLS, e8.tokenizer),
            trigger=trigger,
        ))
        assert got == ref
        assert METRICS.snapshot()["counters"].get(
            "scheduler.turbo_rollbacks", 0
        ) > before, "trigger landed mid-scan but no rollback was taken"
        text = e8.tokenizer.decode(got)
        if trigger in text and text.endswith("</tool_call>"):
            payload = text.split(trigger, 1)[1][: -len("</tool_call>")]
            obj = json.loads(payload)
            assert obj["name"] in {t["name"] for t in TOOLS}

    def test_free_phase_no_trigger_scans_turbo(self, monkeypatch):
        """A toolcall request whose stream never completes the trigger
        must still decode its free phase in turbo scans (it was per-token
        before this change), token-identical to the reference."""
        e1 = self._engine(1, monkeypatch)
        e8 = self._engine(8, monkeypatch)
        gen = GenerationConfig(max_new_tokens=32, ignore_eos=True)
        prompt = list(range(7, 15))
        g1 = compile_agent_tool_grammar(TOOLS, e1.tokenizer)
        free = e1.tokenizer.decode(list(e1.scheduler.stream(prompt, gen)))
        trigger = "\x00\x01impossible"  # never emitted by the stream
        if trigger in free:
            pytest.skip("stream emitted the sentinel trigger")
        ref = list(e1.generate_stream_toolcalls(
            prompt, gen, grammar=g1, trigger=trigger,
        ))
        before = METRICS.snapshot()["counters"].get(
            "scheduler.multi_steps", 0
        )
        got = list(e8.generate_stream_toolcalls(
            prompt, gen,
            grammar=compile_agent_tool_grammar(TOOLS, e8.tokenizer),
            trigger=trigger,
        ))
        assert got == ref and len(got) == 32
        assert METRICS.snapshot()["counters"].get(
            "scheduler.multi_steps", 0
        ) > before, "free phase kept per-token stepping"


class TestToolcallFallbackTermination:
    def test_fallback_toolcall_ends_at_acceptance(self):
        """A host-mask fallback tool-call request (second distinct grammar
        in flight) must end its turn at DFA acceptance like the native
        path — not burn the remaining budget on stop tokens when
        ignore_eos leaves the stop set empty."""
        paged = InferenceEngine.from_config("tiny", paged=True, batch_size=2)
        g1 = compile_agent_tool_grammar(TOOLS[:1], paged.tokenizer)
        g2 = compile_agent_tool_grammar(TOOLS[1:], paged.tokenizer)
        gen = GenerationConfig(max_new_tokens=200, ignore_eos=True)
        sched = paged.scheduler
        # g1 native and still in flight while g2 submits -> g2 falls back
        sa = sched.submit(list(range(7, 15)), gen, grammar=g1)
        sb = sched.submit(
            list(range(9, 17)), gen, grammar=g2, grammar_trigger="",
        )
        assert sb.grammar is None and sb.mask_fn is not None
        a = list(sched.drain(sa))
        b = list(sched.drain(sb))
        # empty trigger engages the masker at the first walkable token
        # (free-phase noise may precede the call); acceptance must END the
        # stream AT the completing token, with a complete valid call as
        # the tail — never burn budget on stop tokens past it. (How soon
        # greedy closes the call's strings is model behavior, not a
        # contract: the masker's budget-feasibility rule guarantees a
        # valid close no later than the budget, and under the tiny
        # model's weights greedy rides that bound.)
        text = paged.tokenizer.decode(b)
        assert sb.gaccepted, text
        assert len(b) <= 200, (len(b), text)
        assert any(
            char_walk(g2, text[i:]) == g2.accept
            for i, ch in enumerate(text) if ch == "{"
        ), text
        # the final DELIVERED token is the one that completes the call:
        # without it the text must not already end in an accepted call
        # (catches post-acceptance stop-token burn even when stops decode
        # to empty text)
        prev = paged.tokenizer.decode(b[:-1])
        assert prev != text, "final token added no text (stop-token burn)"
        assert not any(
            char_walk(g2, prev[i:]) == g2.accept
            for i, ch in enumerate(prev) if ch == "{"
        ), prev
        assert char_walk(g1, paged.tokenizer.decode(a)) == g1.accept
