"""Failure domains: request-scoped isolation, backpressure, deadlines,
the crash-loop breaker, and the fault-injection harness (engine/faults.py).

The claims under test (docs/ENGINE.md "Failure domains"):
- a host-side per-request failure (admission, grammar walk, delivery)
  fails ONLY the offending sequence — concurrent streams decode on,
  byte-identical to an unfaulted run, and the pool + prefix cache survive;
- only device-scoped failures (typed DeviceError, or the donated pool
  actually consumed) reach _fail_all, which drops the pool for rebuild;
- repeated device failures trip a breaker into a degraded state that
  sheds submits with a typed, Retry-After-carrying error;
- a bounded waiting queue sheds over-limit submits (HTTP 429 at the
  server), and deadlines are enforced both at admission (an expired
  request never occupies a slot) and mid-decode.

Every path triggers deterministically through FAULTS — no sleeps racing
the scheduler thread; ``match`` predicates pick the victim by prompt.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.faults import FAULTS, FaultInjector
from fei_tpu.utils.errors import (
    DeadlineExceededError,
    DeviceError,
    EngineDegradedError,
    EngineError,
    QueueFullError,
    RequestError,
)
from fei_tpu.utils.metrics import METRICS

PROMPTS = [list(range(11 + i, 29 + i)) for i in range(4)]
PROMPT = PROMPTS[0]


def _counter(name: str) -> float:
    return METRICS.snapshot()["counters"].get(name, 0)


def _gauge(name: str) -> float:
    return METRICS.snapshot()["gauges"].get(name, 0)


def _gen(**kw) -> GenerationConfig:
    kw.setdefault("max_new_tokens", 24)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("ignore_eos", True)
    return GenerationConfig(**kw)


def _make(**kwargs) -> InferenceEngine:
    return InferenceEngine.from_config(
        "tiny", paged=True, batch_size=kwargs.pop("batch_size", 2), **kwargs
    )


def _run_concurrent(sched, prompts, gen):
    """Drain one stream per prompt concurrently; [(tokens, exc or None)]."""
    results: list = [None] * len(prompts)

    def go(i):
        toks: list[int] = []
        try:
            for t in sched.stream(prompts[i], gen):
                toks.append(t)
            results[i] = (toks, None)
        except BaseException as exc:  # noqa: BLE001 — the assertion target
            results[i] = (toks, exc)

    ts = [threading.Thread(target=go, args=(i,)) for i in range(len(prompts))]
    [t.start() for t in ts]
    [t.join(timeout=300) for t in ts]
    assert all(r is not None for r in results), "a stream never finished"
    return results


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


class TestHarness:
    """The injector itself: arm/check/fired/disarm semantics."""

    def test_count_decrements_and_disarms(self):
        FAULTS.arm("delivery.detok", "request", count=2)
        for _ in range(2):
            with pytest.raises(RequestError):
                FAULTS.check("delivery.detok")
        FAULTS.check("delivery.detok")  # exhausted: no-op
        assert FAULTS.fired("delivery.detok") == 2

    def test_match_filters_without_consuming(self):
        FAULTS.arm("delivery.detok", "request", count=1,
                   match=lambda ctx: ctx.get("rid") == "victim")
        FAULTS.check("delivery.detok", rid="bystander")  # not consumed
        FAULTS.check("delivery.detok", rid="other")
        with pytest.raises(RequestError):
            FAULTS.check("delivery.detok", rid="victim")
        assert FAULTS.fired("delivery.detok") == 1

    def test_kinds_map_to_taxonomy(self):
        FAULTS.arm("decode.dispatch", "device")
        with pytest.raises(DeviceError):
            FAULTS.check("decode.dispatch")

    def test_unknown_point_or_kind_rejected(self):
        with pytest.raises(EngineError):
            FAULTS.arm("no.such.point")
        with pytest.raises(EngineError):
            FAULTS.arm("decode.dispatch", "meteor")

    def test_env_arming(self, monkeypatch):
        monkeypatch.setenv(
            "FEI_TPU_FAULT", "decode.dispatch:device:1, bogus, nope:request"
        )
        inj = FaultInjector()  # parses env at construction
        with pytest.raises(DeviceError):
            inj.check("decode.dispatch")
        inj.check("decode.dispatch")  # count=1: disarmed


class TestRequestIsolation:
    """The tentpole proof: one doomed request out of four concurrent
    streams fails alone; survivors are byte-identical to an unfaulted
    run and the pool/prefix cache keep serving."""

    def test_delivery_fault_mid_scan_isolates_victim(self):
        gen = _gen()
        base = _make(batch_size=4, prefix_cache=True)
        baseline = _run_concurrent(base.scheduler, PROMPTS, gen)
        assert all(exc is None for _, exc in baseline)

        eng = _make(batch_size=4, prefix_cache=True)
        sched = eng.scheduler
        victim = PROMPTS[0]
        # fire on the victim's 6th token delivery — with the default
        # 8-step turbo scan armed this lands INSIDE a multi-step scan,
        # so the survivors' rollback path is what's under test
        FAULTS.arm(
            "delivery.detok", "request", count=1,
            match=lambda ctx: (
                ctx["seq"].prompt_ids == victim
                and len(ctx["seq"].generated) >= 5
            ),
        )
        before = _counter("scheduler.requests_failed_isolated")
        results = _run_concurrent(sched, PROMPTS, gen)

        toks0, exc0 = results[0]
        assert isinstance(exc0, RequestError)
        assert toks0 == baseline[0][0][:5]  # clean prefix, then the fault
        for i in (1, 2, 3):
            toks, exc = results[i]
            assert exc is None
            assert toks == baseline[i][0], f"survivor {i} diverged"
        assert FAULTS.fired("delivery.detok") == 1
        assert _counter("scheduler.requests_failed_isolated") == before + 1
        # the pool and prefix cache survived the request-scoped failure...
        assert sched._pool is not None
        assert sched._prefix is not None
        # ...and the victim's prompt replays to the full baseline
        again = list(sched.stream(victim, gen))
        assert again == baseline[0][0]

    def test_admission_fault_isolates_and_slot_is_released(self):
        gen = _gen()
        base = _make()
        solo = list(base.scheduler.stream(PROMPTS[1], gen))

        eng = _make()
        sched = eng.scheduler
        FAULTS.arm(
            "admission.prefill", "request", count=1,
            match=lambda ctx: ctx["seq"].prompt_ids == PROMPTS[0],
        )
        results = _run_concurrent(sched, PROMPTS[:2], gen)
        assert isinstance(results[0][1], RequestError)
        assert results[1][1] is None and results[1][0] == solo
        # the aborted admission released its slot: the victim's prompt
        # re-admits and decodes normally on the same engine
        assert list(sched.stream(PROMPTS[0], gen))
        assert all(s is None for s in sched._slots)

    def test_grammar_compile_fault_falls_back_to_posthoc(self):
        from fei_tpu.agent.providers import JaxLocalProvider

        eng = _make()
        provider = JaxLocalProvider(engine=eng)
        tools = [{"name": "GlobTool", "description": "find",
                  "input_schema": {"type": "object", "properties": {
                      "pattern": {"type": "string"}}}}]
        FAULTS.arm("grammar.compile", "request", count=1)
        # the injected compile failure downgrades THIS schema set to
        # post-hoc parsing (cached None) instead of failing the turn
        assert provider._tool_grammar(tools) is None
        assert FAULTS.fired("grammar.compile") == 1
        # a fresh provider (fresh memo) compiles the same tools fine
        clean = JaxLocalProvider(engine=eng)
        assert clean._tool_grammar(tools) is not None


class TestDeviceDomain:
    def test_device_fault_fails_all_drops_pool_and_recovers(self):
        gen = _gen()
        baseline = list(_make().scheduler.stream(PROMPT, gen))

        eng = _make()
        sched = eng.scheduler
        FAULTS.arm("decode.dispatch", "device", count=1)
        with pytest.raises(DeviceError):
            list(sched.stream(PROMPT, gen))
        # device domain: the donated pool is presumed consumed and dropped
        assert sched._pool is None
        # one failure is below the breaker threshold; the next submit
        # rebuilds the pool and serves identically
        assert not sched.degraded()
        assert list(sched.stream(PROMPT, gen)) == baseline

    def test_breaker_trips_then_resets(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_BREAKER_FAILS", "2")
        monkeypatch.setenv("FEI_TPU_BREAKER_WINDOW_S", "60")
        monkeypatch.setenv("FEI_TPU_BREAKER_COOLDOWN_S", "300")
        gen = _gen()
        eng = _make()
        sched = eng.scheduler
        healthy = list(sched.stream(PROMPT, gen))

        for _ in range(2):
            FAULTS.arm("decode.dispatch", "device", count=1)
            with pytest.raises(DeviceError):
                list(sched.stream(PROMPT, gen))
        assert sched.degraded()
        assert _gauge("engine.degraded") == 1
        shed0 = _counter("scheduler.requests_shed")
        with pytest.raises(EngineDegradedError) as e:
            sched.submit(PROMPT, gen)
        assert e.value.retry_after_s > 0
        assert _counter("scheduler.requests_shed") == shed0 + 1

        sched.reset_degraded()
        assert _gauge("engine.degraded") == 0
        assert list(sched.stream(PROMPT, gen)) == healthy


class TestBackpressure:
    def test_queue_full_sheds_with_retry_after(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_MAX_QUEUE", "2")
        eng = _make()
        sched = eng.scheduler
        # park the loop so the queue depth is deterministic
        monkeypatch.setattr(sched, "_start_thread", lambda: None)
        gen = _gen(max_new_tokens=4)
        queued = [sched.submit(PROMPT, gen) for _ in range(2)]
        shed0 = _counter("scheduler.requests_shed")
        sub0 = _counter("scheduler.requests_submitted")
        with pytest.raises(QueueFullError) as e:
            sched.submit(PROMPT, gen)
        assert e.value.retry_after_s == sched.retry_after_s
        assert _counter("scheduler.requests_shed") == shed0 + 1
        # a shed request was never admitted into the lifecycle
        assert _counter("scheduler.requests_submitted") == sub0
        for s in queued:
            sched.cancel(s)

    def test_server_maps_saturation_to_429_and_503(self, monkeypatch):
        from fei_tpu.agent.providers import JaxLocalProvider
        from fei_tpu.ui.server import ServeAPI

        monkeypatch.setenv("FEI_TPU_MAX_QUEUE", "1")
        eng = _make()
        sched = eng.scheduler
        monkeypatch.setattr(sched, "_start_thread", lambda: None)
        held = sched.submit(PROMPT, _gen(max_new_tokens=4))  # fills the queue
        api = ServeAPI(JaxLocalProvider(engine=eng), model_name="tiny")
        body = {"messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4}

        res = api.handle("POST", "/v1/chat/completions", body, {})
        assert res[0] == 429
        assert res[1]["error"]["type"] == "overloaded_error"
        assert int(res[2]["Retry-After"]) >= 1

        # trip the breaker by hand: degraded maps to 503 + Retry-After
        # and /health flips so load balancers eject the replica
        sched._degraded_until = time.monotonic() + 60
        res = api.handle("POST", "/v1/chat/completions", body, {})
        assert res[0] == 503 and int(res[2]["Retry-After"]) >= 1
        assert api.handle("GET", "/health", {}, {})[0] == 503
        sched.reset_degraded()
        assert api.handle("GET", "/health", {}, {})[0] == 200
        sched.cancel(held)


class TestDeadlines:
    def test_expired_in_queue_sheds_without_occupying_a_slot(self, monkeypatch):
        eng = _make()
        sched = eng.scheduler
        start = sched._start_thread  # bound: restartable after the park
        monkeypatch.setattr(sched, "_start_thread", lambda: None)
        seq = sched.submit(PROMPT, _gen(deadline_s=0.02))
        assert seq.deadline > 0
        time.sleep(0.05)  # the deadline expires while the loop is parked
        shed0 = _counter("scheduler.requests_shed")
        with sched._lock:  # _start_thread's contract: callers hold the lock
            start()
        with pytest.raises(DeadlineExceededError):
            list(sched.drain(seq))
        assert seq.trace.status == "deadline_exceeded"
        # the whole lifecycle happened in the queue: never admitted
        assert "admitted" not in [p for p, _ in seq.trace.events]
        assert _counter("scheduler.requests_shed") == shed0 + 1

    def test_mid_decode_deadline_cancels_with_typed_error(self):
        eng = _make()
        sched = eng.scheduler
        ded0 = _counter("scheduler.requests_deadline_exceeded")
        seq = sched.submit(PROMPT, _gen(max_new_tokens=512))
        it = sched.drain(seq)
        next(it)  # decoding is underway
        seq.deadline = time.perf_counter() - 1.0  # force-expire
        with pytest.raises(DeadlineExceededError):
            for _ in it:
                pass
        assert seq.trace.status == "deadline_exceeded"
        assert _counter("scheduler.requests_deadline_exceeded") == ded0 + 1
        # healthy-pool eviction: the engine keeps serving
        assert sched._pool is not None
        assert len(list(sched.stream(PROMPT, _gen(max_new_tokens=8)))) == 8

    def test_default_deadline_env(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_DEFAULT_DEADLINE_S", "30")
        eng = _make()
        sched = eng.scheduler
        monkeypatch.setattr(sched, "_start_thread", lambda: None)
        seq = sched.submit(PROMPT, _gen())
        assert seq.deadline == pytest.approx(seq.t_queued + 30, abs=1.0)
        sched.cancel(seq)


@pytest.mark.skipif(
    not os.environ.get("FEI_TPU_FAULT"),
    reason="chaos sweep only: set FEI_TPU_FAULT (scripts/*_pipeline.sh)",
)
def test_env_fault_sweep_recovers():
    """Under ANY env-armed engine fault the engine must (a) fail requests
    with typed errors only and (b) serve normally once the fault drains.
    The pipeline chaos stages sweep FEI_TPU_FAULT across kinds/points."""
    FAULTS.load_env()  # the autouse disarm cleared the import-time arming
    eng = _make()
    gen = _gen(max_new_tokens=8)
    for _ in range(4):
        try:
            list(eng.scheduler.stream(PROMPT, gen))
        except Exception:  # noqa: BLE001 — injected faults surface here
            pass
    FAULTS.disarm()
    eng.scheduler.reset_degraded()  # a device sweep may trip the breaker
    assert len(list(eng.scheduler.stream(PROMPT, gen))) == 8
