"""The on-chip record must be outage-proof (VERDICT r3 #1): real TPU
measurements persist to onchip_state.json, and the CPU-fallback bench line
carries the last on-chip result as structured metadata so a backend outage
at snapshot time can no longer erase the record from the driver artifact."""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench"] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "STATE_PATH", str(tmp_path / "state.json"))
    monkeypatch.delenv("FEI_TPU_BENCH_CPU_FALLBACK", raising=False)
    monkeypatch.delenv("FEI_TPU_BENCH_ONCHIP", raising=False)
    yield mod
    sys.modules.pop("bench", None)


def _last_line(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_onchip_emit_persists_state(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit("m-8b-int8_decode_tok_s_per_chip", 71.81,
                extra={"ttft_ms": 164.1})
    line = _last_line(capsys)
    assert line["metric"] == "m-8b-int8_decode_tok_s_per_chip"
    state = json.loads(Path(bench.STATE_PATH).read_text())
    rec = state["last_onchip"]
    assert rec["value"] == 71.81
    assert rec["ttft_ms"] == 164.1
    assert "ts" in rec
    assert state["suites"]["m-8b-int8_decode_tok_s_per_chip"] == rec


def test_gate_metric_owns_headline_slot(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 70.0)
    # later pipeline stages — paged, moe decode, int4 decode — are recorded
    # but must NOT displace the gate number from the headline slot
    bench._emit("llama3-1b_paged_4stream_agg_tok_s_per_chip", 175.0)
    bench._emit("moe-2b_decode_tok_s_per_chip", 141.9)
    bench._emit("llama3-8b-int4_decode_tok_s_per_chip", 100.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["last_onchip"]["metric"] == bench.GATE_METRIC
    assert state["last_onchip"]["value"] == 70.0
    assert len(state["suites"]) == 4
    bench._emit(bench.GATE_METRIC, 72.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["last_onchip"]["value"] == 72.0


def test_cpu_fallback_carries_last_onchip(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 71.81)
    capsys.readouterr()
    monkeypatch.delenv("FEI_TPU_BENCH_ONCHIP")
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_decode_tok_s_per_chip", 239.4)
    line = _last_line(capsys)
    assert line["metric"].endswith("_CPU_FALLBACK_TPU_UNAVAILABLE")
    assert line["last_onchip"]["value"] == 71.81
    # the fallback line itself must never be recorded as an on-chip result
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert "tiny" not in json.dumps(state)


def test_fallback_without_state_still_emits(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_decode_tok_s_per_chip", 1.0)
    line = _last_line(capsys)
    assert "last_onchip" not in line


def test_committed_state_carries_gate():
    """The committed state file must always hold A gate measurement — the
    round-3 seed (71.81) or any later on-chip refresh — above the 20 tok/s
    floor, taken on a real TPU."""
    state = json.loads((REPO / "onchip_state.json").read_text())
    rec = state["last_onchip"]
    assert rec["metric"] == "llama3-8b-int8_decode_tok_s_per_chip"
    assert rec["value"] >= 20.0  # the BASELINE north-star floor
    assert rec["device"].startswith("TPU")
    assert "ts" in rec and "ttft_ms" in rec
