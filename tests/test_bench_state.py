"""The on-chip record must be outage-proof (VERDICT r3 #1): real TPU
measurements persist to onchip_state.json, and the CPU-fallback bench line
carries the last on-chip result as structured metadata so a backend outage
at snapshot time can no longer erase the record from the driver artifact."""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench"] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "STATE_PATH", str(tmp_path / "state.json"))
    monkeypatch.delenv("FEI_TPU_BENCH_CPU_FALLBACK", raising=False)
    monkeypatch.delenv("FEI_TPU_BENCH_ONCHIP", raising=False)
    yield mod
    sys.modules.pop("bench", None)


def _last_line(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_onchip_emit_persists_state(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 71.81, extra={"ttft_ms": 164.1})
    line = _last_line(capsys)
    assert line["metric"] == bench.GATE_METRIC
    assert line["ttft_ms"] == 164.1  # extras ride the printed line too
    state = json.loads(Path(bench.STATE_PATH).read_text())
    rec = state["last_onchip"]
    assert rec["value"] == 71.81
    assert rec["ttft_ms"] == 164.1
    assert "ts" in rec
    assert state["suites"][bench.GATE_METRIC] == rec


def test_non_gate_suite_never_occupies_headline(bench, monkeypatch, capsys):
    """A first-recorded non-gate stage (int4 A/B, paged) must not own the
    outage-carried headline slot, even when no gate result exists yet
    (round-4 advisory): the slot stays empty until the gate metric runs."""
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit("llama3-8b-int4_decode_tok_s_per_chip", 100.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert "last_onchip" not in state
    assert "llama3-8b-int4_decode_tok_s_per_chip" in state["suites"]
    bench._emit(bench.GATE_METRIC, 70.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["last_onchip"]["metric"] == bench.GATE_METRIC


def test_gate_metric_owns_headline_slot(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 70.0)
    # later pipeline stages — paged, moe decode, int4 decode — are recorded
    # but must NOT displace the gate number from the headline slot
    bench._emit("llama3-1b_paged_4stream_agg_tok_s_per_chip", 175.0)
    bench._emit("moe-2b_decode_tok_s_per_chip", 141.9)
    bench._emit("llama3-8b-int4_decode_tok_s_per_chip", 100.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["last_onchip"]["metric"] == bench.GATE_METRIC
    assert state["last_onchip"]["value"] == 70.0
    assert len(state["suites"]) == 4
    bench._emit(bench.GATE_METRIC, 72.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["last_onchip"]["value"] == 72.0


def test_cpu_fallback_headline_is_gate_record(bench, monkeypatch, capsys):
    """On CPU fallback the HEADLINE parsed fields are the last real gate
    measurement, clearly marked stale; the CPU number is demoted to
    liveness metadata (round-4 verdict #4: a driver reading parsed.value
    gets a TPU number in both the live and the outage case)."""
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 71.81, extra={"ttft_ms": 164.1})
    capsys.readouterr()
    monkeypatch.delenv("FEI_TPU_BENCH_ONCHIP")
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_decode_tok_s_per_chip", 239.4)
    line = _last_line(capsys)
    assert line["metric"] == bench.GATE_METRIC
    assert line["value"] == 71.81
    assert line["ttft_ms"] == 164.1
    assert line["stale"] is True
    assert line["source"].startswith("onchip_state ")
    assert line["cpu_liveness"]["value"] == 239.4
    assert line["cpu_liveness"]["metric"].endswith("_CPU_FALLBACK")
    # the fallback line itself must never be recorded as an on-chip result
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert "tiny" not in json.dumps(state)


def test_best_onchip_tracks_max_gate_value(bench, monkeypatch, capsys):
    """best-AND-latest: consecutive lease windows measured 71.8 then 30.7
    tok/s for the SAME config (backend variance). The latest value owns
    last_onchip; the best survives in its own slot."""
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 71.81)
    bench._emit(bench.GATE_METRIC, 30.7)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["last_onchip"]["value"] == 30.7
    assert state["best_onchip"]["value"] == 71.81
    bench._emit(bench.GATE_METRIC, 80.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["last_onchip"]["value"] == 80.0
    assert state["best_onchip"]["value"] == 80.0


def test_best_onchip_ignores_non_gate_suites(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit("llama3-8b-int4_decode_tok_s_per_chip", 500.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert "best_onchip" not in state
    bench._emit(bench.GATE_METRIC, 70.0)
    state = json.loads(Path(bench.STATE_PATH).read_text())
    assert state["best_onchip"]["value"] == 70.0  # int4's 500 never counted


def test_cpu_fallback_reports_best_and_latest(bench, monkeypatch, capsys):
    """Outage headline = LATEST gate number (stale-marked), with the BEST
    one attached so window-to-window variance reads as variance, not as a
    framework regression."""
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 71.81)
    bench._emit(bench.GATE_METRIC, 30.7)
    capsys.readouterr()
    monkeypatch.delenv("FEI_TPU_BENCH_ONCHIP")
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_decode_tok_s_per_chip", 239.4)
    line = _last_line(capsys)
    assert line["value"] == 30.7
    assert line["stale"] is True
    assert line["best_onchip"]["value"] == 71.81
    assert "ts" in line["best_onchip"]


def test_cpu_fallback_never_promotes_non_gate(bench, monkeypatch, capsys):
    """With only non-gate suites recorded, the fallback must keep the
    honest CPU label instead of promoting a non-gate number."""
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit("llama3-8b-int4_decode_tok_s_per_chip", 100.0)
    capsys.readouterr()
    monkeypatch.delenv("FEI_TPU_BENCH_ONCHIP")
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_decode_tok_s_per_chip", 239.4)
    line = _last_line(capsys)
    assert line["metric"].endswith("_CPU_FALLBACK_TPU_UNAVAILABLE")
    assert line["value"] == 239.4
    assert "stale" not in line


def test_cpu_fallback_non_decode_keeps_suite_identity(bench, monkeypatch, capsys):
    """A mid-pipeline outage during a prefill/paged/agent stage must NOT
    replace that stage's line with the decode gate record — the suite's own
    (labeled) metric survives, the gate rides as metadata."""
    monkeypatch.setenv("FEI_TPU_BENCH_ONCHIP", "1")
    bench._emit(bench.GATE_METRIC, 71.81)
    capsys.readouterr()
    monkeypatch.delenv("FEI_TPU_BENCH_ONCHIP")
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_prefill512_tok_s_per_chip", 900.0,
                extra={"ttft_ms": 570.0})
    line = _last_line(capsys)
    assert line["metric"] == (
        "tiny_prefill512_tok_s_per_chip_CPU_FALLBACK_TPU_UNAVAILABLE"
    )
    assert line["value"] == 900.0
    assert line["last_onchip"]["metric"] == bench.GATE_METRIC
    assert "stale" not in line


def test_cpu_fallback_strips_tpu_roofline_extras(bench, monkeypatch, capsys):
    """pct_v5e_hbm for a run that never touched a TPU is disinformation —
    the fallback line must drop the roofline fields."""
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_decode_tok_s_per_chip", 60.0,
                extra={"ttft_ms": 57.0, "gb_per_tok": 0.001,
                       "achieved_gbps": 0.06, "pct_v5e_hbm": 0.0,
                       "roofline_tok_s": 3306686.0})
    line = _last_line(capsys)
    for k in ("gb_per_tok", "achieved_gbps", "pct_v5e_hbm", "roofline_tok_s"):
        assert k not in line
    assert line["ttft_ms"] == 57.0


def test_fallback_without_state_still_emits(bench, monkeypatch, capsys):
    monkeypatch.setenv("FEI_TPU_BENCH_CPU_FALLBACK", "1")
    bench._emit("tiny_decode_tok_s_per_chip", 1.0)
    line = _last_line(capsys)
    assert "last_onchip" not in line


def test_committed_state_carries_gate():
    """The committed state file must always hold A gate measurement — the
    round-3 seed (71.81) or any later on-chip refresh — above the 20 tok/s
    floor, taken on a real TPU."""
    state = json.loads((REPO / "onchip_state.json").read_text())
    rec = state["last_onchip"]
    assert rec["metric"] == "llama3-8b-int8_decode_tok_s_per_chip"
    assert rec["value"] >= 20.0  # the BASELINE north-star floor
    assert rec["device"].startswith("TPU")
    assert "ts" in rec and "ttft_ms" in rec
