"""Sharded continuous batching: the tp×dp mesh as the engine's serving mode.

The claims under test (docs/ENGINE.md "Mesh modes"):

- ``FEI_TPU_MESH=tp2`` routes the paged scheduler — prefill, decode
  dispatch, sampling — through the shard_map'd kernel on a real mesh, and
  the output is TOKEN-IDENTICAL to the single-chip engine, greedy AND
  seeded. The serving profile replicates weights (Megatron psums reorder
  summation and flip near-tie argmax); only the page pool (kv heads over
  tp) and the dispatch batch (rows over dp) shard.
- dp replica groups MULTIPLY the aggregate decode slots: ``batch_size``
  is per-replica, the scheduler serves dp× slots.
- The PR 4-5 survival machinery keeps working sharded: preempt-and-resume
  stays byte-identical under tp2, drain → warm-restart round-trips, and a
  warm restart onto a DIFFERENT mesh geometry RESTORES byte-identically
  (mesh is provenance since snapshot v3; docs/ENGINE.md "Mesh
  elasticity"). The one geometry axis still refused is page_size, with a
  typed error — and the snapshot file survives the refusal.

Everything runs on the conftest-forced 8-device CPU host mesh.
"""

from __future__ import annotations

import os
import threading

import jax
import pytest

from fei_tpu.engine.checkpoint import (
    CheckpointError,
    load_request_snapshots,
    save_request_snapshots,
)
from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.parallel.mesh import (
    AXES,
    mesh_from_env,
    mesh_geometry,
    mesh_tag,
    parse_mesh_shape,
)
from fei_tpu.utils.metrics import METRICS

from conftest import requires_shard_map

pytestmark = requires_shard_map

PROMPT = list(range(11, 29))
PROMPTS = [list(range(11 + i, 29 + i)) for i in range(3)]


def _gen(**kw) -> GenerationConfig:
    kw.setdefault("max_new_tokens", 16)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("ignore_eos", True)
    return GenerationConfig(**kw)


def _make_env(mesh_spec: str | None = None, **kwargs):
    """A tiny paged engine, optionally in FEI_TPU_MESH serving mode.

    Sets/clears the env var around from_config directly (no monkeypatch)
    so module/class-scoped fixtures can share ONE engine per mesh mode —
    each meshed engine pays ~50s of shard_map compile on the 8-device
    CPU mesh, so per-test engines would dominate the tier-1 budget."""
    old = os.environ.get("FEI_TPU_MESH")
    if mesh_spec:
        os.environ["FEI_TPU_MESH"] = mesh_spec
    else:
        os.environ.pop("FEI_TPU_MESH", None)
    try:
        return InferenceEngine.from_config(
            "tiny", paged=True, batch_size=kwargs.pop("batch_size", 2),
            **kwargs,
        )
    finally:
        if old is None:
            os.environ.pop("FEI_TPU_MESH", None)
        else:
            os.environ["FEI_TPU_MESH"] = old


def _make(monkeypatch, mesh_spec: str | None = None, **kwargs):
    """Function-scoped spelling of _make_env (the monkeypatch arg just
    documents that the caller owns per-test env state)."""
    del monkeypatch
    return _make_env(mesh_spec, **kwargs)


def _counter(name: str) -> float:
    return METRICS.snapshot()["counters"].get(name, 0)


class TestMeshEnv:
    """FEI_TPU_MESH parsing and the mesh_from_env contract."""

    def test_single_chip_spellings(self):
        for spec in ("", "0", "off", "none", "single", "ms1"):
            assert mesh_from_env(env=spec) is None

    def test_compact_and_legacy_specs(self):
        m = mesh_from_env(num_kv_heads=2, env="tp2")
        assert mesh_tag(m) == "tp2"
        m = mesh_from_env(num_kv_heads=2, env="dp2tp2")
        assert mesh_geometry(m)["dp"] == 2 and mesh_geometry(m)["tp"] == 2
        legacy = mesh_from_env(num_kv_heads=2, env="dp=2,tp=2")
        assert mesh_geometry(legacy) == mesh_geometry(m)

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_mesh_shape("tp2xx")
        with pytest.raises(ValueError):
            mesh_from_env(env="zz9")

    def test_too_many_devices_rejected(self):
        with pytest.raises(ValueError, match="devices"):
            mesh_from_env(num_kv_heads=64, env="tp64")

    def test_tp_must_divide_kv_heads(self):
        with pytest.raises(ValueError, match="kv heads"):
            mesh_from_env(num_kv_heads=2, env="tp4")

    def test_auto_uses_visible_devices(self):
        m = mesh_from_env(num_kv_heads=8, env="auto")
        assert m is not None
        assert m.devices.size == len(jax.devices())

    def test_all_ones_collapses_to_single_chip(self):
        assert mesh_from_env(env="tp1") is None
        assert mesh_geometry(None) == {ax: 1 for ax in AXES}
        assert mesh_tag(None) == "ms1"


@pytest.fixture(scope="class")
def parity_engines():
    """ONE ms1 reference engine + ONE tp2 engine shared by the parity
    tests: the tp2 shard_map compile is the dominant cost, and streams on
    a live scheduler are independent, so sharing engines changes nothing
    about what the tests prove."""
    # batch_size=2: XLA compile scales steeply with batch width here
    # (bs=4 costs ~3x), and the parity streams run one at a time anyway
    ms1 = _make_env(None, batch_size=2)
    tp2 = _make_env("tp2", batch_size=2)
    yield ms1, tp2
    ms1.scheduler.close()
    tp2.scheduler.close()


class TestShardedParity:
    """tp2 decode through the paged scheduler is token-identical to ms1."""

    def test_tp2_greedy_token_identical(self, parity_engines):
        ms1, tp2 = parity_engines
        gen = _gen()
        ref = list(ms1.scheduler.stream(PROMPT, gen))
        assert mesh_tag(tp2.mesh) == "tp2"
        got = list(tp2.scheduler.stream(PROMPT, gen))
        assert got == ref

    # each distinct (engine, sampling-config) pair pays its own ~20s
    # shard_map compile on the CPU mesh, so only the greedy tp2 parity
    # proof rides the fast tier-1 lane; the seeded / tp2dp2 / preemption
    # variants run in the slow lane and FOR REAL in
    # scripts/rehearse_pipeline.sh's sharded_serving stage.
    @pytest.mark.slow
    def test_tp2_seeded_token_identical(self, parity_engines):
        ms1, tp2 = parity_engines
        gen = _gen(temperature=0.8, seed=1234, top_k=20)
        ref = list(ms1.scheduler.stream(PROMPT, gen))
        got = list(tp2.scheduler.stream(PROMPT, gen))
        assert got == ref

    @pytest.mark.slow
    def test_tp2dp2_token_identical(self, parity_engines):
        """Adding dp replica groups must not change a stream's tokens —
        the batch-row split is numerics-neutral. batch_size=2 on dp2
        also proves the slot multiplication on a live engine."""
        ms1, _ = parity_engines
        gen = _gen()
        ref = list(ms1.scheduler.stream(PROMPT, gen))
        eng = _make_env("tp2dp2", batch_size=2)
        try:
            assert eng.batch_size == 4  # 2 per replica x dp2
            got = list(eng.scheduler.stream(PROMPT, gen))
        finally:
            eng.scheduler.close()
        assert got == ref

    def test_dp_multiplies_decode_slots(self, monkeypatch):
        eng = _make(monkeypatch, "dp2", batch_size=2)
        try:
            assert eng.batch_size == 4  # 2 slots per replica x dp2
        finally:
            eng.scheduler.close()
        ms1 = _make(monkeypatch, None, batch_size=2)
        try:
            assert ms1.batch_size == 2
        finally:
            ms1.scheduler.close()

    def test_weights_profile_validated(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_MESH_WEIGHTS", "diagonal")
        monkeypatch.setenv("FEI_TPU_MESH", "tp2")
        with pytest.raises(ValueError, match="weights profile"):
            InferenceEngine.from_config("tiny", paged=True, batch_size=2)

    def test_serving_mode_replicates_weights(self, monkeypatch):
        """The bit-identity guarantee rests on replicated weights: no
        param of the serving-mode engine may shard over tp."""
        import jax.tree_util as jtu

        eng = _make(monkeypatch, "tp2", batch_size=2)
        try:
            for leaf in jtu.tree_leaves(eng.params):
                spec = getattr(leaf.sharding, "spec", None)
                assert spec is not None
                assert all(s is None for s in spec), spec
        finally:
            eng.scheduler.close()


class TestShardedSurvival:
    """PR 4-5 machinery under tp2: preempt/resume, drain, warm restart."""

    def _tight(self, monkeypatch, mesh_spec):
        """A pool two worst-case reservations cannot share (the
        test_preemption sizing) so preemption triggers organically."""
        return _make(
            monkeypatch, mesh_spec,
            page_size=4, num_pages=14, prefix_cache=True, batch_size=2,
        )

    @pytest.mark.slow  # see TestShardedParity: one compile per lane test
    def test_tp2_preempt_resume_byte_identical(self, monkeypatch):
        gen = _gen(max_new_tokens=24)
        roomy = _make(monkeypatch, "tp2", prefix_cache=True, batch_size=2)
        refs = [list(roomy.scheduler.stream(p, gen)) for p in PROMPTS]
        roomy.scheduler.close()

        eng = self._tight(monkeypatch, "tp2")
        sched = eng.scheduler
        p0 = _counter("scheduler.preemptions")
        seqs = [sched.submit(p, gen) for p in PROMPTS]
        results: list = [None] * len(PROMPTS)

        def go(i):
            results[i] = list(sched.drain(seqs[i]))

        ts = [threading.Thread(target=go, args=(i,)) for i in range(3)]
        [t.start() for t in ts]
        [t.join(timeout=300) for t in ts]
        sched.close()
        assert _counter("scheduler.preemptions") > p0
        for i, toks in enumerate(results):
            assert toks == refs[i], f"stream {i} diverged after preemption"

    def test_tp2_drain_warm_restart_round_trip(self, monkeypatch, tmp_path):
        gen = _gen()
        roomy = _make(monkeypatch, "tp2", prefix_cache=True)
        refs = [list(roomy.scheduler.stream(p, gen)) for p in PROMPTS[:2]]
        roomy.scheduler.close()

        eng = _make(monkeypatch, "tp2")
        sched = eng.scheduler
        monkeypatch.setattr(sched, "_start_thread", lambda: None)  # park
        for p in PROMPTS[:2]:
            sched.submit(p, gen)
        eng.begin_drain(deadline_s=0, snapshot_dir=str(tmp_path))
        assert sched.wait_drained(timeout=10)

        # the snapshot payload carries the mesh geometry it drained on
        snaps = load_request_snapshots(
            str(tmp_path), expect_mesh=mesh_geometry(eng.mesh)
        )
        assert len(snaps) == 2
        assert all(s["mesh"]["tp"] == 2 for s in snaps)

        eng2 = _make(monkeypatch, "tp2", prefix_cache=True)
        restored = eng2.warm_restart(str(tmp_path))
        assert len(restored) == 2
        outs = [list(eng2.scheduler.drain(s)) for s in restored]
        eng2.scheduler.close()
        assert outs == refs

    def test_warm_restart_crosses_mesh_byte_identical(self, monkeypatch,
                                                      tmp_path):
        """The shrink scenario: a tp2 replica drains, the replacement
        boots on a SINGLE chip, and the restored stream is byte-identical
        to an uninterrupted single-chip run — snapshot mesh is
        provenance (v3), not a restore gate."""
        gen = _gen()
        ref_eng = _make(monkeypatch, None)
        ref = list(ref_eng.scheduler.stream(PROMPT, gen))
        ref_eng.scheduler.close()

        eng = _make(monkeypatch, "tp2")
        sched = eng.scheduler
        monkeypatch.setattr(sched, "_start_thread", lambda: None)
        sched.submit(PROMPT, gen)
        eng.begin_drain(deadline_s=0, snapshot_dir=str(tmp_path))
        assert sched.wait_drained(timeout=10)
        snaps = load_request_snapshots(str(tmp_path))
        assert all(s["mesh"]["tp"] == 2 for s in snaps)

        ms1 = _make(monkeypatch, None)
        restored = ms1.warm_restart(str(tmp_path))
        assert len(restored) == 1
        out = list(ms1.scheduler.drain(restored[0]))
        ms1.scheduler.close()
        assert out == ref

    def test_warm_restart_refuses_page_size_mismatch(self, monkeypatch,
                                                     tmp_path):
        """page_size is the ONE geometry axis restore still gates on
        (it changes the paged kernel's summation order): typed error
        naming both sizes, and the snapshot file survives the refusal
        so a matching engine still restores afterwards."""
        from fei_tpu.utils.errors import PageSizeMismatchError

        gen = _gen()
        eng = _make(monkeypatch, None, page_size=4)
        sched = eng.scheduler
        monkeypatch.setattr(sched, "_start_thread", lambda: None)
        sched.submit(PROMPT, gen)
        eng.begin_drain(deadline_s=0, snapshot_dir=str(tmp_path))
        assert sched.wait_drained(timeout=10)

        other = _make(monkeypatch, None, page_size=8)
        monkeypatch.setattr(other.scheduler, "_start_thread",
                            lambda: None)
        with pytest.raises(PageSizeMismatchError) as exc:
            other.warm_restart(str(tmp_path))
        assert exc.value.ours == 8 and exc.value.theirs == 4
        assert isinstance(exc.value, CheckpointError)  # old catches work
        other.scheduler.close()

        same = _make(monkeypatch, None, page_size=4)
        monkeypatch.setattr(same.scheduler, "_start_thread", lambda: None)
        assert len(same.warm_restart(str(tmp_path))) == 1
        same.scheduler.close()

    def test_legacy_v1_snapshots_load_on_any_mesh(self, tmp_path):
        """A v1 file (pre-mesh, pre-page_size) loads everywhere: its
        writer's only page size was the default, and mesh stopped being
        a gate in v3."""
        import json
        import os

        snaps = [{"rid": "req-1", "prompt_ids": [1, 2], "generated": [3]}]
        os.makedirs(str(tmp_path), exist_ok=True)
        with open(tmp_path / "requests.json", "w") as f:
            json.dump({"version": 1, "requests": snaps}, f)
        assert load_request_snapshots(
            str(tmp_path), expect_mesh=mesh_geometry(None)
        ) == snaps
        tp2_geo = dict(mesh_geometry(None), tp=2)
        assert load_request_snapshots(
            str(tmp_path), expect_mesh=tp2_geo, expect_page_size=64
        ) == snaps

    def test_save_records_geometry(self, tmp_path):
        save_request_snapshots(str(tmp_path), [{"rid": "r"}], page_size=16)
        import json

        payload = json.loads((tmp_path / "requests.json").read_text())
        assert payload["version"] == 3
        assert payload["mesh"] == mesh_geometry(None)
        assert payload["page_size"] == 16
