"""Multi-tenant QoS: weighted-fair admission, priority shed ordering,
and priority slot preemption (docs/FLEET.md "Multi-tenant QoS").

The claims under test:
- requests carry ``tenant`` + ``priority`` (GenerationConfig fields fed
  from the body or the X-FEI-* headers); labels sanitize to a metric-
  safe alphabet and priorities clamp to small ordinal classes;
- with no FEI_TPU_TENANT_BUDGETS table and uniform priorities the
  admission order is EXACTLY the legacy FIFO head (byte-identity and
  starvation guarantees unchanged);
- with a policy table, admission is start-time weighted fair queueing
  over served tokens: two always-backlogged tenants at weights 3:1 are
  admitted within 10% of 3:1; priority classes admit strictly first;
  a tenant's token budget defers its admissions while its running
  sequences hold the budget;
- backpressure sheds in priority order: a full queue evicts the
  lowest-priority newest-queued request STRICTLY below the arrival
  (equals keep FIFO fairness), so 429s land on priority 0 first;
- a high-priority arrival with no free slot preempts a strictly
  lower-priority running victim through the snapshot/resume ladder and
  the victim's stream is BYTE-IDENTICAL to an unpreempted run — greedy
  and seeded.
"""

from __future__ import annotations

import threading
import time

import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.tenancy import (
    TenantBook,
    TenantPolicy,
    clamp_priority,
    parse_tenant_budgets,
    sanitize_tenant,
)
from fei_tpu.utils.errors import QueueFullError
from fei_tpu.utils.metrics import METRICS

PROMPT = list(range(11, 29))


def _counter(name: str) -> float:
    return METRICS.snapshot()["counters"].get(name, 0)


def _gen(**kw) -> GenerationConfig:
    kw.setdefault("max_new_tokens", 16)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("ignore_eos", True)
    return GenerationConfig(**kw)


def _make(**kwargs) -> InferenceEngine:
    return InferenceEngine.from_config(
        "tiny", paged=True, batch_size=kwargs.pop("batch_size", 2), **kwargs
    )


def _parked(**kwargs):
    """An engine whose scheduler thread never starts: submits park in
    the waiting queue so admission order and shed ordering are
    observable as pure data-structure facts (no decode, no sleeps)."""
    eng = _make(**kwargs)
    sched = eng.scheduler
    sched._start_thread = lambda: None
    return sched


class TestPolicyParse:
    def test_full_spec(self):
        t = parse_tenant_budgets("gold:4,silver:2:8,bronze:1:4:4096,*:1")
        assert t["gold"] == TenantPolicy("gold", 4.0, 0, 0)
        assert t["silver"] == TenantPolicy("silver", 2.0, 8, 0)
        assert t["bronze"] == TenantPolicy("bronze", 1.0, 4, 4096)
        assert t["*"].weight == 1.0

    def test_malformed_entries_skip_not_raise(self):
        t = parse_tenant_budgets("gold:nope,:3,silver:2,,x:1:bad")
        assert set(t) == {"silver"}

    def test_non_positive_weight_coerces_to_one(self):
        assert parse_tenant_budgets("a:0")["a"].weight == 1.0
        assert parse_tenant_budgets("a:-3")["a"].weight == 1.0

    def test_names_sanitize(self):
        t = parse_tenant_budgets("team a/b:2")
        assert "team_a_b" in t
        assert sanitize_tenant("  spaced out!  ") == "spaced_out_"
        assert sanitize_tenant("") == "default"
        assert len(sanitize_tenant("x" * 200)) == 64

    def test_priority_clamps(self):
        assert clamp_priority(999999) == 9
        assert clamp_priority(-4) == 0
        assert clamp_priority("3") == 3
        assert clamp_priority("soon") == 0
        assert clamp_priority(None) == 0


class TestTenantBook:
    def test_unconfigured_book_is_fast_path_eligible(self):
        book = TenantBook(policies={})
        assert not book.configured
        assert book.policy("anyone").weight == 1.0

    def test_charge_scales_inverse_to_weight(self):
        book = TenantBook(policies=parse_tenant_budgets("gold:4,bronze:1"))
        book.charge("gold", 8)
        book.charge("bronze", 8)
        assert book.vtime("gold") == pytest.approx(2.0)
        assert book.vtime("bronze") == pytest.approx(8.0)

    def test_activate_reanchors_at_busy_floor(self):
        book = TenantBook(policies=parse_tenant_budgets("a:1,b:1"))
        book.charge("a", 100)
        book.charge("b", 120)
        # c was idle the whole time: it competes from the floor, not
        # from vtime 0 (which would starve a and b while c catches up)
        book.activate("c", [book.vtime("a"), book.vtime("b")])
        assert book.vtime("c") == pytest.approx(100.0)
        # re-activating a busy tenant never moves it backwards
        book.activate("b", [book.vtime("a")])
        assert book.vtime("b") == pytest.approx(120.0)


class TestAdmissionOrder:
    def test_uniform_no_budgets_is_exact_legacy_fifo(self):
        sched = _parked()
        seqs = [sched.submit(PROMPT, _gen()) for _ in range(4)]
        assert not sched.tenants.configured
        assert sched._next_admission_locked() is seqs[0]

    def test_higher_priority_class_admits_first(self):
        sched = _parked()
        sched.submit(PROMPT, _gen(priority=0))
        hi = sched.submit(PROMPT, _gen(priority=2))
        sched.submit(PROMPT, _gen(priority=1))
        assert sched._next_admission_locked() is hi

    def test_wfq_three_to_one_within_ten_percent(self):
        """The fairness pin: both tenants permanently backlogged, each
        admission charged the same service — admission counts land
        within 10% of the configured 3:1 weights."""
        sched = _parked()
        book = TenantBook(policies=parse_tenant_budgets("gold:3,bronze:1"))
        sched.tenants = book
        for i in range(40):
            sched.submit(PROMPT, _gen(tenant="gold"))
            sched.submit(PROMPT, _gen(tenant="bronze"))
        served = {"gold": 0, "bronze": 0}
        for _ in range(40):
            pick = sched._next_admission_locked()
            assert pick is not None
            sched._waiting.remove(pick)
            served[pick.tenant] += 1
            book.charge(pick.tenant, 8)  # same tokens per admission
        share = served["gold"] / 40
        assert abs(share - 0.75) <= 0.075, served

    def test_token_budget_defers_tenant(self):
        sched = _parked()
        sched.tenants = TenantBook(
            policies=parse_tenant_budgets("capped:1:0:64,free:1")
        )
        d0 = _counter("scheduler.tenant_budget_deferred")
        # a running sequence holding capped's whole budget
        running = sched.submit(PROMPT, _gen(tenant="capped", max_new_tokens=46))
        sched._waiting.remove(running)
        sched._slots[0] = running
        queued_capped = sched.submit(PROMPT, _gen(tenant="capped"))
        queued_free = sched.submit(PROMPT, _gen(tenant="free"))
        assert sched._next_admission_locked() is queued_free
        assert _counter("scheduler.tenant_budget_deferred") > d0
        # with nothing of capped's running it always gets a floor of one
        sched._slots[0] = None
        sched._waiting.remove(queued_free)
        assert sched._next_admission_locked() is queued_capped

    def test_budget_deferred_class_falls_through_to_lower_priority(self):
        """Work conservation: when EVERY tenant in the top waiting class
        is token-budget-deferred, admission falls through to the next
        class instead of idling free slots behind the capped queue."""
        sched = _parked()
        sched.tenants = TenantBook(
            policies=parse_tenant_budgets("capped:1:0:64,free:1")
        )
        running = sched.submit(
            PROMPT, _gen(tenant="capped", max_new_tokens=46, priority=2)
        )
        sched._waiting.remove(running)
        sched._slots[0] = running
        # the only top-class candidate is budget-held...
        sched.submit(PROMPT, _gen(tenant="capped", priority=2))
        lo = sched.submit(PROMPT, _gen(tenant="free", priority=0))
        # ...so the lower class admits rather than nobody
        assert sched._next_admission_locked() is lo


class TestShedOrdering:
    """429s land on the lowest priority class first."""

    def _full_queue(self, priorities, max_queue=None):
        sched = _parked()
        sched.max_queue = max_queue if max_queue is not None else len(priorities)
        seqs = [sched.submit(PROMPT, _gen(priority=p)) for p in priorities]
        return sched, seqs

    def _shed_error(self, seq):
        item = seq.out.get_nowait()
        assert isinstance(item, QueueFullError), item
        return item

    def test_arrival_evicts_newest_of_lowest_class(self):
        sched, seqs = self._full_queue([0, 1, 0])
        arrival = sched.submit(PROMPT, _gen(priority=2))
        # newest priority-0 (index 2) was evicted, not the older one
        assert seqs[2] not in sched._waiting
        assert seqs[0] in sched._waiting and seqs[1] in sched._waiting
        assert arrival in sched._waiting
        err = self._shed_error(seqs[2])
        assert err.retry_after_s > 0
        assert seqs[2].trace.status == "shed"

    def test_priority_ladder_drains_bottom_up(self):
        sched, seqs = self._full_queue([0, 1, 2])
        sched.submit(PROMPT, _gen(priority=2))  # evicts the 0
        self._shed_error(seqs[0])
        sched.submit(PROMPT, _gen(priority=2))  # then the 1
        self._shed_error(seqs[1])
        # only priority-2 requests remain: an equal arrival sheds ITSELF
        with pytest.raises(QueueFullError):
            sched.submit(PROMPT, _gen(priority=2))
        assert seqs[2] in sched._waiting

    def test_equal_priorities_keep_fifo_no_eviction(self):
        sched, seqs = self._full_queue([1, 1, 1])
        s0 = _counter("scheduler.requests_shed")
        with pytest.raises(QueueFullError):
            sched.submit(PROMPT, _gen(priority=1))
        assert all(s in sched._waiting for s in seqs)
        assert _counter("scheduler.requests_shed") == s0 + 1

    def test_lower_priority_arrival_sheds_itself(self):
        sched, seqs = self._full_queue([2, 2, 2])
        with pytest.raises(QueueFullError):
            sched.submit(PROMPT, _gen(priority=0))
        assert all(s in sched._waiting for s in seqs)

    def test_per_tenant_queue_cap(self):
        sched = _parked()
        sched.max_queue = 0  # only the tenant cap below applies
        sched.tenants = TenantBook(
            policies=parse_tenant_budgets("capped:1:2,free:1")
        )
        a = sched.submit(PROMPT, _gen(tenant="capped", priority=0))
        sched.submit(PROMPT, _gen(tenant="capped", priority=1))
        # the cap binds per tenant: other tenants are unaffected
        sched.submit(PROMPT, _gen(tenant="free"))
        # an equal-priority arrival over the cap sheds itself...
        with pytest.raises(QueueFullError, match="capped"):
            sched.submit(PROMPT, _gen(tenant="capped", priority=0))
        # ...a higher-priority one evicts within the tenant's own queue
        sched.submit(PROMPT, _gen(tenant="capped", priority=2))
        assert a not in sched._waiting
        self_err = a.out.get_nowait()
        assert isinstance(self_err, QueueFullError)

    def test_tenant_shed_metrics_move(self):
        t0 = _counter("tenant.solo.sheds")
        sched, _ = self._full_queue([0])
        sched.submit(PROMPT, _gen(priority=1, tenant="solo"))  # evicts the 0
        with pytest.raises(QueueFullError):  # only solo's own p1 left
            sched.submit(PROMPT, _gen(priority=1, tenant="solo"))
        assert _counter("tenant.solo.sheds") == t0 + 1

    def test_evicted_victim_counts_into_requests_shed(self):
        """A queue-evicted victim is a shed request like any other
        backpressure rejection (the trace.py 'shed' phase contract)."""
        sched, _ = self._full_queue([0])
        s0 = _counter("scheduler.requests_shed")
        sched.submit(PROMPT, _gen(priority=1))  # evicts the priority-0
        assert _counter("scheduler.requests_shed") == s0 + 1

    def test_append_time_cap_check_backstops_a_stale_precheck(self, monkeypatch):
        """Concurrent submits can all pass _check_queue_caps against the
        same stale depth; the cap is ENFORCED in the same locked section
        that appends. Simulate the race by disabling the pre-check."""
        sched = _parked()
        sched.max_queue = 1
        monkeypatch.setattr(sched, "_check_queue_caps",
                            lambda *a, **k: None)
        first = sched.submit(PROMPT, _gen(priority=1))
        # equal priority: the arrival itself sheds at append time
        with pytest.raises(QueueFullError):
            sched.submit(PROMPT, _gen(priority=1))
        assert list(sched._waiting) == [first]
        # higher priority: the append-time check still evicts in order
        arrival = sched.submit(PROMPT, _gen(priority=2))
        assert list(sched._waiting) == [arrival]
        assert isinstance(first.out.get_nowait(), QueueFullError)
        assert first.trace.status == "shed"


class TestPriorityPreemption:
    """A high-priority arrival with all slots busy evicts a strictly
    lower-priority victim; the victim resumes byte-identically."""

    def _victim_scenario(self, victim_gen, ref_gen=None):
        """batch_size=1: the victim owns the only slot, the arrival can
        only run by preempting it. Reference runs FIRST on the same
        engine (same compiled programs, same page geometry) — the claim
        is that the preemption round-trip changes nothing."""
        eng = _make(batch_size=1, page_size=16, num_pages=64)
        sched = eng.scheduler
        sched.prefill_chunk = 8  # resumed prefill uses the chunked path
        ref = list(sched.stream(PROMPT, ref_gen or victim_gen))

        p0 = _counter("scheduler.priority_preemptions")
        victim = sched.submit(PROMPT, victim_gen)
        out: list = []

        def drain_victim():
            out.extend(sched.drain(victim))

        t = threading.Thread(target=drain_victim)
        t.start()
        # the victim must survive a dispatch (its admission shield) and
        # have tokens in flight before the high-priority arrival lands
        deadline = time.monotonic() + 60
        while len(victim.generated) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(victim.generated) >= 3, "victim never started decoding"
        hi = list(sched.stream(PROMPT, _gen(priority=2, max_new_tokens=8)))
        t.join(timeout=300)
        assert len(hi) == 8
        assert _counter("scheduler.priority_preemptions") > p0
        assert out == ref, "victim diverged across the preemption"
        phases = [p for p, _ in victim.trace.events]
        assert "preempted" in phases and "resumed" in phases
        assert phases.index("resumed") > phases.index("preempted")

    @pytest.mark.slow  # pipeline `tenancy_tests` stage runs these for
    # real; tier-1's budget keeps only the queue-order pins above
    def test_victim_resumes_byte_identical_greedy(self):
        self._victim_scenario(_gen(max_new_tokens=48, priority=0))

    @pytest.mark.slow
    def test_victim_resumes_byte_identical_seeded(self):
        self._victim_scenario(
            _gen(max_new_tokens=48, priority=0,
                 temperature=1.0, top_k=40, seed=107),
        )

    def test_equal_priority_never_slot_preempts(self):
        """Uniform-priority traffic keeps the legacy wait-for-a-slot
        behavior: _pick_victim with max_priority below every running
        class finds nothing."""
        from fei_tpu.engine.scheduler import _Seq

        eng = _make()
        sched = eng.scheduler
        a = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None, stops=set(),
                 budget=16, priority=1)
        a.generated = [1] * 4
        sched._slots[0] = a
        assert sched._pick_victim(exclude=None, max_priority=0) is None
        assert sched._pick_victim(exclude=None, max_priority=1) is a

    def test_victim_order_is_priority_then_progress(self):
        from fei_tpu.engine.scheduler import _Seq

        eng = _make(batch_size=3)
        sched = eng.scheduler
        low_far = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None,
                       stops=set(), budget=16, priority=0)
        low_far.generated = [1] * 14
        low_near = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None,
                        stops=set(), budget=16, priority=0)
        low_near.generated = [1] * 2
        mid = _Seq(prompt_ids=PROMPT, gen=_gen(), mask_fn=None,
                   stops=set(), budget=16, priority=1)
        mid.generated = [1]  # least progressed but higher class
        for i, s in enumerate([low_far, low_near, mid]):
            sched._slots[i] = s
        assert sched._pick_victim(exclude=None, max_priority=1) is low_near


class TestServerPlumbing:
    """tenant/priority/deadline ride the body or the X-FEI-* headers
    into GenerationConfig overrides (no engine needed)."""

    def test_body_fields(self):
        from fei_tpu.ui.server import _gen_overrides

        over = _gen_overrides(
            {"tenant": "gold", "priority": 2, "deadline_s": 5}, {}
        )
        assert over["tenant"] == "gold"
        assert over["priority"] == 2
        assert over["deadline_s"] == 5.0

    def test_headers_and_body_precedence(self):
        from fei_tpu.ui.server import _gen_overrides

        over = _gen_overrides({}, {"X-FEI-Tenant": "silver",
                                   "X-FEI-Priority": "1"})
        assert over["tenant"] == "silver" and over["priority"] == 1
        over = _gen_overrides({"tenant": "gold"},
                              {"x-fei-tenant": "silver"})
        assert over["tenant"] == "gold"  # body wins
        over = _gen_overrides({"priority": "soon"}, {})
        assert "priority" not in over  # junk drops, not 500s

    def test_propagated_deadline_folds_min(self):
        from fei_tpu.ui.server import _gen_overrides

        over = _gen_overrides({"deadline_s": 9},
                              {"X-FEI-Deadline-S": "2.5"})
        assert over["deadline_s"] == 2.5
        over = _gen_overrides({"deadline_s": 1},
                              {"X-FEI-Deadline-S": "30"})
        assert over["deadline_s"] == 1.0
        # an already-expired propagated budget clamps to an epsilon (0
        # would mean "no deadline") so the scheduler sheds it on arrival
        over = _gen_overrides({}, {"X-FEI-Deadline-S": "0"})
        assert over["deadline_s"] == pytest.approx(1e-3)

    def test_submit_resolves_and_sanitizes(self):
        sched = _parked()
        seq = sched.submit(
            PROMPT, _gen(tenant="team a!", priority=99)
        )
        assert seq.tenant == "team_a_"
        assert seq.priority == 9  # clamped ordinal class
        anon = sched.submit(PROMPT, _gen())
        assert anon.tenant == sched.tenants.default_tenant
