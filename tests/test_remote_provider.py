"""RemoteProvider without litellm: the dependency-free OpenAI-compatible
urllib client against a loopback stub (BASELINE config #1's client path).
The reference's transport is litellm HTTP dispatch
(fei/core/assistant.py:524-530); this pins the in-tree equivalent."""

from __future__ import annotations

import http.server
import json
import threading

import pytest

from fei_tpu.agent.providers import RemoteProvider
from fei_tpu.utils.errors import ProviderError


class _Stub(http.server.BaseHTTPRequestHandler):
    last_payload: dict = {}

    def do_POST(self):
        raw = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).last_payload = json.loads(raw)
        msg = {"role": "assistant", "content": "maildir names are immutable"}
        if type(self).last_payload.get("tools"):
            msg = {
                "role": "assistant", "content": None,
                "tool_calls": [{
                    "id": "call_1", "type": "function",
                    "function": {"name": "GlobTool",
                                 "arguments": '{"pattern": "*.py"}'},
                }],
            }
        body = json.dumps({
            "choices": [{"message": msg, "finish_reason": "stop"}],
            "usage": {"prompt_tokens": 5, "completion_tokens": 7},
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


@pytest.fixture()
def stub_base():
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Stub)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}/v1"
    server.shutdown()


class TestRemoteProviderUrllib:
    def test_plain_completion(self, stub_base):
        p = RemoteProvider("openai", model="stub", api_base=stub_base)
        resp = p.complete([{"role": "user", "content": "hi"}], system="sys")
        assert resp.content == "maildir names are immutable"
        assert resp.stop_reason == "stop"
        assert resp.usage["completion_tokens"] == 7
        sent = _Stub.last_payload
        assert sent["messages"][0] == {"role": "system", "content": "sys"}

    def test_tool_call_parsing(self, stub_base):
        p = RemoteProvider("openai", model="stub", api_base=stub_base)
        tools = [{"name": "GlobTool", "description": "find",
                  "input_schema": {"type": "object", "properties": {}}}]
        resp = p.complete([{"role": "user", "content": "find"}], tools=tools)
        assert resp.stop_reason == "tool_use"
        assert resp.tool_calls[0].name == "GlobTool"
        assert resp.tool_calls[0].arguments == {"pattern": "*.py"}
        assert _Stub.last_payload["tools"][0]["function"]["name"] == "GlobTool"

    def test_keyless_local_endpoint_allowed(self, stub_base, monkeypatch):
        for var in ("OPENAI_API_KEY", "LLM_API_KEY"):
            monkeypatch.delenv(var, raising=False)
        p = RemoteProvider("openai", model="stub", api_base=stub_base)
        assert p.api_key == "local"

    def test_no_litellm_no_base_raises(self, monkeypatch):
        monkeypatch.delenv("OPENAI_API_BASE", raising=False)
        try:
            import litellm  # noqa: F401

            pytest.skip("litellm installed; fallback path not reachable")
        except ImportError:
            pass
        with pytest.raises(ProviderError):
            RemoteProvider("openai", model="stub", api_key="k")
