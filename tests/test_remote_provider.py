"""RemoteProvider without litellm: the dependency-free OpenAI-compatible
urllib client against the shared loopback stub (BASELINE config #1's client
path — the same stub the bench's remote suite measures, so the protocols
cannot drift). Reference transport: fei/core/assistant.py:524-530."""

from __future__ import annotations

import http.server
import json
import threading

import pytest

from fei_tpu.agent.providers import RemoteProvider
from fei_tpu.utils.errors import AuthenticationError, ProviderError
from fei_tpu.utils.openai_stub import serve_openai_stub


def _tool_responder(payload: dict):
    usage = {"prompt_tokens": 5, "completion_tokens": 7, "total_tokens": 12}
    if payload.get("tools"):
        return (
            {"role": "assistant", "content": None,
             "tool_calls": [{
                 "id": "call_1", "type": "function",
                 "function": {"name": "GlobTool",
                              "arguments": '{"pattern": "*.py"}'},
             }]},
            usage,
        )
    return {"role": "assistant", "content": "maildir names are immutable"}, usage


@pytest.fixture()
def stub():
    server, base = serve_openai_stub(responder=_tool_responder)
    yield server, base
    server.shutdown()


class TestRemoteProviderUrllib:
    def test_plain_completion(self, stub):
        server, base = stub
        p = RemoteProvider("openai", model="stub", api_base=base)
        resp = p.complete([{"role": "user", "content": "hi"}], system="sys")
        assert resp.content == "maildir names are immutable"
        assert resp.stop_reason == "stop"
        assert resp.usage["completion_tokens"] == 7
        assert server.last_payload["messages"][0] == {
            "role": "system", "content": "sys"
        }

    def test_tool_call_parsing(self, stub):
        server, base = stub
        p = RemoteProvider("openai", model="stub", api_base=base)
        tools = [{"name": "GlobTool", "description": "find",
                  "input_schema": {"type": "object", "properties": {}}}]
        resp = p.complete([{"role": "user", "content": "find"}], tools=tools)
        assert resp.stop_reason == "tool_use"
        assert resp.tool_calls[0].name == "GlobTool"
        assert resp.tool_calls[0].arguments == {"pattern": "*.py"}
        sent = server.last_payload
        assert sent["tools"][0]["function"]["name"] == "GlobTool"

    def test_keyless_loopback_endpoint_allowed(self, stub, monkeypatch):
        _, base = stub
        for var in ("OPENAI_API_KEY", "LLM_API_KEY"):
            monkeypatch.delenv(var, raising=False)
        p = RemoteProvider("openai", model="stub", api_base=base)
        assert p.api_key == "local"

    def test_keyless_remote_endpoint_still_raises(self, monkeypatch):
        for var in ("OPENAI_API_KEY", "LLM_API_KEY"):
            monkeypatch.delenv(var, raising=False)
        with pytest.raises(AuthenticationError):
            RemoteProvider(
                "openai", model="m", api_base="https://api.example.com/v1"
            )

    def test_error_shaped_200_surfaces_as_provider_error(self):
        class ErrStub(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                body = json.dumps(
                    {"error": {"message": "model overloaded"}}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), ErrStub)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}/v1"
        p = RemoteProvider("openai", model="stub", api_base=base)
        with pytest.raises(ProviderError, match="model overloaded"):
            p.complete([{"role": "user", "content": "hi"}])
        server.shutdown()

    def test_no_litellm_no_base_raises(self, monkeypatch):
        monkeypatch.delenv("OPENAI_API_BASE", raising=False)
        try:
            import litellm  # noqa: F401

            pytest.skip("litellm installed; fallback path not reachable")
        except ImportError:
            pass
        with pytest.raises(ProviderError):
            RemoteProvider("openai", model="stub", api_key="k")
