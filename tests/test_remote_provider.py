"""RemoteProvider without litellm: the dependency-free OpenAI-compatible
urllib client against the shared loopback stub (BASELINE config #1's client
path — the same stub the bench's remote suite measures, so the protocols
cannot drift). Reference transport: fei/core/assistant.py:524-530."""

from __future__ import annotations

import http.server
import json
import threading

import pytest

from fei_tpu.agent.providers import RemoteProvider
from fei_tpu.engine.faults import FAULTS
from fei_tpu.utils.errors import (
    AuthenticationError,
    ProviderError,
    RateLimitError,
)
from fei_tpu.utils.metrics import METRICS
from fei_tpu.utils.openai_stub import serve_openai_stub


def _tool_responder(payload: dict):
    usage = {"prompt_tokens": 5, "completion_tokens": 7, "total_tokens": 12}
    if payload.get("tools"):
        return (
            {"role": "assistant", "content": None,
             "tool_calls": [{
                 "id": "call_1", "type": "function",
                 "function": {"name": "GlobTool",
                              "arguments": '{"pattern": "*.py"}'},
             }]},
            usage,
        )
    return {"role": "assistant", "content": "maildir names are immutable"}, usage


@pytest.fixture()
def stub():
    server, base = serve_openai_stub(responder=_tool_responder)
    yield server, base
    server.shutdown()


class TestRemoteProviderUrllib:
    def test_plain_completion(self, stub):
        server, base = stub
        p = RemoteProvider("openai", model="stub", api_base=base)
        resp = p.complete([{"role": "user", "content": "hi"}], system="sys")
        assert resp.content == "maildir names are immutable"
        assert resp.stop_reason == "stop"
        assert resp.usage["completion_tokens"] == 7
        assert server.last_payload["messages"][0] == {
            "role": "system", "content": "sys"
        }

    def test_tool_call_parsing(self, stub):
        server, base = stub
        p = RemoteProvider("openai", model="stub", api_base=base)
        tools = [{"name": "GlobTool", "description": "find",
                  "input_schema": {"type": "object", "properties": {}}}]
        resp = p.complete([{"role": "user", "content": "find"}], tools=tools)
        assert resp.stop_reason == "tool_use"
        assert resp.tool_calls[0].name == "GlobTool"
        assert resp.tool_calls[0].arguments == {"pattern": "*.py"}
        sent = server.last_payload
        assert sent["tools"][0]["function"]["name"] == "GlobTool"

    def test_keyless_loopback_endpoint_allowed(self, stub, monkeypatch):
        _, base = stub
        for var in ("OPENAI_API_KEY", "LLM_API_KEY"):
            monkeypatch.delenv(var, raising=False)
        p = RemoteProvider("openai", model="stub", api_base=base)
        assert p.api_key == "local"

    def test_keyless_remote_endpoint_still_raises(self, monkeypatch):
        for var in ("OPENAI_API_KEY", "LLM_API_KEY"):
            monkeypatch.delenv(var, raising=False)
        with pytest.raises(AuthenticationError):
            RemoteProvider(
                "openai", model="m", api_base="https://api.example.com/v1"
            )

    def test_error_shaped_200_surfaces_as_provider_error(self):
        class ErrStub(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                body = json.dumps(
                    {"error": {"message": "model overloaded"}}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), ErrStub)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}/v1"
        p = RemoteProvider("openai", model="stub", api_base=base)
        with pytest.raises(ProviderError, match="model overloaded"):
            p.complete([{"role": "user", "content": "hi"}])
        server.shutdown()

    def test_injected_conn_fault_is_retried(self, stub, monkeypatch):
        """The provider.http fault point sits inside the retry loop, so
        an injected transport fault exercises exactly the recovery path
        a flaky network would."""
        monkeypatch.setenv("FEI_TPU_PROVIDER_BACKOFF_S", "0.01")
        _, base = stub
        p = RemoteProvider("openai", model="stub", api_base=base)
        FAULTS.arm("provider.http", "conn", count=1)
        try:
            resp = p.complete([{"role": "user", "content": "hi"}])
            fired = FAULTS.fired("provider.http")
        finally:
            FAULTS.disarm()
        assert resp.content == "maildir names are immutable"
        assert fired == 1

    def test_no_litellm_no_base_raises(self, monkeypatch):
        monkeypatch.delenv("OPENAI_API_BASE", raising=False)
        try:
            import litellm  # noqa: F401

            pytest.skip("litellm installed; fallback path not reachable")
        except ImportError:
            pass
        with pytest.raises(ProviderError):
            RemoteProvider("openai", model="stub", api_key="k")


def _flaky_server(codes: list[int], retry_after: str | None = None):
    """Loopback endpoint failing with ``codes`` in order, then succeeding.

    Returns (server, api_base, state) where state["calls"] counts POSTs."""
    state = {"calls": 0}

    class Flaky(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            i, state["calls"] = state["calls"], state["calls"] + 1
            if i < len(codes):
                self.send_response(codes[i])
                if retry_after is not None:
                    self.send_header("Retry-After", retry_after)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            body = json.dumps({
                "choices": [{"message": {"role": "assistant",
                                         "content": "recovered"}}],
                "usage": {"prompt_tokens": 1, "completion_tokens": 1},
            }).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Flaky)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}/v1", state


def _retries() -> float:
    return METRICS.snapshot()["counters"].get("provider.retries", 0)


class TestRetryPolicy:
    """Bounded exponential-backoff retries around the urllib transport
    (PR 4 satellite): transient 5xx/429/connection failures recover,
    client errors fail fast, Retry-After is honored."""

    @pytest.fixture(autouse=True)
    def _fast_backoff(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_PROVIDER_BACKOFF_S", "0.01")

    def test_transient_503s_recover(self):
        server, base, state = _flaky_server([503, 503])
        before = _retries()
        p = RemoteProvider("openai", model="stub", api_base=base)
        resp = p.complete([{"role": "user", "content": "hi"}])
        server.shutdown()
        assert resp.content == "recovered"
        assert state["calls"] == 3
        assert _retries() == before + 2

    def test_429_honors_retry_after(self):
        server, base, state = _flaky_server([429], retry_after="0")
        p = RemoteProvider("openai", model="stub", api_base=base)
        resp = p.complete([{"role": "user", "content": "hi"}])
        server.shutdown()
        assert resp.content == "recovered"
        assert state["calls"] == 2

    def test_429_exhaustion_is_rate_limit_error(self, monkeypatch):
        monkeypatch.setenv("FEI_TPU_PROVIDER_RETRIES", "1")
        server, base, state = _flaky_server([429] * 5, retry_after="0")
        p = RemoteProvider("openai", model="stub", api_base=base)
        with pytest.raises(RateLimitError):
            p.complete([{"role": "user", "content": "hi"}])
        server.shutdown()
        assert state["calls"] == 2  # 1 attempt + 1 retry, bounded

    def test_client_error_fails_fast(self):
        server, base, state = _flaky_server([400])
        before = _retries()
        p = RemoteProvider("openai", model="stub", api_base=base)
        with pytest.raises(ProviderError):
            p.complete([{"role": "user", "content": "hi"}])
        server.shutdown()
        assert state["calls"] == 1  # 4xx is the caller's bug: never retried
        assert _retries() == before
