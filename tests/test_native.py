"""Native C++ scan engine: build, correctness vs the Python scan path, and
the fallback contract (regex patterns and disabled-native return None)."""

import os

import pytest

from fei_tpu.native import scan
from fei_tpu.native.build import lib_path
from fei_tpu.tools.code import GrepTool


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    root = tmp_path_factory.mktemp("corpus")
    (root / "a.py").write_text(
        "def alpha():\n    return beta()\n\ndef beta():\n    return 1\n"
    )
    (root / "b.txt").write_text("beta appears here\nand beta again\n")
    (root / "sub").mkdir()
    (root / "sub" / "c.py").write_text("gamma = beta\n")
    (root / "bin.dat").write_bytes(b"\x00\x01beta\x00")
    return root


class TestBuild:
    def test_builds_and_caches(self):
        p1 = lib_path()
        if p1 is None:
            pytest.skip("no C++ compiler in environment")
        assert os.path.exists(p1)
        assert lib_path() == p1  # cache hit, same artifact


class TestGrepFiles:
    def test_matches_python_scan(self, corpus):
        files = [
            str(corpus / "a.py"), str(corpus / "b.txt"),
            str(corpus / "sub" / "c.py"), str(corpus / "bin.dat"),
        ]
        got = scan.grep_files(files, "beta", max_results=100)
        if got is None:
            pytest.skip("native scan unavailable")
        want = GrepTool().search("beta", path=str(corpus))
        got_set = {(os.path.basename(f), n, t.strip()) for f, n, t in got}
        want_set = {
            (os.path.basename(m.file), m.line_number, m.line.strip())
            for m in want
        }
        assert got_set == want_set
        # binary file skipped
        assert not any("bin.dat" in f for f, _, _ in got)

    def test_one_match_per_line(self, corpus):
        got = scan.grep_files([str(corpus / "b.txt")], "beta", 100)
        if got is None:
            pytest.skip("native scan unavailable")
        assert [n for _, n, _ in sorted(got)] == [1, 2]

    def test_max_results_respected(self, corpus):
        files = [str(corpus / "a.py"), str(corpus / "b.txt")]
        got = scan.grep_files(files, "beta", max_results=2)
        if got is None:
            pytest.skip("native scan unavailable")
        assert len(got) == 2

    def test_regex_returns_none(self, corpus):
        assert scan.grep_files([str(corpus / "a.py")], r"beta\(", 10) is None
        assert scan.grep_files([str(corpus / "a.py")], "be.a", 10) is None

    def test_disabled_returns_none(self, corpus, monkeypatch):
        monkeypatch.setenv("FEI_TPU_NATIVE", "0")
        monkeypatch.setattr(scan, "_lib", None)
        try:
            assert scan.grep_files([str(corpus / "a.py")], "beta", 10) is None
        finally:
            scan._lib = None  # let other tests reload


class TestGrepToolIntegration:
    def test_fixed_string_search_through_tool(self, corpus):
        """GrepTool results are identical whether or not the native engine
        kicks in (it self-selects for fixed strings)."""
        matches = GrepTool().search("beta", path=str(corpus))
        assert {os.path.basename(m.file) for m in matches} == {
            "a.py", "b.txt", "c.py"
        }


class TestNulAfterSniff:
    def test_nul_in_line_past_sniff_window(self, tmp_path):
        """A NUL beyond the 4 KiB sniff must not truncate or over-read the
        matched line (POINTER(c_char) binding regression)."""
        clean = "x" * 5000 + "\n"
        payload = "beta before\x00after\n"
        p = tmp_path / "late_nul.txt"
        p.write_bytes(clean.encode() + payload.encode())
        got = scan.grep_files([str(p)], "beta", 10)
        if got is None:
            pytest.skip("native scan unavailable")
        assert len(got) == 1
        _, line_no, text = got[0]
        assert line_no == 2
        assert text == "beta before\x00after"


class TestOrderingParity:
    def test_grep_tool_native_sorted_like_python(self, tmp_path):
        import time as _time

        old = tmp_path / "old.py"
        new = tmp_path / "new.py"
        old.write_text("needle one\n")
        _time.sleep(0.05)
        new.write_text("needle two\n")
        matches = GrepTool().search("needle", path=str(tmp_path))
        # newest file first — the documented ordering contract
        assert [os.path.basename(m.file) for m in matches] == ["new.py", "old.py"]
