"""TUI smoke tests (reference test_textual.py:1-68): construct the app,
drive message/command dispatch headlessly, verify graceful degradation when
the memdir server is unavailable."""

import asyncio

import pytest

from fei_tpu.ui.textual_chat import (
    ChatMessage,
    FeiChatApp,
    MemCommandCompleter,
    MEM_COMMANDS,
)


class FakeHandlers:
    """Stands in for MemoryToolHandlers without a server."""

    def memory_list(self, folder="", status="new", with_content=False):
        return {"memories": [{"id": "m1", "headers": {"Subject": "hello"}}], "count": 1}

    def memory_search(self, query, folder=None, with_content=False, limit=None):
        return {"results": [{"id": "m1", "q": query}]}

    def memory_search_by_tag(self, tag, limit=None):
        return {"results": [{"id": "m1", "tag": tag}]}

    def memory_view(self, memory_id, folder=None):
        return {"id": memory_id, "content": "body"}

    def memory_create(self, content, subject=None, tags=None, folder="", flags=""):
        self.last = dict(content=content, subject=subject, tags=tags)
        return {"created": "new-id"}

    def memory_delete(self, memory_id, hard=False):
        return {"deleted": True, "memory_id": memory_id, "hard": hard}

    def memory_server_status(self):
        return {"running": False}

    def memory_server_start(self):
        return {"running": True}

    def memory_server_stop(self):
        return {"stopped": True}


class EchoAssistant:
    on_text = None

    def __init__(self):
        self.resets = 0

    async def chat(self, message, system_prompt=None):
        if self.on_text:
            self.on_text("echo: ")
            self.on_text(message)
        return f"echo: {message}"

    def reset(self):
        self.resets += 1


@pytest.fixture()
def app():
    return FeiChatApp(assistant=EchoAssistant(), memory_handlers=FakeHandlers())


class TestChatMessage:
    def test_render_ansi_caches(self):
        m = ChatMessage("assistant", "**hi**")
        first = m.render_ansi(60)
        assert "hi" in first
        assert m.render_ansi(60) is first  # cache hit

    def test_render_never_raises(self):
        m = ChatMessage("weird-role", "x" * 10)
        assert "x" in m.render_ansi(5)


class TestMemCommands:
    def test_help(self, app):
        out = app.handle_memory_command("help")
        for sub in MEM_COMMANDS:
            assert sub in out

    def test_list(self, app):
        out = app.handle_memory_command("list")
        assert "1 memories" in out and "m1" in out

    def test_search_and_tag(self, app):
        assert "m1" in app.handle_memory_command("search urgent stuff")
        assert "m1" in app.handle_memory_command("tag python")

    def test_save_parses_tags_and_subject(self, app):
        out = app.handle_memory_command("save remember this #a,b subject=Note")
        assert "new-id" in out
        assert app.memory.last == dict(
            content="remember this", subject="Note", tags="a,b"
        )

    def test_view_delete_server(self, app):
        assert "body" in app.handle_memory_command("view m1")
        assert "deleted" in app.handle_memory_command("delete m1 --hard")
        assert "running" in app.handle_memory_command("server status")

    def test_unknown_subcommand(self, app):
        assert "unknown /mem subcommand" in app.handle_memory_command("frobnicate")

    def test_graceful_when_server_down(self):
        """Real handlers with an unreachable server must render an error,
        not raise (reference test_textual.py:34-47)."""
        from fei_tpu.tools.memdir_connector import MemdirConnector
        from fei_tpu.tools.memory_tools import MemoryToolHandlers

        conn = MemdirConnector(
            server_url="http://127.0.0.1:1", api_key="x", auto_start=False
        )
        app = FeiChatApp(memory_handlers=MemoryToolHandlers(conn))
        out = app.handle_memory_command("list")
        assert "error" in out.lower()


class TestDispatch:
    def test_user_message_streams(self, app):
        asyncio.run(app.handle_user_message("hello tui"))
        roles = [m.role for m in app.messages]
        assert roles[-2:] == ["user", "assistant"]
        assert app.messages[-1].content == "echo: hello tui"
        assert not app.messages[-1].live

    def test_clear_resets_assistant(self, app):
        asyncio.run(app.handle_user_message("hi"))
        asyncio.run(app.handle_user_message("/clear"))
        assert len(app.messages) == 1
        assert app.assistant.resets == 1

    def test_mem_dispatch(self, app):
        asyncio.run(app.handle_user_message("/mem list"))
        assert app.messages[-1].role == "memory"

    def test_metrics_command(self, app):
        from fei_tpu.utils.metrics import METRICS

        METRICS.incr("tool.calls")
        asyncio.run(app.handle_user_message("/metrics"))
        msg = app.messages[-1]
        assert msg.role == "system"
        assert "tool.calls" in msg.content
        assert "/metrics" in app._help_text()

    def test_completer(self):
        from prompt_toolkit.document import Document

        comp = MemCommandCompleter()
        got = [
            c.text for c in comp.get_completions(Document("/mem se"), None)
        ]
        assert "search" in got and "server" in got
        got = [c.text for c in comp.get_completions(Document("/m"), None)]
        assert "/mem" in got
        got = [c.text for c in comp.get_completions(Document("/me"), None)]
        assert "/metrics" in got and "/mem" in got

    def test_build_app_layout(self, app):
        built = app._build_app()
        assert built is not None and app._app is built
