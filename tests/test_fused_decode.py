"""Fused chunked free-phase decode (engine/fused_decode.py).

Every test checks the chunked path against the per-token reference loop
that survives in-tree behind ``gen.chunk=1`` — token-for-token parity is
the contract, including the awkward mid-chunk cases: a stop token landing
inside a chunk, a grammar trigger completing inside a chunk (cache
rollback → constrained-phase re-entry state must match the reference), a
trigger whose characters SPLIT across a chunk boundary, and a budget that
exhausts mid-chunk (no KV write past max_seq_len). The dispatch-count
acceptance bound (≤ ceil(B/chunk)+1 dispatches for a B-token free run) is
pinned via the ``engine.decode_dispatches`` counter.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.fused_decode import (
    DEFAULT_CHUNK,
    ChunkDecoder,
    resolve_chunk,
)
from fei_tpu.engine.grammar import char_walk, compile_agent_tool_grammar
from fei_tpu.utils.metrics import METRICS

TOOLS = [
    {
        "name": "Glob",
        "description": "find files",
        "input_schema": {
            "type": "object",
            "properties": {"pattern": {"type": "string"}},
            "required": ["pattern"],
        },
    },
    {
        "name": "Shell",
        "description": "run a command",
        "input_schema": {
            "type": "object",
            "properties": {"command": {"type": "string"}},
            "required": ["command"],
        },
    },
]


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine.from_config("tiny", dtype=jnp.float32, max_seq_len=128)


@pytest.fixture(scope="module")
def grammar(engine):
    return compile_agent_tool_grammar(TOOLS, engine.tokenizer)


def _ref_tokens(engine, prompt, n, **gen_kw):
    gen = GenerationConfig(max_new_tokens=n, ignore_eos=True, chunk=1, **gen_kw)
    return list(engine.generate_stream(prompt, gen))


def _clean_char(engine, tok) -> str | None:
    """The token's text iff it is one printable char that round-trips."""
    text = engine.tokenizer.decode([tok])
    if (
        len(text) == 1
        and text.isprintable()
        and engine.tokenizer.encode(text) == [tok]
    ):
        return text
    return None


def test_resolve_chunk_precedence(monkeypatch):
    monkeypatch.delenv("FEI_TPU_DECODE_CHUNK", raising=False)
    assert resolve_chunk() == DEFAULT_CHUNK
    monkeypatch.setenv("FEI_TPU_DECODE_CHUNK", "24")
    assert resolve_chunk() == 24
    assert resolve_chunk(4) == 4  # gen.chunk wins over the env
    monkeypatch.setenv("FEI_TPU_DECODE_CHUNK", "garbage")
    assert resolve_chunk() == DEFAULT_CHUNK


@pytest.mark.parametrize("chunk", [2, 3, 8, 16])
def test_greedy_parity_across_chunks(engine, chunk):
    prompt = engine.tokenizer.encode("fused decode", add_bos=True)
    ref = _ref_tokens(engine, prompt, 33)
    gen = GenerationConfig(max_new_tokens=33, ignore_eos=True, chunk=chunk)
    assert list(engine.generate_stream(prompt, gen)) == ref


@pytest.mark.parametrize("chunk", [3, 8])
def test_seeded_sampling_parity(engine, chunk):
    """rng split discipline matches the reference: one split per live step,
    none after a stop — so seeded streams are bit-identical."""
    prompt = engine.tokenizer.encode("sample parity", add_bos=True)
    kw = dict(temperature=0.9, top_k=40, seed=7)
    ref = _ref_tokens(engine, prompt, 25, **kw)
    gen = GenerationConfig(
        max_new_tokens=25, ignore_eos=True, chunk=chunk, **kw
    )
    assert list(engine.generate_stream(prompt, gen)) == ref


@pytest.mark.parametrize("stop_idx", [1, 4, 9])
def test_stop_token_mid_chunk_parity(engine, stop_idx):
    prompt = engine.tokenizer.encode("stops", add_bos=True)
    full = _ref_tokens(engine, prompt, 16)
    stop_at = full[stop_idx]
    for chunk in (1, 8):
        gen = GenerationConfig(
            max_new_tokens=16, stop_token_ids=(stop_at,), chunk=chunk
        )
        got = list(engine.generate_stream(prompt, gen))
        expect = []
        stops = {stop_at} | set(engine.tokenizer.stop_token_ids)
        for t in full:
            if t in stops:
                break
            expect.append(t)
        assert got == expect, f"chunk={chunk}"


def test_fused_fn_early_exit_stops_kv_writes(engine):
    """Device-level contract: once the stop is sampled, later scan
    iterations are no-ops — cache.length freezes at the tokens actually
    fed, and the carry token repeats through the ys."""
    prompt = engine.tokenizer.encode("device stop", add_bos=True)
    full = _ref_tokens(engine, prompt, 12)
    j = 3  # the fused chunk samples full[1:] — stop lands at scan step j
    stop_at = full[1 + j]
    gen = GenerationConfig(max_new_tokens=12, stop_token_ids=(stop_at,))
    tok, cache, rng = engine._prefill_sample(prompt, gen)
    assert int(tok[0]) == full[0]
    n = 10
    fused = engine._free_fused_fn(gen, n)
    done = jnp.zeros((1,), dtype=jnp.bool_)
    stop_ids = jnp.asarray([stop_at], dtype=jnp.int32)
    toks, cache, _, _, done, _ = fused(
        engine.params, cache, tok.reshape(1, 1), rng, done, stop_ids
    )
    host = np.asarray(toks)[0].tolist()
    assert host[:j + 1] == full[1:1 + j + 1]
    assert host[j] == stop_at
    # dead iterations recycle the carry token; nothing new is sampled
    assert all(t == stop_at for t in host[j:])
    assert bool(np.asarray(done)[0])
    # KV writes froze at the step that SAMPLED the stop: the stop token
    # itself was never fed, and no slot past it was written
    assert int(np.asarray(cache.length)[0]) == len(prompt) + j + 1


def test_dispatch_count_bounded(engine):
    """Acceptance: a B-token free-phase run costs ≤ ceil(B/chunk)+1
    dispatches (the +1 allows the pipelined speculative chunk)."""
    prompt = engine.tokenizer.encode("count dispatches", add_bos=True)
    B, chunk = 48, 8
    gen = GenerationConfig(max_new_tokens=B, ignore_eos=True, chunk=chunk)
    before = METRICS.snapshot()["counters"].get("engine.decode_dispatches", 0)
    out = list(engine.generate_stream(prompt, gen))
    after = METRICS.snapshot()["counters"].get("engine.decode_dispatches", 0)
    assert len(out) == B
    assert after - before <= math.ceil(B / chunk) + 1


def test_budget_exhausted_mid_chunk_no_kv_overflow(engine):
    """A chunk that would run past the cache end is clamped: the stream
    stops at the budget and the cache never writes past max_seq_len."""
    prompt = [5] * 100  # budget = 128 - 100 = 28; chunk 8 doesn't divide 27
    gen = GenerationConfig(max_new_tokens=64, ignore_eos=True, chunk=8)
    out = list(engine.generate_stream(prompt, gen))
    assert len(out) == engine.max_seq_len - len(prompt)
    # drive the decoder directly to inspect the final device-side length
    tok, cache, rng = engine._prefill_sample(prompt, gen)
    dec = ChunkDecoder(
        engine, gen, cache, tok, rng,
        fed=len(prompt), chunk=8, want=27, stops=(),
    )
    toks = [t for ch in dec.chunks() for t in ch.tokens]
    assert len(toks) == 27  # 8 + 8 + 8 + 3: the tail chunk clamped
    assert int(np.asarray(dec._cache.length)[0]) <= engine.max_seq_len
    assert toks == out[1:]


def _free_stream(engine, prompt, n):
    """Greedy unconstrained tokens, the raw material for trigger hunting."""
    return _ref_tokens(engine, prompt, n)


def _find_trigger_at(engine, idx, lookahead=8):
    """(prompt, trigger, stream): greedy ``stream`` whose token at
    ``idx`` is one clean char that does not occur earlier in the decoded
    stream — so TriggerScanner completes exactly at stream index ``idx``."""
    for base in range(5, 90, 3):
        prompt = [base, base + 1, base + 2, base + 3]
        stream = _free_stream(engine, prompt, lookahead)
        if len(stream) <= idx:
            continue
        ch = _clean_char(engine, stream[idx])
        if ch is None:
            continue
        if ch in engine.tokenizer.decode(stream[:idx]):
            continue  # would complete earlier
        return prompt, ch, stream
    pytest.skip("no prompt yields a clean trigger at the wanted index")


def test_trigger_mid_chunk_rollback_matches_reference(engine, grammar):
    """Trigger completes at stream index 2 — the middle of the first
    4-token chunk. The chunked path must roll the cache back and re-enter
    the constrained phase with EXACTLY the reference's state: full-stream
    token parity against gen.chunk=1 proves it."""
    prompt, trigger, _ = _find_trigger_at(engine, 2)
    ref = list(engine.generate_stream_toolcalls(
        prompt,
        GenerationConfig(max_new_tokens=64, ignore_eos=True, chunk=1),
        grammar=grammar, trigger=trigger,
    ))
    got = list(engine.generate_stream_toolcalls(
        prompt,
        GenerationConfig(max_new_tokens=64, ignore_eos=True, chunk=4),
        grammar=grammar, trigger=trigger,
    ))
    assert got == ref
    text = engine.tokenizer.decode(got)
    assert trigger in text
    if text.endswith("</tool_call>"):
        payload = text.split(trigger, 1)[1][: -len("</tool_call>")]
        assert char_walk(grammar, payload) == grammar.accept


def test_trigger_split_across_chunk_boundary(engine, grammar):
    """A two-char trigger whose first char is the LAST token of chunk 1
    and second char the FIRST token of chunk 2 (chunk=3: chunks are
    s1..s3 / s4..s6). The TriggerScanner state must carry across the
    chunk boundary and the rollback must land on the exact token."""
    for base in range(5, 90, 3):
        prompt = [base, base + 1, base + 2, base + 3]
        stream = _free_stream(engine, prompt, 8)
        if len(stream) < 5:
            continue
        c1 = _clean_char(engine, stream[3])
        c2 = _clean_char(engine, stream[4])
        if c1 is None or c2 is None:
            continue
        trigger = c1 + c2
        if trigger in engine.tokenizer.decode(stream[:4]):
            continue  # would complete before the boundary
        break
    else:
        pytest.skip("no prompt yields a boundary-splitting trigger")
    ref = list(engine.generate_stream_toolcalls(
        prompt,
        GenerationConfig(max_new_tokens=64, ignore_eos=True, chunk=1),
        grammar=grammar, trigger=trigger,
    ))
    got = list(engine.generate_stream_toolcalls(
        prompt,
        GenerationConfig(max_new_tokens=64, ignore_eos=True, chunk=3),
        grammar=grammar, trigger=trigger,
    ))
    assert got == ref
    assert trigger in engine.tokenizer.decode(got)


def test_generate_fused_matches_stream(engine):
    prompt = engine.tokenizer.encode("fused result", add_bos=True)
    ref = _ref_tokens(engine, prompt, 24)
    res = engine.generate_fused(
        prompt,
        GenerationConfig(max_new_tokens=24, ignore_eos=True),
        chunk=7,
    )
    assert res.token_ids == ref
