"""Golden-token parity against HuggingFace transformers (installed in-image).

VERDICT r1 weak-spot #7: nothing previously compared our stacked-pytree
forward against a reference implementation, so a silent RoPE/GQA layout bug
could pass every hermetic test. Here a tiny random HF LlamaForCausalLM is
save_pretrained'd, loaded through engine/weights.load_checkpoint, and the
logits must agree to fp32 tolerance; the chat-template ids must be identical
between our HFTokenizer wrapper and transformers' own apply_chat_template.
"""

import jax.numpy as jnp
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from fei_tpu.engine.weights import load_checkpoint
from fei_tpu.models.configs import get_model_config
from fei_tpu.models.llama import KVCache, forward

pytestmark = pytest.mark.slow  # fast lane: -m 'not slow' (docs/TESTING.md)


def _tiny_hf_llama(tmp_path, tie_embeddings=False, attention_bias=False):
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,  # GQA: the layout bug this test exists for
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=tie_embeddings,
        attention_bias=attention_bias,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval()
    if attention_bias:
        # transformers' _init_weights zeroes Linear biases; randomize so
        # parity exercises the q/k/v AND o bias math
        with torch.no_grad():
            for layer in model.model.layers:
                for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
                    getattr(layer.self_attn, proj).bias.normal_(0, 0.5)
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    return model, cfg


class TestHFLogitParity:
    @pytest.mark.parametrize("tie", [False, True])
    def test_logits_match(self, tmp_path, tie):
        model, hf_cfg = _tiny_hf_llama(tmp_path, tie_embeddings=tie)

        ids = np.array([[1, 7, 42, 99, 3, 250, 17, 5]], dtype=np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).logits.float().numpy()

        cfg = get_model_config("tiny")  # every field overridden by config.json
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        assert cfg2.num_kv_heads == 2 and cfg2.tie_embeddings == tie

        cache = KVCache.create(cfg2, 1, ids.shape[1], jnp.float32)
        got, _ = forward(params, cfg2, jnp.asarray(ids, jnp.int32), cache)

        np.testing.assert_allclose(np.asarray(got)[0], want[0], atol=1e-3)

    def test_attention_bias_logits_match(self, tmp_path):
        """HF Llama attention_bias=true biases q/k/v AND o_proj — all four
        must load and apply (cfg.attn_bias + cfg.o_bias)."""
        model, _ = _tiny_hf_llama(tmp_path, attention_bias=True)

        ids = np.array([[1, 8, 44, 98, 2, 249, 16, 4]], dtype=np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).logits.float().numpy()

        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        assert cfg2.attn_bias and cfg2.o_bias
        assert {"bq", "bk", "bv", "bo"} <= set(params["layers"])
        assert float(np.abs(np.asarray(params["layers"]["bo"])).max()) > 0

        cache = KVCache.create(cfg2, 1, ids.shape[1], jnp.float32)
        got, _ = forward(params, cfg2, jnp.asarray(ids, jnp.int32), cache)

        np.testing.assert_allclose(np.asarray(got)[0], want[0], atol=1e-3)

    def test_decode_matches_prefill_split(self, tmp_path):
        """Prefill 5 tokens then decode 3 one-by-one == one 8-token prefill
        (exercises the cache write path against HF-derived weights)."""
        model, _ = _tiny_hf_llama(tmp_path)
        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)

        ids = jnp.array([[1, 7, 42, 99, 3, 250, 17, 5]], jnp.int32)
        cache_full = KVCache.create(cfg2, 1, 8, jnp.float32)
        want, _ = forward(params, cfg2, ids, cache_full)

        cache = KVCache.create(cfg2, 1, 8, jnp.float32)
        _, cache = forward(params, cfg2, ids[:, :5], cache)
        outs = []
        for t in range(5, 8):
            logits, cache = forward(params, cfg2, ids[:, t : t + 1], cache)
            outs.append(np.asarray(logits)[0, 0])
        np.testing.assert_allclose(
            np.stack(outs), np.asarray(want)[0, 5:], atol=1e-3
        )

    def test_int8_tracks_hf(self, tmp_path):
        """Quantized load stays within int8 error of the HF reference."""
        model, _ = _tiny_hf_llama(tmp_path)
        ids = np.array([[1, 7, 42, 99]], dtype=np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).logits.float().numpy()

        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(
            str(tmp_path), cfg, dtype=jnp.float32, quantize="int8"
        )
        cache = KVCache.create(cfg2, 1, 4, jnp.float32)
        got, _ = forward(params, cfg2, jnp.asarray(ids, jnp.int32), cache)
        rel = np.abs(np.asarray(got)[0] - want[0]).max() / np.abs(want[0]).max()
        assert rel < 0.05, f"int8 relative error vs HF: {rel}"


def _tiny_hf_qwen2(tmp_path, tie_embeddings=False):
    cfg = transformers.Qwen2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=tie_embeddings,
    )
    torch.manual_seed(1)
    model = transformers.Qwen2ForCausalLM(cfg).eval()
    # transformers' _init_weights zeroes Linear biases; randomize the qkv
    # biases so parity actually exercises the bias math
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_proj.bias.normal_(0, 0.5)
            layer.self_attn.k_proj.bias.normal_(0, 0.5)
            layer.self_attn.v_proj.bias.normal_(0, 0.5)
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    return model, cfg


class TestQwen2Parity:
    """Qwen2 family: same pre-norm GQA block plus qkv biases
    (cfg.attn_bias). Qwen2's HF config carries no attention_bias field —
    the loader keys off model_type — so this test locks both the bias
    math in qkv_proj and the config-merge path."""

    @pytest.mark.parametrize("tie", [False, True])
    def test_logits_match(self, tmp_path, tie):
        model, _ = _tiny_hf_qwen2(tmp_path, tie_embeddings=tie)

        ids = np.array([[1, 9, 43, 100, 4, 251, 18, 6]], dtype=np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).logits.float().numpy()

        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        assert cfg2.attn_bias and "bq" in params["layers"]
        # HF random init draws nonzero biases, so the bias path is live
        assert float(np.abs(np.asarray(params["layers"]["bq"])).max()) > 0

        cache = KVCache.create(cfg2, 1, ids.shape[1], jnp.float32)
        got, _ = forward(params, cfg2, jnp.asarray(ids, jnp.int32), cache)

        np.testing.assert_allclose(np.asarray(got)[0], want[0], atol=1e-3)

    def test_random_init_matches_layout(self, tmp_path):
        """init_params('tiny-bias') and the checkpoint loader must produce
        the same pytree structure (the jitted programs are shared)."""
        import jax

        from fei_tpu.models.llama import init_params

        _tiny_hf_qwen2(tmp_path)
        cfg = get_model_config("tiny")
        cfg2, loaded = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        inited = init_params(
            get_model_config("tiny-bias"), jax.random.PRNGKey(0),
            dtype=jnp.float32,
        )
        assert set(loaded["layers"]) == set(inited["layers"])


def _tiny_hf_mixtral(tmp_path):
    cfg = transformers.MixtralConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
    )
    torch.manual_seed(2)
    model = transformers.MixtralForCausalLM(cfg).eval()
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    return model, cfg


class TestMixtralParity:
    """MoE golden parity: router softmax/top-k normalization and expert
    dispatch/combine against transformers' MixtralForCausalLM — the
    routing math was previously pinned only against our own dense
    oracle, never an external reference."""

    @pytest.mark.parametrize("routed", ["0", "1"])
    def test_logits_match(self, tmp_path, routed, monkeypatch):
        monkeypatch.setenv("FEI_TPU_ROUTED_MOE", routed)
        model, _ = _tiny_hf_mixtral(tmp_path)

        ids = np.array([[1, 11, 47, 101, 5, 252, 19, 7]], dtype=np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).logits.float().numpy()

        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        assert cfg2.is_moe and cfg2.num_experts == 4

        cache = KVCache.create(cfg2, 1, ids.shape[1], jnp.float32)
        got, _ = forward(
            params, cfg2, jnp.asarray(ids, jnp.int32), cache,
            routed_moe=(routed == "1"),
        )

        np.testing.assert_allclose(np.asarray(got)[0], want[0], atol=2e-3)


class TestChatTemplateParity:
    def test_template_ids_identical(self, tmp_path):
        """Our HFTokenizer.apply_chat_template must produce byte-identical
        ids to transformers' own (same template, same specials)."""
        # zero egress: build a local tokenizer + template instead of a hub one
        pytest.importorskip("tokenizers")
        from tokenizers import Tokenizer, models, pre_tokenizers

        vocab = {chr(i) if 32 <= i < 127 else f"<0x{i:02X}>": i for i in range(256)}
        vocab["<|bos|>"] = 256
        vocab["<|eot|>"] = 257
        t = Tokenizer(models.WordLevel(vocab, unk_token="<0x00>"))
        t.pre_tokenizer = pre_tokenizers.Split("", "isolated")
        fast = transformers.PreTrainedTokenizerFast(
            tokenizer_object=t, bos_token="<|bos|>", eos_token="<|eot|>"
        )
        fast.chat_template = (
            "{{ bos_token }}{% for m in messages %}"
            "[{{ m.role }}]{{ m.content }}{{ eos_token }}{% endfor %}"
            "{% if add_generation_prompt %}[assistant]{% endif %}"
        )
        fast.save_pretrained(str(tmp_path))

        from fei_tpu.engine.tokenizer import HFTokenizer

        ours = HFTokenizer(str(tmp_path))
        msgs = [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi there"},
        ]
        want = fast.apply_chat_template(msgs, add_generation_prompt=True)
        got = ours.apply_chat_template(msgs, add_generation_prompt=True)
        assert list(got) == list(want)


def _tiny_hf_gemma(tmp_path):
    cfg = transformers.GemmaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,  # Gemma's head_dim is independent of hidden/heads
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        hidden_activation="gelu_pytorch_tanh",
    )
    torch.manual_seed(2)
    model = transformers.GemmaForCausalLM(cfg).eval()
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    return model, cfg


class TestGemmaParity:
    """Gemma family: RMSNorm multiplies by (1 + w) on zero-centered
    weights, embeddings scale by sqrt(hidden_size), the MLP is GeGLU
    (tanh-approx gelu), head_dim (2 * hidden/heads here) decouples from
    hidden/heads, and embeddings are always tied. Gemma's HF config has no
    flags for any of this — the loader keys off model_type — so this test
    locks the norm-offset, embed-scale, activation, and config-merge paths
    at once."""

    def test_logits_match(self, tmp_path):
        model, _ = _tiny_hf_gemma(tmp_path)

        ids = np.array([[2, 11, 45, 102, 5, 252, 19, 7]], dtype=np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).logits.float().numpy()

        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        assert cfg2.norm_offset and cfg2.embed_scale
        assert cfg2.hidden_act == "gelu" and cfg2.tie_embeddings
        assert cfg2.head_dim_ == 32 and cfg2.num_heads == 4

        cache = KVCache.create(cfg2, 1, ids.shape[1], jnp.float32)
        got, _ = forward(params, cfg2, jnp.asarray(ids, jnp.int32), cache)

        np.testing.assert_allclose(np.asarray(got)[0], want[0], atol=2e-3)

    def test_decode_matches_prefill_split(self, tmp_path):
        """Prefill(6) + two decode steps == one prefill(8): the cache path
        (norm offset + scaled embeddings under incremental lengths) agrees
        with the all-at-once forward."""
        model, _ = _tiny_hf_gemma(tmp_path)
        ids = np.array([[2, 11, 45, 102, 5, 252, 19, 7]], dtype=np.int64)
        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)

        cache = KVCache.create(cfg2, 1, 8, jnp.float32)
        want, _ = forward(params, cfg2, jnp.asarray(ids, jnp.int32), cache)

        cache = KVCache.create(cfg2, 1, 8, jnp.float32)
        _, cache = forward(params, cfg2, jnp.asarray(ids[:, :6], jnp.int32), cache)
        got6, cache = forward(params, cfg2, jnp.asarray(ids[:, 6:7], jnp.int32), cache)
        got7, _ = forward(params, cfg2, jnp.asarray(ids[:, 7:8], jnp.int32), cache)

        np.testing.assert_allclose(
            np.asarray(got6)[0, 0], np.asarray(want)[0, 6], atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(got7)[0, 0], np.asarray(want)[0, 7], atol=1e-4
        )

    def test_engine_serves_tiny_gemma(self):
        """The tiny-gemma preset decodes through the engine (random init:
        zero-centered norms, scaled embeddings, GeGLU) and the paged
        scheduler serves it identically to the dense path."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine

        gen = GenerationConfig(max_new_tokens=8, temperature=0.0, ignore_eos=True)
        eng = InferenceEngine.from_config(
            "tiny-gemma", tokenizer="byte", max_seq_len=64
        )
        # norm_offset random init stores zero-centered norm weights
        assert float(np.abs(np.asarray(eng.params["final_norm"])).max()) == 0
        want = eng.generate(eng.tokenizer.encode("gemma probe"), gen).token_ids
        assert len(want) == 8

        paged = InferenceEngine.from_config(
            "tiny-gemma", tokenizer="byte", max_seq_len=64, paged=True,
            batch_size=2, page_size=8,
        )
        try:
            got = list(
                paged.scheduler.stream(
                    paged.tokenizer.encode("gemma probe"), gen
                )
            )
            assert got == want
        finally:
            paged.close()
