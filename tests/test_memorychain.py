"""Memorychain tests: blocks/PoW, wallet, multi-node loopback consensus,
task lifecycle with rewards, chain sync, HTTP node federation — the
hermetic distributed tests the reference lacks (SURVEY.md §4)."""

import json
import urllib.request

import pytest

from fei_tpu.memory.memorychain.chain import (
    DIFFICULTY_REWARDS,
    INITIAL_GRANT,
    FeiCoinWallet,
    MemoryBlock,
    MemoryChain,
)
from fei_tpu.memory.memorychain.transport import LoopbackTransport


def make_cluster(tmp_path, n=3, difficulty=1):
    """n chains wired over a loopback transport, fully meshed."""
    transport = LoopbackTransport()
    chains = []
    for i in range(n):
        c = MemoryChain(f"node-{i}", str(tmp_path / f"n{i}"),
                        transport=transport, difficulty=difficulty)
        transport.register(f"node-{i}", c)
        chains.append(c)
    for c in chains:
        for other in chains:
            if other is not c:
                c.register_peer(other.node_id)
    return chains, transport


class TestBlock:
    def test_mine_meets_difficulty(self):
        b = MemoryBlock(1, 1.0, "m1", {"content": "x"}, "0" * 64)
        b.mine(2)
        assert b.hash.startswith("00") and b.hash == b.calculate_hash()

    def test_hash_covers_payload(self):
        b = MemoryBlock(1, 1.0, "m1", {"content": "x"}, "0" * 64)
        b.mine(1)
        h = b.hash
        b.memory_data = {"content": "tampered"}
        assert b.calculate_hash() != h

    def test_difficulty_plurality(self):
        b = MemoryBlock(1, 1.0, "t", {"content": "task"}, "0" * 64, is_task=True)
        b.vote_on_difficulty("a", 2)
        b.vote_on_difficulty("b", 3)
        assert b.vote_on_difficulty("c", 3) == 3


class TestWallet:
    def test_initial_grant_and_transfer(self, tmp_path):
        w = FeiCoinWallet(str(tmp_path / "w.json"))
        assert w.balance("a") == INITIAL_GRANT
        assert w.transfer("a", "b", 30.0)
        assert w.balance("a") == 70.0 and w.balance("b") == 130.0
        assert not w.transfer("a", "b", 1e9)

    def test_persistence(self, tmp_path):
        path = str(tmp_path / "w.json")
        FeiCoinWallet(path).add_funds("a", 5.0)
        w2 = FeiCoinWallet(path)
        assert w2.balance("a") == INITIAL_GRANT + 5.0
        assert any(t["kind"] == "reward" for t in w2.history("a"))


class TestChain:
    def test_genesis_and_persistence(self, tmp_path):
        c = MemoryChain("solo", str(tmp_path))
        c.add_block({"content": "first"})
        reloaded = MemoryChain("solo", str(tmp_path))
        assert len(reloaded.blocks) == 2
        assert reloaded.validate_chain()

    def test_validate_detects_tamper(self, tmp_path):
        c = MemoryChain("solo", str(tmp_path), difficulty=1)
        c.add_block({"content": "a"})
        c.blocks[1].memory_data = {"content": "evil"}
        assert not c.validate_chain()

    def test_solo_propose_commits(self, tmp_path):
        c = MemoryChain("solo", str(tmp_path), difficulty=1)
        block = c.propose_memory({"content": "alone"})
        assert block is not None and c.validate_chain()


class TestConsensus:
    def test_quorum_accepts_and_broadcasts(self, tmp_path):
        chains, _ = make_cluster(tmp_path, 3)
        block = chains[0].propose_memory({"content": "agreed", "tags": ["x"]})
        assert block is not None
        for c in chains:
            assert len(c.blocks) == 2
            assert c.blocks[1].memory_id == block.memory_id
            assert c.validate_chain()

    def test_responsible_node_deterministic(self, tmp_path):
        chains, _ = make_cluster(tmp_path, 3)
        block = chains[1].propose_memory({"content": "who owns this"})
        assert block.responsible_node in {c.node_id for c in chains}

    def test_invalid_proposal_rejected(self, tmp_path):
        chains, _ = make_cluster(tmp_path, 3)
        assert chains[0].vote_on_proposal({"memory_data": {}}) is False
        # peers reject schema-less proposals; 1/3 < quorum
        assert chains[0].propose_memory("not-a-dict") is None  # type: ignore[arg-type]

    def test_unreachable_peers_count_as_no(self, tmp_path):
        transport = LoopbackTransport()
        a = MemoryChain("a", str(tmp_path / "a"), transport=transport, difficulty=1)
        transport.register("a", a)
        a.register_peer("ghost-1")
        a.register_peer("ghost-2")
        assert a.propose_memory({"content": "lonely"}) is None  # 1/3

    def test_longest_chain_adoption(self, tmp_path):
        chains, _ = make_cluster(tmp_path, 2)
        a, b = chains
        a.propose_memory({"content": "one"})
        a.propose_memory({"content": "two"})
        assert len(b.blocks) == 3  # broadcast kept b in sync
        # b must refuse a shorter or diverged chain
        assert not b.receive_chain_update([blk.to_dict() for blk in b.blocks[:2]])
        forged = [blk.to_dict() for blk in b.blocks]
        forged[1]["memory_data"] = {"content": "forged"}
        assert not b.receive_chain_update(forged + [forged[-1]])


class TestTasks:
    def test_full_lifecycle_with_reward(self, tmp_path):
        chains, _ = make_cluster(tmp_path, 3)
        a, b, c = chains
        task = a.propose_task("port the kernel", difficulty=2)
        assert task is not None and task.task_state == "proposed"
        assert a.claim_task(task.memory_id, "node-1")
        assert a.validate_chain()  # suffix re-mined after mutation
        entry = a.submit_solution(task.memory_id, "done: see patch", "node-1")
        assert entry is not None
        before = a.wallet.balance("node-1")
        state = a.vote_on_solution(task.memory_id, entry["id"], True, "node-0")
        assert state == "solution_submitted"  # 1/3 approvals yet
        state = a.vote_on_solution(task.memory_id, entry["id"], True, "node-2")
        assert state == "completed"
        assert a.wallet.balance("node-1") == before + DIFFICULTY_REWARDS[2]

    def test_rejected_solution_dropped(self, tmp_path):
        chains, _ = make_cluster(tmp_path, 3)
        a = chains[0]
        task = a.propose_task("hard thing")
        a.claim_task(task.memory_id)
        entry = a.submit_solution(task.memory_id, "wrong answer")
        a.vote_on_solution(task.memory_id, entry["id"], False, "node-1")
        state = a.vote_on_solution(task.memory_id, entry["id"], False, "node-2")
        assert state == "claimed"
        assert a.get_block(task.memory_id).solutions == []

    def test_list_tasks_by_state(self, tmp_path):
        chains, _ = make_cluster(tmp_path, 3)
        a = chains[0]
        a.propose_task("t1")
        t2 = a.propose_task("t2")
        a.claim_task(t2.memory_id)
        assert len(a.list_tasks()) == 2
        assert len(a.list_tasks("claimed")) == 1


class TestHTTPNode:
    @pytest.fixture
    def nodes(self, tmp_path):
        from fei_tpu.memory.memorychain.node import MemorychainNode

        n1 = MemorychainNode("http-a", 0, str(tmp_path / "a"))
        n1.start_background()
        n2 = MemorychainNode("http-b", 0, str(tmp_path / "b"), seed=n1.address)
        n2.start_background()
        # n1 learns about n2 through the register call n2 made
        yield n1, n2
        n1.shutdown()
        n2.shutdown()

    def _post(self, addr, path, payload):
        req = urllib.request.Request(
            f"{addr}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    def _get(self, addr, path):
        with urllib.request.urlopen(f"{addr}{path}", timeout=10) as resp:
            return json.loads(resp.read())

    def test_register_and_health(self, nodes):
        n1, n2 = nodes
        assert self._get(n1.address, "/health")["status"] == "ok"
        assert n2.address in n1.chain.peers
        assert n1.address in n2.chain.peers

    def test_propose_replicates_over_http(self, nodes):
        n1, n2 = nodes
        out = self._post(n1.address, "/memorychain/propose",
                         {"memory_data": {"content": "over http"}})
        assert "block" in out
        chain2 = self._get(n2.address, "/memorychain/chain")
        assert chain2["length"] == 2 and chain2["valid"]

    def test_task_over_http_and_wallet(self, nodes):
        n1, n2 = nodes
        out = self._post(n1.address, "/memorychain/propose_task",
                         {"description": "http task", "difficulty": 1})
        tid = out["block"]["memory_id"]
        assert self._post(n1.address, "/memorychain/claim_task",
                          {"task_id": tid, "node_id": "worker"})["claimed"]
        sol = self._post(n1.address, "/memorychain/submit_solution",
                         {"task_id": tid, "solution": "ok", "node_id": "worker"})
        state = self._post(n1.address, "/memorychain/vote_solution",
                           {"task_id": tid, "solution_id": sol["solution"]["id"],
                            "approve": True, "voter": "http-b"})["task_state"]
        assert state == "solution_submitted"  # 1 of 2 voters < 51 %
        state = self._post(n1.address, "/memorychain/vote_solution",
                           {"task_id": tid, "solution_id": sol["solution"]["id"],
                            "approve": True, "voter": "http-a"})["task_state"]
        assert state == "completed"
        bal = self._get(n1.address, "/memorychain/wallet/worker")["balance"]
        assert bal == 100.0 + DIFFICULTY_REWARDS[1]

    def test_network_status(self, nodes):
        n1, n2 = nodes
        status = self._get(n1.address, "/memorychain/network_status")
        assert status["reachable"] == 2
