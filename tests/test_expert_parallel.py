"""Expert-parallel MoE vs the dense single-device formulation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.ops.moe import moe_mlp
from fei_tpu.parallel.expert import moe_mlp_ep
from fei_tpu.parallel.mesh import make_mesh


def _setup(key, B, T, H, I, E):
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (B, T, H)) * 0.3
    router = jax.random.normal(ks[1], (H, E)) * 0.3
    wg = jax.random.normal(ks[2], (E, H, I)) * (H ** -0.5)
    wu = jax.random.normal(ks[3], (E, H, I)) * (H ** -0.5)
    wd = jax.random.normal(ks[4], (E, I, H)) * (I ** -0.5)
    return x, router, wg, wu, wd


@pytest.fixture(scope="module")
def ep_mesh():
    n = 4 if len(jax.devices()) >= 4 else len(jax.devices())
    return make_mesh({"ep": n}, devices=jax.devices()[:n])


class TestExpertParallel:
    def test_matches_dense(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(0), 2, 8, 32, 64, 2 * n)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        got = moe_mlp_ep(x, router, wg, wu, wd, 2, ep_mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_top1_routing(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(1), 1, 4, 16, 32, n)
        want = moe_mlp(x, router, wg, wu, wd, 1)
        got = moe_mlp_ep(x, router, wg, wu, wd, 1, ep_mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_jit_compiles(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(2), 1, 4, 16, 32, n)

        @jax.jit
        def f(*args):
            return moe_mlp_ep(*args, 2, ep_mesh)

        got = f(x, router, wg, wu, wd)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_rejects_indivisible_experts(self, ep_mesh):
        if ep_mesh.shape["ep"] == 1:
            pytest.skip("needs ep > 1")
        x, router, wg, wu, wd = _setup(
            jax.random.PRNGKey(3), 1, 4, 16, 32, ep_mesh.shape["ep"] + 1
        )
        with pytest.raises(ValueError):
            moe_mlp_ep(x, router, wg, wu, wd, 2, ep_mesh)
