"""Expert-parallel MoE vs the dense single-device formulation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import requires_shard_map

from fei_tpu.ops.moe import moe_mlp, moe_mlp_routed
from fei_tpu.parallel.expert import (
    expert_flops_share,
    moe_mlp_ep,
    moe_mlp_ep_routed,
    routed_capacity,
)
from fei_tpu.parallel.mesh import make_mesh
from fei_tpu.utils.platform import shard_map


def _setup(key, B, T, H, I, E):
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (B, T, H)) * 0.3
    router = jax.random.normal(ks[1], (H, E)) * 0.3
    wg = jax.random.normal(ks[2], (E, H, I)) * (H ** -0.5)
    wu = jax.random.normal(ks[3], (E, H, I)) * (H ** -0.5)
    wd = jax.random.normal(ks[4], (E, I, H)) * (I ** -0.5)
    return x, router, wg, wu, wd


@pytest.fixture(scope="module")
def ep_mesh():
    n = 4 if len(jax.devices()) >= 4 else len(jax.devices())
    return make_mesh({"ep": n}, devices=jax.devices()[:n])


class TestExpertParallel:
    @requires_shard_map
    def test_matches_dense(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(0), 2, 8, 32, 64, 2 * n)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        got = moe_mlp_ep(x, router, wg, wu, wd, 2, ep_mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    @requires_shard_map
    def test_top1_routing(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(1), 1, 4, 16, 32, n)
        want = moe_mlp(x, router, wg, wu, wd, 1)
        got = moe_mlp_ep(x, router, wg, wu, wd, 1, ep_mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    @requires_shard_map
    def test_jit_compiles(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(2), 1, 4, 16, 32, n)

        @jax.jit
        def f(*args):
            return moe_mlp_ep(*args, 2, ep_mesh)

        got = f(x, router, wg, wu, wd)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_rejects_indivisible_experts(self, ep_mesh):
        if ep_mesh.shape["ep"] == 1:
            pytest.skip("needs ep > 1")
        x, router, wg, wu, wd = _setup(
            jax.random.PRNGKey(3), 1, 4, 16, 32, ep_mesh.shape["ep"] + 1
        )
        with pytest.raises(ValueError):
            moe_mlp_ep(x, router, wg, wu, wd, 2, ep_mesh)


class TestRoutedSingleDevice:
    """Token-routed MoE (sort + ragged_dot grouped GEMM) vs the dense
    all-experts oracle — identical math, k/E of the expert FLOPs."""

    @pytest.mark.parametrize("k", [1, 2])
    def test_matches_dense(self, k):
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(0), 2, 8, 32, 64, 8)
        want = moe_mlp(x, router, wg, wu, wd, k)
        got = moe_mlp_routed(x, router, wg, wu, wd, k)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_jit_and_single_token(self, ):
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(1), 1, 1, 16, 32, 4)
        got = jax.jit(lambda *a: moe_mlp_routed(*a, 2))(x, router, wg, wu, wd)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_model_forward_routed_matches_dense(self):
        """The engine's auto gate: a tiny-moe forward with routed_moe=True
        must emit the same logits as the dense path."""
        from fei_tpu.models.configs import get_model_config
        from fei_tpu.models.llama import KVCache, forward, init_params

        cfg = get_model_config("tiny-moe", num_layers=2)
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        cache = KVCache.create(cfg, 2, 64, dtype=jnp.float32)
        dense_logits, _ = forward(params, cfg, tokens, cache, routed_moe=False)
        cache = KVCache.create(cfg, 2, 64, dtype=jnp.float32)
        routed_logits, _ = forward(params, cfg, tokens, cache, routed_moe=True)
        np.testing.assert_allclose(
            np.asarray(routed_logits), np.asarray(dense_logits), atol=3e-4
        )


class TestRoutedExpertParallel:
    """GShard-style token-routed EP: dispatch/combine masks + two
    all_to_alls over the ep axis (SURVEY.md hard part #2)."""

    @requires_shard_map
    def test_dropless_matches_dense(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(0), 2, 8, 32, 64, 2 * n)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        got = moe_mlp_ep_routed(x, router, wg, wu, wd, 2, ep_mesh, dropless=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    @requires_shard_map
    def test_dropless_top1(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(1), 1, 8, 16, 32, n)
        want = moe_mlp(x, router, wg, wu, wd, 1)
        got = moe_mlp_ep_routed(x, router, wg, wu, wd, 1, ep_mesh, dropless=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    @requires_shard_map
    def test_uneven_tokens_padded(self, ep_mesh):
        """B*T not divisible by the ep axis: padding rows must route
        nowhere and consume no capacity."""
        n = ep_mesh.shape["ep"]
        if n < 2:
            pytest.skip("needs ep > 1")
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(2), 1, 7, 16, 32, n)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        got = moe_mlp_ep_routed(
            x, router, wg, wu, wd, 2, ep_mesh, dropless=True
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    @staticmethod
    def _numpy_drop_reference(x, router, wg, wu, wd, k, n, C):
        """Independent numpy model of the GShard drop rule: per ep-shard,
        choice-major order, each expert accepts the first C assignments from
        each source shard and drops the rest."""
        x, router = np.asarray(x, np.float64), np.asarray(router, np.float64)
        wg, wu, wd = (np.asarray(a, np.float64) for a in (wg, wu, wd))
        B, T, H = x.shape
        N = B * T
        xf = x.reshape(N, H)
        Nl = -(-N // n)
        out = np.zeros((N, H))
        for shard in range(n):
            rows = [r for r in range(shard * Nl, min((shard + 1) * Nl, N))]
            logits = xf[rows] @ router
            order = np.argsort(-logits, axis=-1, kind="stable")[:, :k]
            vals = np.take_along_axis(logits, order, axis=-1)
            w = np.exp(vals - vals.max(-1, keepdims=True))
            w = w / w.sum(-1, keepdims=True)
            fill = {}
            for choice in range(k):  # first choices claim slots first
                for i, r in enumerate(rows):
                    e = int(order[i, choice])
                    if fill.get(e, 0) >= C:
                        continue  # dropped
                    fill[e] = fill.get(e, 0) + 1
                    xr = xf[r]
                    act = (xr @ wg[e]) * (1 / (1 + np.exp(-(xr @ wg[e])))) * (
                        xr @ wu[e]
                    )
                    out[r] += w[i, choice] * (act @ wd[e])
        return out.reshape(B, T, H)

    @requires_shard_map
    def test_capacity_drops_match_reference(self, ep_mesh):
        """Tight capacity: kept/dropped assignments must match an
        independent numpy model of the drop rule, not just stay finite."""
        import functools

        from jax.sharding import PartitionSpec as P

        from fei_tpu.parallel.expert import _routed_shard

        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(3), 2, 8, 32, 64, 2 * n)
        C = 2  # well below the dropless worst case of B*T/n tokens
        fn = shard_map(
            functools.partial(_routed_shard, k=2, capacity=C, axis_name="ep"),
            mesh=ep_mesh,
            in_specs=(P(), P(), P("ep"), P("ep"), P("ep")),
            out_specs=P(),
            check_vma=False,
        )
        got = fn(x, router, wg, wu, wd)
        want = self._numpy_drop_reference(
            np.asarray(x), np.asarray(router), np.asarray(wg),
            np.asarray(wu), np.asarray(wd), 2, n, C,
        )
        np.testing.assert_allclose(np.asarray(got), want, atol=2e-4)

    @requires_shard_map
    def test_jit_compiles(self, ep_mesh):
        n = ep_mesh.shape["ep"]
        x, router, wg, wu, wd = _setup(jax.random.PRNGKey(4), 2, 8, 32, 64, 2 * n)

        @jax.jit
        def f(*args):
            return moe_mlp_ep_routed(*args, 2, ep_mesh, dropless=True)

        got = f(x, router, wg, wu, wd)
        want = moe_mlp(x, router, wg, wu, wd, 2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_flops_share_is_k_over_E(self):
        """The counter proving per-device expert FLOPs ≈ cf·k/E of the
        dense-local formulation (VERDICT round-1 item 4)."""
        N, E, k, ep = 4096, 8, 2, 4
        routed_rows, dense_rows = expert_flops_share(N, E, k, ep, capacity_factor=1.0)
        assert routed_rows / dense_rows == pytest.approx(k / E, rel=0.01)
        # capacity slack scales linearly
        r2, _ = expert_flops_share(N, E, k, ep, capacity_factor=2.0)
        assert r2 == 2 * routed_rows

    def test_routed_capacity_floor(self):
        assert routed_capacity(1, 64, 1, 1.0) == 1

    @requires_shard_map
    def test_meshed_moe_engine_end_to_end(self, ep_mesh, monkeypatch):
        """Mixtral-architecture engine on an ep mesh: prefill + decode run
        with token-routed EP inside the jitted programs and emit the same
        greedy tokens as the single-device dense engine (BASELINE #4).
        Dropless capacity gives exact parity; the default capacity factor
        (2.0) is the serving config and may drop skewed tokens."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine

        n = ep_mesh.shape["ep"]
        if 4 % n:
            pytest.skip("tiny-moe has 4 experts; need ep | 4")
        monkeypatch.setenv("FEI_TPU_EP_CAPACITY", "dropless")
        kw = dict(
            dtype=jnp.float32, seed=0, tokenizer="byte",
            max_seq_len=128, num_layers=2,
        )
        dense = InferenceEngine.from_config("tiny-moe", **kw)
        sharded = InferenceEngine.from_config("tiny-moe", mesh=ep_mesh, **kw)
        gen = GenerationConfig(max_new_tokens=12, temperature=0.0, ignore_eos=True)
        prompt = dense.tokenizer.encode("mixtral expert-parallel end to end")
        want = dense.generate(prompt, gen).token_ids
        got = sharded.generate(prompt, gen).token_ids
        assert got == want

    @requires_shard_map
    def test_meshed_moe_engine_default_capacity(self, ep_mesh):
        """Default serving capacity (factor 2.0): generation completes and
        per-device expert FLOPs are bounded by 2k/E of dense."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine

        n = ep_mesh.shape["ep"]
        if 4 % n:
            pytest.skip("tiny-moe has 4 experts; need ep | 4")
        eng = InferenceEngine.from_config(
            "tiny-moe", mesh=ep_mesh, dtype=jnp.float32, tokenizer="byte",
            max_seq_len=128, num_layers=2,
        )
        gen = GenerationConfig(max_new_tokens=8, temperature=0.0, ignore_eos=True)
        res = eng.generate(eng.tokenizer.encode("serving capacity"), gen)
        assert len(res.token_ids) == 8
