"""Sliding-window attention (Mistral-v0.1 style, cfg.sliding_window).

Guarantees, layered like the flash suite:
- the XLA oracle masks exactly positions <= p - window
- the flash kernel (interpret on CPU) matches the oracle, forward and
  backward (the window mask runs in the dq/dkv kernels too)
- an engine decode over a window-sized cache matches a from-scratch
  forward (the cache path honors the window across incremental lengths)
- HF golden parity vs transformers MistralForCausalLM with a window small
  enough to bite at test length
- paged serving: windowed decode kernel vs the gathered oracle, scheduler
  token parity vs dense under concurrency, and rolling-buffer page release
  (below-window pages return to the pool mid-stream)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fei_tpu.ops.attention import attention


def _rand_qkv(key, B, T, S, H, K, D):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, K, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, K, D), jnp.float32)
    return q, k, v


class TestOracleWindow:
    def test_window_masks_exactly(self):
        """Brute-force check: output at position p must equal attention
        computed over only keys (p-w, p]."""
        B, T, H, K, D, W = 1, 12, 2, 1, 8, 4
        q, k, v = _rand_qkv(jax.random.PRNGKey(0), B, T, T, H, K, D)
        positions = jnp.arange(T)[None, :]
        out = attention(q, k, v, positions, T, window=W)
        for p in range(T):
            lo = max(0, p - W + 1)
            ref = attention(
                q[:, p : p + 1],
                k[:, lo : p + 1],
                v[:, lo : p + 1],
                jnp.array([[p - lo]]),  # position within the slice
                p + 1 - lo,
            )
            np.testing.assert_allclose(
                np.asarray(out[:, p]), np.asarray(ref[:, 0]), atol=1e-5
            )

    def test_window_off_is_full_causal(self):
        B, T, H, K, D = 1, 8, 2, 2, 8
        q, k, v = _rand_qkv(jax.random.PRNGKey(1), B, T, T, H, K, D)
        positions = jnp.arange(T)[None, :]
        a = attention(q, k, v, positions, T, window=0)
        b = attention(q, k, v, positions, T)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestFlashWindow:
    @pytest.mark.parametrize("T,S,q_start,W", [
        (16, 64, 0, 8), (64, 64, 0, 16), (8, 128, 40, 24),
    ])
    def test_matches_oracle(self, T, S, q_start, W):
        from fei_tpu.ops.pallas import flash_attention

        B, H, K, D = 1, 4, 2, 64
        q, k, v = _rand_qkv(jax.random.PRNGKey(0), B, T, S, H, K, D)
        kv_len = jnp.array([q_start + T], jnp.int32)
        starts = jnp.array([q_start], jnp.int32)
        positions = q_start + jnp.arange(T)[None, :]
        got = flash_attention(
            q, k, v, starts, kv_len, block_q=16, block_k=16, window=W
        )
        want = attention(q, k, v, positions, kv_len, window=W)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-3
        )

    def test_backward_matches_oracle(self):
        """Window mask must run in the dq/dkv kernels too: grads of an
        arbitrary scalar loss agree with the oracle's autodiff."""
        from fei_tpu.ops.pallas import flash_attention

        B, T, H, K, D, W = 1, 32, 2, 1, 64, 8
        q, k, v = _rand_qkv(jax.random.PRNGKey(2), B, T, T, H, K, D)
        starts = jnp.zeros((B,), jnp.int32)
        kv_len = jnp.full((B,), T, jnp.int32)
        positions = jnp.arange(T)[None, :]
        probe = jax.random.normal(jax.random.PRNGKey(3), (B, T, H, D))

        def loss_flash(q, k, v):
            out = flash_attention(
                q, k, v, starts, kv_len, block_q=16, block_k=16, window=W
            )
            return jnp.sum(out * probe)

        def loss_oracle(q, k, v):
            return jnp.sum(attention(q, k, v, positions, T, window=W) * probe)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, go, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-3,
                err_msg=f"d{name} mismatch",
            )


@pytest.mark.slow  # fast lane: -m 'not slow'
class TestEngineSWA:
    def test_decode_honors_window_across_cache_growth(self):
        """Greedy decode with a window smaller than the context must match
        token-by-token recomputation from scratch (cache path == fresh
        forward at every length)."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine
        from fei_tpu.models.llama import KVCache, forward

        eng = InferenceEngine.from_config(
            "tiny-swa", tokenizer="byte", max_seq_len=48, dtype=jnp.float32
        )
        assert eng.cfg.sliding_window == 8
        ids = eng.tokenizer.encode("sliding window probe text")
        gen = GenerationConfig(max_new_tokens=10, temperature=0.0, ignore_eos=True)
        got = eng.generate(ids, gen).token_ids

        # from-scratch argmax chain (full forward each step, same window)
        cur = list(ids)
        want = []
        for _ in range(10):
            cache = KVCache.create(eng.cfg, 1, 48, jnp.float32)
            logits, _ = forward(
                eng.params, eng.cfg, jnp.asarray([cur], jnp.int32), cache
            )
            nxt = int(jnp.argmax(logits[0, len(cur) - 1]))
            want.append(nxt)
            cur.append(nxt)
        assert got == want

    def test_paged_serving_matches_dense(self):
        """The paged scheduler (windowed decode kernel + chunked admission)
        streams token-identically to the dense SWA engine, concurrently."""
        import concurrent.futures as cf

        from fei_tpu.engine import GenerationConfig, InferenceEngine

        gen = GenerationConfig(max_new_tokens=10, temperature=0.0, ignore_eos=True)
        dense = InferenceEngine.from_config(
            "tiny-swa", tokenizer="byte", max_seq_len=64
        )
        ids = dense.tokenizer.encode("sliding window paged probe")
        want = dense.generate(ids, gen).token_ids

        paged = InferenceEngine.from_config(
            "tiny-swa", tokenizer="byte", max_seq_len=64, paged=True,
            batch_size=2, page_size=8,
        )
        try:
            with cf.ThreadPoolExecutor(2) as ex:
                outs = list(ex.map(
                    lambda _: list(paged.scheduler.stream(ids, gen)), range(2)
                ))
            assert outs[0] == outs[1] == want
        finally:
            paged.close()

    def test_paged_kernel_matches_windowed_oracle(self):
        """Unit: the decode kernel's window mask equals the gathered-view
        oracle with the same window."""
        from fei_tpu.engine.paged_cache import (
            PagedKVCache,
            paged_attention_reference,
        )
        from fei_tpu.models.configs import get_model_config
        from fei_tpu.ops.pallas.paged_attention import paged_attention

        cfg = get_model_config("tiny")
        B, W = 2, 8
        pool = PagedKVCache.create(cfg, 16, B, 4, page_size=8, dtype=jnp.float32)
        # the kernel consumes ONE layer's [P, K, ps, D] slice of the pool
        k_pages = jax.random.normal(
            jax.random.PRNGKey(0), pool.k_pages.shape[1:], jnp.float32
        )
        v_pages = jax.random.normal(
            jax.random.PRNGKey(1), pool.v_pages.shape[1:], jnp.float32
        )
        table = jnp.array([[0, 1, 2, 3], [4, 5, 6, 7]], jnp.int32)
        lengths = jnp.array([27, 13], jnp.int32)
        q = jax.random.normal(
            jax.random.PRNGKey(2),
            (B, cfg.num_heads, cfg.head_dim_), jnp.float32,
        )
        got = paged_attention(q, k_pages, v_pages, table, lengths, window=W)
        want = paged_attention_reference(
            q, k_pages, v_pages, table, lengths, window=W
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-3
        )
        # and the windowed result differs from full attention (window bites)
        full = paged_attention(q, k_pages, v_pages, table, lengths)
        assert np.abs(np.asarray(got) - np.asarray(full)).max() > 1e-3


@pytest.mark.slow  # fast lane: -m 'not slow'
class TestRollingBuffer:
    def test_release_prefix_refcounts(self):
        from fei_tpu.engine.paged_cache import PageAllocator

        alloc = PageAllocator(num_pages=16, page_size=8)
        pages = alloc.alloc(0, 6)
        free0 = alloc.free_pages
        dropped = alloc.release_prefix(0, 2)
        assert dropped == pages[:2]
        assert alloc.free_pages == free0 + 2
        assert alloc.pages_for(0) == pages[2:]
        alloc.free(0)  # remaining pages only; no double-free
        assert alloc.free_pages == 15  # all but the null page

    def test_released_shared_page_survives_via_registry_ref(self):
        from fei_tpu.engine.paged_cache import PageAllocator

        alloc = PageAllocator(num_pages=16, page_size=8)
        pages = alloc.alloc(0, 3)
        alloc.take_ref(pages[:1])  # registry-style hold on the first page
        free0 = alloc.free_pages
        alloc.release_prefix(0, 2)
        # page[0] stays referenced (registry); page[1] actually freed
        assert alloc.refcount(pages[0]) == 1
        assert alloc.refcount(pages[1]) == 0
        assert alloc.free_pages == free0 + 1

    # Environment precondition: dense-vs-paged token identity over a 48-
    # token greedy stream relies on the paged SWA block kernel and the
    # dense reference rounding identically; on CPU XLA (interpret-mode
    # Pallas / the non-Mosaic fallback) the two paths diverge by ~1 bf16
    # ulp and the argmax flips around token 10 — reproducible at the
    # test's own introducing commit (eba3a0e), so this never held on CPU.
    # The onchip pipeline's kernels stage validates it under Mosaic.
    @pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="paged-vs-dense SWA numeric identity needs TPU Mosaic "
               "rounding; CPU XLA fallback kernels flip the greedy "
               "argmax mid-stream (fails at its introducing commit)",
    )
    def test_scheduler_releases_pages_midstream_and_stays_correct(self):
        """A long SWA generation returns below-window pages to the pool
        while decoding — and the stream stays token-identical to the dense
        engine (the released pages were never attendable again)."""
        from fei_tpu.engine import GenerationConfig, InferenceEngine
        from fei_tpu.utils.metrics import METRICS

        gen = GenerationConfig(max_new_tokens=48, temperature=0.0, ignore_eos=True)
        dense = InferenceEngine.from_config(
            "tiny-swa", tokenizer="byte", max_seq_len=96
        )
        ids = dense.tokenizer.encode("rolling buffer release probe")
        want = dense.generate(ids, gen).token_ids

        paged = InferenceEngine.from_config(
            "tiny-swa", tokenizer="byte", max_seq_len=96, paged=True,
            batch_size=1, page_size=8,
        )
        try:
            before = METRICS.snapshot()["counters"].get("scheduler.swa_pages_released", 0)
            got = list(paged.scheduler.stream(ids, gen))
            after = METRICS.snapshot()["counters"].get(
                "scheduler.swa_pages_released", 0
            )
            released = after - before
            assert got == want
            # window 8, page 8, margin = draft(8)+page(8): releases start
            # once cur > 32; at ~75 final tokens several pages must go back
            assert released >= 2, released
        finally:
            paged.close()


class TestHFWindowMerge:
    """Config-merge rules for sliding_window (engine/weights.py)."""

    def _merge(self, tmp_path, hf_cfg: dict):
        import json

        from fei_tpu.engine.weights import _merge_hf_config
        from fei_tpu.models.configs import get_model_config

        (tmp_path / "config.json").write_text(json.dumps(hf_cfg))
        return _merge_hf_config(str(tmp_path), get_model_config("mistral-7b"))

    def test_mistral_null_disables_preset_window(self, tmp_path):
        """Mistral v0.2+ sets sliding_window: null — it must OVERRIDE the
        preset's v0.1 default of 4096, not be dropped by the None-filter."""
        cfg = self._merge(
            tmp_path, {"model_type": "mistral", "sliding_window": None}
        )
        assert cfg.sliding_window is None

    def test_mistral_v01_window_adopted(self, tmp_path):
        cfg = self._merge(
            tmp_path, {"model_type": "mistral", "sliding_window": 4096}
        )
        assert cfg.sliding_window == 4096

    def test_qwen2_full_coverage_means_no_window(self, tmp_path):
        """HF Qwen2 defaults max_window_layers == num_layers: SWA applies
        to zero layers even with use_sliding_window=true."""
        cfg = self._merge(tmp_path, {
            "model_type": "qwen2", "use_sliding_window": True,
            "sliding_window": 128, "max_window_layers": 4,
            "num_hidden_layers": 4,
        })
        assert cfg.sliding_window is None

    def test_qwen2_absent_mwl_inherits_hf_default(self, tmp_path):
        """A config.json that relies on HF Qwen2Config's max_window_layers
        default (28) must get the SAME semantics as an explicit 28: with
        <= 28 layers, zero sliding layers — NOT all-layers windowing
        (ADVICE r3, corrected to the real HF default in r4)."""
        cfg = self._merge(tmp_path, {
            "model_type": "qwen2", "use_sliding_window": True,
            "sliding_window": 128, "num_hidden_layers": 4,
        })
        assert cfg.sliding_window is None

    def test_qwen2_absent_mwl_deep_config_rejected(self, tmp_path):
        """Deeper than 28 layers with the key absent = HF windows layers
        28..n-1 — partial windowing the uniform decoder cannot represent:
        must fail loudly, not silently load full-causal."""
        from fei_tpu.utils.errors import CheckpointError

        with pytest.raises(CheckpointError, match="max_window_layers"):
            self._merge(tmp_path, {
                "model_type": "qwen2", "use_sliding_window": True,
                "sliding_window": 128, "num_hidden_layers": 48,
            })

    def test_qwen2_explicit_zero_windows_all_layers(self, tmp_path):
        cfg = self._merge(tmp_path, {
            "model_type": "qwen2", "use_sliding_window": True,
            "sliding_window": 128, "max_window_layers": 0,
            "num_hidden_layers": 4,
        })
        assert cfg.sliding_window == 128

    def test_qwen2_partial_windowing_rejected(self, tmp_path):
        from fei_tpu.utils.errors import CheckpointError

        with pytest.raises(CheckpointError, match="max_window_layers"):
            self._merge(tmp_path, {
                "model_type": "qwen2", "use_sliding_window": True,
                "sliding_window": 128, "max_window_layers": 2,
                "num_hidden_layers": 4,
            })


transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.mark.slow  # fast lane: -m 'not slow'
class TestMistralParity:
    def test_logits_match_with_window_biting(self, tmp_path):
        """Golden parity vs HF MistralForCausalLM with sliding_window=4 at
        sequence length 10 — the window truncates most rows, so full-causal
        attention CANNOT pass this."""
        cfg_hf = transformers.MistralConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, rope_theta=10000.0,
            rms_norm_eps=1e-5, sliding_window=4,
        )
        torch.manual_seed(3)
        model = transformers.MistralForCausalLM(cfg_hf).eval()
        model.save_pretrained(str(tmp_path), safe_serialization=True)

        from fei_tpu.engine.weights import load_checkpoint
        from fei_tpu.models.configs import get_model_config
        from fei_tpu.models.llama import KVCache, forward

        ids = np.array([[3, 9, 44, 101, 7, 250, 16, 8, 77, 30]], np.int64)
        with torch.no_grad():
            want = model(torch.from_numpy(ids)).logits.float().numpy()

        cfg = get_model_config("tiny")
        cfg2, params = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
        assert cfg2.sliding_window == 4

        cache = KVCache.create(cfg2, 1, ids.shape[1], jnp.float32)
        got, _ = forward(params, cfg2, jnp.asarray(ids, jnp.int32), cache)
        np.testing.assert_allclose(
            np.asarray(got)[0], want[0], atol=2e-3
        )
