"""Multi-step scheduler decode: N batched steps per device dispatch.

The continuous-batching scheduler otherwise pays one host round-trip per
decode step, which bounds aggregate throughput when dispatch latency is
high (the tunneled TPU backend's round-trip IS the step time). When the
host has nothing to do between steps — no pending admission, no host
masks, no grammar trigger scanning — ``_try_multi_step`` scans up to
``FEI_TPU_SCHED_MULTISTEP`` steps inside one compiled program. Streams
must be token-identical with the feature on and off, including stops that
land mid-scan and device-grammar constrained requests.
"""

from __future__ import annotations

import json
import threading

import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.grammar import (
    JsonSchemaGrammar,
    TokenGrammar,
    char_walk,
)
from fei_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.slow  # fast lane: -m 'not slow' (docs/TESTING.md)

SCHEMA = {
    "type": "object",
    "properties": {"path": {"type": "string"}},
    "required": ["path"],
}


def _counter(name: str) -> float:
    return METRICS.snapshot()["counters"].get(name, 0)


def _make(multistep: int, monkeypatch, **kwargs) -> InferenceEngine:
    monkeypatch.setenv("FEI_TPU_SCHED_MULTISTEP", str(multistep))
    return InferenceEngine.from_config(
        "tiny", paged=True, batch_size=kwargs.pop("batch_size", 2), **kwargs
    )


PROMPT = list(range(11, 29))


class TestMultiStepParity:
    def test_greedy_stream_identical_and_engaged(self, monkeypatch):
        gen = GenerationConfig(max_new_tokens=40, temperature=0.0, ignore_eos=True)
        single = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        before = _counter("scheduler.multi_steps")
        multi = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert multi == single and len(multi) == 40
        assert _counter("scheduler.multi_steps") > before, "turbo never engaged"

    def test_sampled_stream_identical(self, monkeypatch):
        gen = GenerationConfig(
            max_new_tokens=32, temperature=0.9, top_k=20, seed=3, ignore_eos=True
        )
        a = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        b = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert a == b

    def test_stop_mid_scan_identical(self, monkeypatch):
        gen_free = GenerationConfig(
            max_new_tokens=40, temperature=0.0, ignore_eos=True
        )
        ref = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen_free))
        tok = ref[11]  # forces a stop that lands inside a turbo scan
        gen = GenerationConfig(max_new_tokens=40, temperature=0.0,
                               stop_token_ids=(tok,))
        single = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        multi = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert multi == single and len(multi) < 40

    def test_concurrent_streams_identical(self, monkeypatch):
        gen = GenerationConfig(max_new_tokens=24, temperature=0.0, ignore_eos=True)
        p2 = list(range(40, 55))

        def collect(eng):
            results: dict = {}

            def go(name, prompt):
                results[name] = list(eng.scheduler.stream(prompt, gen))

            ts = [
                threading.Thread(target=go, args=("a", PROMPT)),
                threading.Thread(target=go, args=("b", p2)),
            ]
            [t.start() for t in ts]
            [t.join() for t in ts]
            return results

        r1 = collect(_make(1, monkeypatch))
        r8 = collect(_make(8, monkeypatch))
        assert r1 == r8

    def test_constrained_multi_matches_single_no_host_masks(self, monkeypatch):
        gen = GenerationConfig(max_new_tokens=48)
        es = _make(1, monkeypatch)
        g1 = TokenGrammar(JsonSchemaGrammar(SCHEMA), es.tokenizer)
        ref = es.generate_constrained(PROMPT, g1, gen)
        em = _make(8, monkeypatch)
        g2 = TokenGrammar(JsonSchemaGrammar(SCHEMA), em.tokenizer)
        before_up = _counter("scheduler.host_mask_uploads")
        got = em.generate_constrained(PROMPT, g2, gen)
        assert _counter("scheduler.host_mask_uploads") == before_up
        assert got.token_ids == ref.token_ids
        assert char_walk(g2, got.text) == g2.accept
        json.loads(got.text)

    def test_budget_tail_smaller_than_cap(self, monkeypatch):
        # budget 5 < cap 8: turbo must downshift (4 then singles), not stall
        gen = GenerationConfig(max_new_tokens=5, temperature=0.0, ignore_eos=True)
        single = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        multi = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert multi == single and len(multi) == 5

    def test_mask_fn_requests_fall_back(self, monkeypatch):
        # host-masked requests must keep exact per-step host semantics
        import numpy as np

        eng = _make(8, monkeypatch)
        V = eng.cfg.vocab_size
        allowed = np.zeros((V,), dtype=bool)
        allowed[100:110] = True

        def mask_fn(generated):
            return allowed

        gen = GenerationConfig(max_new_tokens=12, temperature=0.0, ignore_eos=True)
        seq = eng.scheduler.submit(PROMPT, gen, logit_mask_fn=mask_fn)
        toks = list(eng.scheduler.drain(seq))
        assert toks and all(100 <= t < 110 for t in toks)
