"""Multi-step scheduler decode: N batched steps per device dispatch.

The continuous-batching scheduler otherwise pays one host round-trip per
decode step, which bounds aggregate throughput when dispatch latency is
high (the tunneled TPU backend's round-trip IS the step time). When the
host has nothing to do between steps — no pending admission, no host
masks, no grammar trigger scanning — ``_try_multi_step`` scans up to
``FEI_TPU_SCHED_MULTISTEP`` steps inside one compiled program. Streams
must be token-identical with the feature on and off, including stops that
land mid-scan and device-grammar constrained requests.
"""

from __future__ import annotations

import json
import threading

import pytest

from fei_tpu.engine.engine import GenerationConfig, InferenceEngine
from fei_tpu.engine.grammar import (
    JsonSchemaGrammar,
    TokenGrammar,
    char_walk,
)
from fei_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.slow  # fast lane: -m 'not slow' (docs/TESTING.md)

SCHEMA = {
    "type": "object",
    "properties": {"path": {"type": "string"}},
    "required": ["path"],
}


def _counter(name: str) -> float:
    return METRICS.snapshot()["counters"].get(name, 0)


def _make(multistep: int, monkeypatch, **kwargs) -> InferenceEngine:
    monkeypatch.setenv("FEI_TPU_SCHED_MULTISTEP", str(multistep))
    return InferenceEngine.from_config(
        "tiny", paged=True, batch_size=kwargs.pop("batch_size", 2), **kwargs
    )


PROMPT = list(range(11, 29))


class TestMultiStepParity:
    def test_greedy_stream_identical_and_engaged(self, monkeypatch):
        gen = GenerationConfig(max_new_tokens=40, temperature=0.0, ignore_eos=True)
        single = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        before = _counter("scheduler.multi_steps")
        multi = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert multi == single and len(multi) == 40
        assert _counter("scheduler.multi_steps") > before, "turbo never engaged"

    def test_sampled_stream_identical(self, monkeypatch):
        gen = GenerationConfig(
            max_new_tokens=32, temperature=0.9, top_k=20, seed=3, ignore_eos=True
        )
        a = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        b = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert a == b

    def test_stop_mid_scan_identical(self, monkeypatch):
        gen_free = GenerationConfig(
            max_new_tokens=40, temperature=0.0, ignore_eos=True
        )
        ref = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen_free))
        tok = ref[11]  # forces a stop that lands inside a turbo scan
        gen = GenerationConfig(max_new_tokens=40, temperature=0.0,
                               stop_token_ids=(tok,))
        single = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        multi = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert multi == single and len(multi) < 40

    def test_concurrent_streams_identical(self, monkeypatch):
        gen = GenerationConfig(max_new_tokens=24, temperature=0.0, ignore_eos=True)
        p2 = list(range(40, 55))

        def collect(eng):
            results: dict = {}

            def go(name, prompt):
                results[name] = list(eng.scheduler.stream(prompt, gen))

            ts = [
                threading.Thread(target=go, args=("a", PROMPT)),
                threading.Thread(target=go, args=("b", p2)),
            ]
            [t.start() for t in ts]
            [t.join() for t in ts]
            return results

        r1 = collect(_make(1, monkeypatch))
        r8 = collect(_make(8, monkeypatch))
        assert r1 == r8

    def test_constrained_multi_matches_single_no_host_masks(self, monkeypatch):
        gen = GenerationConfig(max_new_tokens=48)
        es = _make(1, monkeypatch)
        g1 = TokenGrammar(JsonSchemaGrammar(SCHEMA), es.tokenizer)
        ref = es.generate_constrained(PROMPT, g1, gen)
        em = _make(8, monkeypatch)
        g2 = TokenGrammar(JsonSchemaGrammar(SCHEMA), em.tokenizer)
        before_up = _counter("scheduler.host_mask_uploads")
        got = em.generate_constrained(PROMPT, g2, gen)
        assert _counter("scheduler.host_mask_uploads") == before_up
        assert got.token_ids == ref.token_ids
        assert char_walk(g2, got.text) == g2.accept
        json.loads(got.text)

    def test_budget_tail_smaller_than_cap(self, monkeypatch):
        # budget 5 < cap 8: one scan covers the whole budget; the tail
        # past token 5 is discarded at delivery, never a 4-2-1 ladder
        gen = GenerationConfig(max_new_tokens=5, temperature=0.0, ignore_eos=True)
        single = list(_make(1, monkeypatch).scheduler.stream(PROMPT, gen))
        multi = list(_make(8, monkeypatch).scheduler.stream(PROMPT, gen))
        assert multi == single and len(multi) == 5

    def test_mask_fn_requests_fall_back(self, monkeypatch):
        # host-masked requests must keep exact per-step host semantics
        import numpy as np

        eng = _make(8, monkeypatch)
        V = eng.cfg.vocab_size
        allowed = np.zeros((V,), dtype=bool)
        allowed[100:110] = True

        def mask_fn(generated):
            return allowed

        gen = GenerationConfig(max_new_tokens=12, temperature=0.0, ignore_eos=True)
        seq = eng.scheduler.submit(PROMPT, gen, logit_mask_fn=mask_fn)
        toks = list(eng.scheduler.drain(seq))
        assert toks and all(100 <= t < 110 for t in toks)


LONG_PROMPT = [(30 + j) % 200 + 2 for j in range(96)]  # 6 chunks at 16


class TestTurboUnderAdmission:
    """The turbo scan must stay armed while admissions are queued or
    prefilling in chunks (the old eligibility wall forced every live
    stream to per-token stepping for the whole admission), and streams
    must stay token-identical to the per-token path while it does."""

    def _run_with_mid_stream_admission(self, eng, gen_a, gen_b):
        """Stream A decodes; after its 4th token, B (long prompt ->
        chunked admission) submits. Returns (a_tokens, b_tokens)."""
        sched = eng.scheduler
        results: dict = {}
        a_started = threading.Event()

        def run_a():
            toks = []
            for t in sched.stream(PROMPT, gen_a):
                toks.append(t)
                if len(toks) == 4:
                    a_started.set()
            results["a"] = toks
            a_started.set()  # A shorter than 4 must not wedge B

        def run_b():
            assert a_started.wait(timeout=60)
            results["b"] = list(sched.stream(LONG_PROMPT, gen_b))

        ts = [threading.Thread(target=run_a), threading.Thread(target=run_b)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        return results["a"], results["b"]

    @pytest.mark.parametrize(
        "kw",
        [
            dict(temperature=0.0),
            dict(temperature=0.9, top_k=20, seed=11),
        ],
        ids=["greedy", "seeded"],
    )
    def test_admission_mid_stream_parity(self, monkeypatch, kw):
        monkeypatch.setenv("FEI_TPU_PREFILL_CHUNK", "16")
        gen_a = GenerationConfig(max_new_tokens=64, ignore_eos=True, **kw)
        gen_b = GenerationConfig(max_new_tokens=12, ignore_eos=True, **kw)
        a1, b1 = self._run_with_mid_stream_admission(
            _make(1, monkeypatch), gen_a, gen_b
        )
        before = _counter("scheduler.turbo_under_admission")
        a8, b8 = self._run_with_mid_stream_admission(
            _make(8, monkeypatch), gen_a, gen_b
        )
        # per-slot PRNG chains make concurrency output-invariant, so the
        # admission overlapping the scan must not perturb either stream
        assert a8 == a1 and len(a8) == 64
        assert b8 == b1 and len(b8) == 12
        assert _counter("scheduler.turbo_under_admission") > before, (
            "no turbo dispatch ran while the admission was in flight"
        )

    def test_dispatch_economics_under_load(self, monkeypatch):
        """Acceptance bound: K concurrent streams + continuous chunked
        admissions, device dispatches per delivered token at multistep=16
        <= 1/4 of the per-token path. decode_steps counts SCANNED steps
        (n per dispatch), so dispatches = (decode_steps - multi_tokens)
        + multi_steps."""
        monkeypatch.setenv("FEI_TPU_PREFILL_CHUNK", "16")
        names = (
            "scheduler.decode_steps", "scheduler.multi_steps",
            "scheduler.multi_tokens", "scheduler.turbo_under_admission",
        )

        def load(eng):
            sched = eng.scheduler
            gen_long = GenerationConfig(
                max_new_tokens=48, temperature=0.0, ignore_eos=True
            )
            gen_short = GenerationConfig(
                max_new_tokens=8, temperature=0.0, ignore_eos=True
            )
            delivered: list[int] = []
            lock = threading.Lock()

            def long_stream(p):
                toks = list(sched.stream(p, gen_long))
                with lock:
                    delivered.append(len(toks))

            def feeder():
                # back-to-back long-prompt requests: for most of the run
                # an admission is queued or prefilling in chunks
                for k in range(4):
                    p = [(57 + 13 * k + j) % 200 + 2 for j in range(48)]
                    toks = list(sched.stream(p, gen_short))
                    with lock:
                        delivered.append(len(toks))

            before = {m: _counter(m) for m in names}
            ts = [
                threading.Thread(
                    target=long_stream,
                    args=([(i * 31 + j) % 200 + 2 for j in range(12)],),
                )
                for i in range(3)
            ] + [threading.Thread(target=feeder)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            d = {m: _counter(m) - before[m] for m in names}
            dispatches = (
                d["scheduler.decode_steps"] - d["scheduler.multi_tokens"]
            ) + d["scheduler.multi_steps"]
            return sum(delivered), dispatches, d

        tok1, disp1, _ = load(_make(1, monkeypatch, batch_size=4))
        tok16, disp16, d16 = load(_make(16, monkeypatch, batch_size=4))
        # greedy + ignore_eos + fixed budgets: both runs deliver the same
        # token count regardless of scheduling interleave
        assert tok1 == tok16 == 3 * 48 + 4 * 8
        assert d16["scheduler.multi_steps"] > 0, "turbo never engaged"
        assert d16["scheduler.turbo_under_admission"] > 0, (
            "turbo disarmed while admissions were in flight"
        )
        assert disp16 / tok16 <= (disp1 / tok1) / 4, (
            f"dispatch economics regressed: {disp16}/{tok16} vs "
            f"{disp1}/{tok1} per-token"
        )
